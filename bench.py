"""Benchmark entry point: prints ONE JSON line for the driver — ALWAYS.

What it measures (reference: ``docs/benchmarks.rst`` +
``examples/pytorch/pytorch_synthetic_benchmark.py``; targets in BASELINE.md):

1. **Allreduce bus-bandwidth (GB/s)** — the north-star metric from
   BASELINE.json — swept over message sizes, through BOTH data planes:
   - the eager engine path (``hvd.allreduce`` → background coordinator →
     fused jitted XLA program), i.e. the framework's own hot path, and
   - the in-graph ``lax.psum`` path (what a jitted train step executes).
   bus-bw = 2*(n-1)/n * bytes / t (ring-allreduce wire traffic per rank).
2. **ResNet-50 synthetic training through the framework**: ``hvd.init()`` +
   ``hvd.DistributedOptimizer`` (gradient averaging over the ``hvd`` mesh
   axis) + cross-replica SyncBatchNorm, shard_map'ped over the world mesh —
   NOT a raw-XLA step.  Reports images/sec/chip and **MFU** (from XLA's own
   cost analysis and the chip's peak bf16 FLOPs).
3. **Framework overhead**: the same model/batch through a raw XLA step
   (no hvd anywhere) — overhead_pct shows what the framework costs.

``vs_baseline`` is framework-path throughput divided by the raw-XLA
throughput on the SAME chip (1.0 = the framework costs nothing); when the
raw section is unavailable it falls back to MFU/100.  The number that
matters either way is ``mfu_pct`` — the prior P100-img/s comparator is gone.

**Failure containment** (VERDICT r2 weak #1): every section runs inside
its own try/except — a failure records ``errors[<section>]`` but the JSON
line still prints with whatever succeeded, and the process exits 0 so the
driver records it.  The first device compile gets bounded retry with backoff
(transient remote-compile-service outages).  ``HVD_BENCH_MINIMAL=1``
measures only the eager-allreduce bus-bw (smallest compile surface).

**Device-claim probing** (VERDICT r3 weak #1): the PJRT device claim inside
this process's first ``import jax`` can wedge un-killably when the TPU
tunnel is sick — so before importing jax here, the claim is proven in
FRESH SUBPROCESSES with a short per-attempt timeout, retried across the
budget (outages are intermittent; a healthy window usually exists).  If no
probe ever succeeds the JSON says explicitly "chip never came up, N
attempts" — distinguishable from "bench slow" — within minutes per attempt,
never a silent 900s burn.

Env overrides: HVD_BENCH_BATCH, HVD_BENCH_STEPS, HVD_BENCH_IMAGE,
HVD_BENCH_SIZES_MB (comma list),
HVD_BENCH_MODEL=resnet50|llama|bert|vit|tf_step|decode, HVD_BENCH_SEQ
(llama/bert context length; defaults 512/256), HVD_BENCH_REMAT=1
(remat_layers on the llama step), HVD_BENCH_EXPERTS / HVD_BENCH_TOPK /
HVD_BENCH_WINDOW (MoE / sliding-window llama variants),
HVD_BENCH_DECODE_BATCH / HVD_BENCH_DECODE_PROMPT (decode mode),
HVD_BENCH_SKIP_RAW=1, HVD_BENCH_SKIP_BUSBW=1, HVD_BENCH_SKIP_AUTOTUNE=1,
HVD_BENCH_AUTOTUNE_STEPS, HVD_BENCH_BATCH_SWEEP (comma list of per-chip
batches, each recorded with img/s + HBM memory analysis), HVD_BENCH_MINIMAL=1,
HVD_BENCH_RETRIES, HVD_BENCH_RETRY_DELAY_S, HVD_BENCH_TIMEOUT_S (total
budget), HVD_BENCH_PROBE_TIMEOUT_S (per probe attempt, default 240),
HVD_BENCH_SKIP_PROBE=1.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# Raw evidence behind every derived number (VERDICT r3 weak #6): section →
# {warmup, timed iterations, wall seconds, clock}.  Attached to the output
# JSON as "timing_evidence" so img/s, MFU and GB/s can be re-derived by a
# skeptical reader instead of taken on faith.
_TIMING: dict = {}


def _record_timing(section, *, warmup, iters, wall_s, **extra):
    _TIMING[section] = {"warmup": warmup, "iters": iters,
                        "wall_s": round(wall_s, 4),
                        "clock": "time.perf_counter", **extra}

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
_PEAK_BF16 = [
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),   # v5e reports device_kind "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _on_tpu():
    import jax
    return any(d.platform != "cpu" for d in jax.devices())


def _peak_flops():
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _retry(fn, label: str):
    """Bounded retry with exponential backoff, for the first device compile
    (the remote-compile service has been observed down for whole rounds —
    a transient outage must not zero the entire bench)."""
    attempts = int(os.environ.get("HVD_BENCH_RETRIES", "4"))
    delay = float(os.environ.get("HVD_BENCH_RETRY_DELAY_S", "5"))
    last = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 - classified by caller
            last = exc
            if i < attempts - 1:
                sys.stderr.write(
                    f"bench: {label} attempt {i + 1}/{attempts} failed "
                    f"({exc}); retrying in {delay:.0f}s\n")
                time.sleep(delay)
                delay *= 2
    raise last


def _probe_device():
    """Smallest possible compile+execute; proves the device path works."""
    import jax
    import jax.numpy as jnp
    y = jax.jit(lambda v: (v * 2).sum())(jnp.ones((8,), jnp.float32))
    jax.block_until_ready(y)
    return float(y)


_PROBE_SRC = (
    "import jax, jax.numpy as jnp\n"
    "y = jax.jit(lambda v: (v * 2).sum())(jnp.ones((8,), jnp.float32))\n"
    "jax.block_until_ready(y)\n"
    "print('PROBE_OK', jax.devices()[0].platform, flush=True)\n"
)


def _probe_subprocess_loop(deadline, out):
    """Prove the device claim in fresh subprocesses BEFORE this process
    imports jax.  Each attempt is a new interpreter with a short timeout
    (a wedged claim is killed, not waited on); attempts repeat until one
    succeeds or the budget runs out.  Returns True on success; on False
    the caller must not import jax (it would wedge the same way)."""
    import subprocess
    probe_timeout = float(os.environ.get("HVD_BENCH_PROBE_TIMEOUT_S", "240"))
    retry_delay = float(os.environ.get("HVD_BENCH_PROBE_RETRY_DELAY_S", "10"))
    info = out["probe"] = {"ok": False, "attempts": 0, "attempt_s": [],
                           "per_attempt_timeout_s": probe_timeout}
    while True:
        left = deadline - time.monotonic()
        if left <= 5:
            return False
        info["attempts"] += 1
        t0 = time.monotonic()
        ok = False
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               timeout=min(probe_timeout, left),
                               capture_output=True, text=True)
            ok = r.returncode == 0 and "PROBE_OK" in r.stdout
            if not ok:
                info["last_error"] = (r.stderr or r.stdout)[-500:]
        except subprocess.TimeoutExpired:
            info["last_error"] = (
                f"probe subprocess killed after "
                f"{min(probe_timeout, left):.0f}s (device claim wedged)")
        info["attempt_s"].append(round(time.monotonic() - t0, 1))
        if ok:
            info["ok"] = True
            return True
        if deadline - time.monotonic() > retry_delay + 5:
            time.sleep(retry_delay)


def _control_plane_stats():
    """Steady-state control-plane overhead for the JSON line: per-cycle
    negotiation microseconds and the response-cache hit rate.  Nulls in
    single-controller mode (no negotiation round exists there) — the point
    is that the perf trajectory captures host-side coordinator overhead,
    not just bus bandwidth."""
    from horovod_tpu.common import basics as _basics
    eng = _basics._get_state().engine
    cycles = getattr(eng, "negotiation_cycles", 0)
    per_cycle = (round(eng.negotiation_us_total / cycles, 2)
                 if cycles else None)
    ctl = getattr(eng, "controller", None)
    rate = ctl.cache_stats.hit_rate() if ctl is not None else None
    # Pipelined data plane telemetry: average chunk count per fused
    # dispatch and the in-flight window high-water mark (0 = inline
    # settling — single-controller mode or MAX_INFLIGHT=1).
    dispatches = getattr(eng, "pipeline_dispatches", 0)
    chunks = (round(getattr(eng, "pipeline_chunks_total", 0) / dispatches, 3)
              if dispatches else None)
    ring = getattr(eng, "_inflight", None)
    # Monitor-plane telemetry (HOROVOD_MONITOR=1): aggregated cycle-time
    # spread / slowest rank from the cross-rank side-channel, plus the
    # frame bytes the new plane itself cost — so BENCH_*.json tracks the
    # monitoring plane's overhead on every line.  Nulls when the monitor
    # (or the multi-rank table) is off — absence of data, not zero cost.
    mon = getattr(_basics._get_state(), "monitor", None)
    if mon is not None:
        skew = mon.aggregator.skew()
        monitor = {
            "enabled": True,
            "ranks_reporting": len(mon.aggregator.ranks()),
            "cycle_us_spread": skew.get("cycle_us_spread"),
            "slowest_rank": skew.get("slowest_rank"),
            "frames_sent": mon.frames_sent,
            "metrics_frame_bytes":
                getattr(ctl, "monitor_bytes_sent", 0) if ctl else 0,
        }
    else:
        monitor = {"enabled": False}
    # Lifecycle-phase breakdown (horovod_tpu.trace): which host-side phase
    # (queue/negotiation/copy_in/reduce/drain) a gradient's latency sits in,
    # when tracing is armed (HOROVOD_TRACE, or the bench_trace A/B below —
    # which also writes this section).  Null when disarmed: absence of
    # data, not zero latency.
    tracer = getattr(eng, "tracer", None)
    trace = tracer.phase_summary() if tracer is not None else None
    # Zero-RTT warm path (protocol v7): speculation outcomes + the
    # in-flight round window, so the trajectory shows whether the warm
    # cycle actually dropped its round trip this run.  Nulls without a
    # controller (single-controller mode has no negotiation round).
    spec_hits = getattr(ctl, "spec_hits", 0) if ctl is not None else 0
    spec_miss = getattr(ctl, "spec_mispredicts", 0) if ctl is not None else 0
    zero_rtt = {
        "spec_hits": spec_hits if ctl is not None else None,
        "spec_mispredicts": spec_miss if ctl is not None else None,
        "spec_rounds": getattr(ctl, "spec_rounds", None)
            if ctl is not None else None,
        "spec_hit_rate": (round(spec_hits / (spec_hits + spec_miss), 4)
                          if spec_hits + spec_miss else None),
        "spec_cycles": getattr(eng, "spec_cycles", 0) or None,
        "inflight_rounds": getattr(ctl, "inflight_high_water", None)
            if ctl is not None else None,
    }
    return {"negotiation_us_per_cycle": per_cycle,
            "zero_rtt": zero_rtt,
            "response_cache_hit_rate":
                round(rate, 4) if rate is not None else None,
            "chunks_per_cycle": chunks,
            "inflight_depth": ring.high_water if ring is not None else 0,
            # Small-message latency war (ISSUE 8): live lane/partition
            # counters, so the trajectory shows whether the fast lane and
            # ByteScheduler partitioning actually engaged this run.
            "fast_lane": {
                "threshold_bytes": getattr(eng, "fast_lane_threshold", 0),
                "dispatches": getattr(eng, "fast_lane_dispatches", 0),
                "pin_hits": getattr(eng, "fast_lane_hits", 0)},
            "partition_splits": getattr(eng, "partition_splits", 0),
            "monitor": monitor,
            "trace": trace}


def _raise_nofile_limit():
    """Best-effort RLIMIT_NOFILE bump toward the hard limit (a 2048-rank
    simulated world needs thousands of in-process sockets); returns the
    resulting soft limit."""
    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    return soft


def _negotiation_world(world, ranks_per_host, rounds, warm=5, hier=None,
                       script=()):
    """One simulated negotiation world against a REAL native root server,
    now driven by the churn-scenario runner
    (``horovod_tpu.testing.churn.ChurnRunner``): ``world`` lightweight
    rank threads speaking raw warm-path frames (the steady-state floor),
    flat (every rank a direct connection) or behind per-host ``HostAgent``
    aggregators, with an optional CHURN SCRIPT (clean LEAVEs, join
    epochs, agent death, preemption drains) replayed mid-run.  Returns
    the runner's report: ``wall_us_per_round`` (box-bound on a shared CPU
    host), ``root_us`` (the root's OWN gather-complete -> responses-
    written service time), per-phase breakdowns across the churn, and
    ``survived``.  Self-contained: own server, own ports, no jax, no
    live engine."""
    from horovod_tpu.testing.churn import ChurnRunner
    if hier is None:
        hier = ranks_per_host > 0
    rep = ChurnRunner(world, ranks_per_host=ranks_per_host,
                      hier=hier, rounds=rounds, warm=warm,
                      script=script).run()
    if not rep["survived"]:
        raise RuntimeError(
            f"negotiation bench world failed: {rep['abort_reason']} "
            f"(failures: {rep['failures'][:4]})")
    return rep


def _default_churn_script(world, ranks_per_host, rounds, hier):
    """The standard mid-run churn for the scaling sweep: a preemption
    notice drains the LAST host (its ranks depart via clean LEAVEs), the
    drained host's agent then dies (survivable — its ranks already left),
    and a fleet-wide join epoch flushes the slot table.  All scheduled
    inside the measured window so the post-churn phases measure the
    SURVIVORS' root service.  Host indices follow ChurnRunner's grouping
    (ceil(world / ranks_per_host) groups — NOT the nominal host-count
    knob, which can exceed it for non-divisible worlds)."""
    from horovod_tpu.testing.faults import parse_churn
    n_groups = (world + ranks_per_host - 1) // ranks_per_host
    if rounds < 9 or n_groups < 2:
        return []
    last = n_groups - 1
    r1 = max(2, rounds // 3)
    parts = [f"preempt_notice:{last}@{r1}"]
    if hier:
        parts.append(f"agent_crash:{last}@{min(rounds, r1 + 2)}")
    parts.append(f"join:*@{max(r1 + 3, (2 * rounds) // 3)}")
    return parse_churn(",".join(parts))


def bench_negotiation_scaling(errors=None):
    """Scale-out control plane A/B under churn (ISSUE 9 + ISSUE 12):
    drive simulated world sizes — now up to 2048 ranks — through the REAL
    native root server, flat single-server vs the hierarchical
    per-host-agent plane with a FIXED host count, with scripted churn
    (preemption-notice drain → clean LEAVEs, agent death, a join epoch)
    injected MID-RUN in both planes.  Two metrics per size: ``round_us``
    (wall per lock-step round — box-bound here) and ``root_us`` (the
    root's own gather-complete -> responses-written service time).  The
    claims under test: root work scales with CONNECTIONS (hier ``root_us``
    stays ~flat as ranks grow), and it KEEPS that shape through churn —
    ``hier_slope_post`` reads the slope on the post-churn phases, and
    ``churn_survived`` certifies no run took an abort.  Self-contained
    (own servers on free ports): runs only in the rank-0 process and
    touches nothing of the live engine."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    sizes = [int(s) for s in os.environ.get(
        "HVD_BENCH_NEGOTIATION_SIZES", "8,32,128").split(",") if s]
    sizes = sorted({max(2, min(s, 2048)) for s in sizes})
    # A 2048-rank flat world needs ~2x2048 fds in this one process (and
    # hierarchical ~4x): raise the soft limit, then clamp the sweep to
    # what the box actually allows rather than dying with EMFILE.
    soft = _raise_nofile_limit()
    fd_cap = max(2, (soft - 256) // 4)
    dropped = [s for s in sizes if s > fd_cap]
    if dropped:
        sizes = [s for s in sizes if s <= fd_cap] or [min(fd_cap, 128)]
        if errors is not None:
            errors["negotiation_scaling_fd_clamp"] = (
                f"sizes {dropped} exceed the fd budget (soft limit {soft})"
                f"; clamped to <= {fd_cap}")
    rounds = int(os.environ.get("HVD_BENCH_NEGOTIATION_ROUNDS", "30"))
    n_hosts = max(1, int(os.environ.get("HVD_BENCH_NEGOTIATION_HOSTS", "8")))
    churn_on = os.environ.get("HVD_BENCH_NEGOTIATION_CHURN", "1") != "0"
    out = {"rounds": rounds, "hosts": n_hosts, "churn": churn_on,
           "sizes": {}}
    t_section = time.perf_counter()
    survived_all = True
    for world in sizes:
        hosts = min(world, n_hosts)
        rph = (world + hosts - 1) // hosts
        # Big worlds amortize: every simulated rank burns this same box's
        # CPU, so scale the round count down as the world grows.
        w_rounds = rounds if world <= 512 else max(12, rounds // 3)
        rec = {"hosts": hosts, "ranks_per_host": rph, "rounds": w_rounds}
        script = (_default_churn_script(world, rph, w_rounds, False)
                  if churn_on else [])
        flat_rep = _negotiation_world(world, rph, w_rounds, hier=False,
                                      script=script)
        script = (_default_churn_script(world, rph, w_rounds, True)
                  if churn_on else [])
        hier_rep = _negotiation_world(world, rph, w_rounds, hier=True,
                                      script=script)
        rec["flat_round_us"] = flat_rep["wall_us_per_round"]
        rec["flat_root_us"] = flat_rep["root_us"]
        rec["hier_round_us"] = hier_rep["wall_us_per_round"]
        rec["hier_root_us"] = hier_rep["root_us"]
        rec["flat_vs_hier"] = (round(rec["flat_root_us"]
                                     / rec["hier_root_us"], 3)
                               if rec["hier_root_us"] else None)
        if churn_on:
            rec["churn_survived"] = (flat_rep["survived"]
                                     and hier_rep["survived"])
            survived_all = survived_all and rec["churn_survived"]
            rec["left_ranks"] = hier_rep["left_ranks"]
            rec["flat_root_us_post_churn"] = flat_rep["root_us_post"]
            rec["hier_root_us_post_churn"] = hier_rep["root_us_post"]
        out["sizes"][str(world)] = rec
    big, small = out["sizes"][str(sizes[-1])], out["sizes"][str(sizes[0])]
    # Scoreboard: how much each plane's ROOT service degraded across the
    # sweep (1.0 = perfectly flat) and the headline flat/hier ratio at the
    # largest world.  The acceptance shape: flat_slope tracks the world
    # growth while hier_slope stays near 1 (root sees a fixed host count)
    # — and hier_slope_post pins the SAME claim on the post-churn phases,
    # i.e. the hierarchy's win does not evaporate where fleets churn.
    out["flat_slope"] = (round(big["flat_root_us"] / small["flat_root_us"],
                               3) if small["flat_root_us"] else None)
    out["hier_slope"] = (round(big["hier_root_us"] / small["hier_root_us"],
                               3) if small["hier_root_us"] else None)
    out["flat_vs_hier"] = big["flat_vs_hier"]
    if churn_on:
        out["churn_survived"] = survived_all
        post_small = small.get("hier_root_us_post_churn")
        post_big = big.get("hier_root_us_post_churn")
        out["hier_slope_post"] = (round(post_big / post_small, 3)
                                  if post_small and post_big else None)
    _record_timing("negotiation_scaling", warmup=5,
                   iters=rounds * len(sizes) * 2,
                   wall_s=time.perf_counter() - t_section,
                   sizes=sizes)
    return out


def bench_autoscale(errors=None):
    """Closed-loop autoscaling micro-costs (ISSUE 10): (1) policy decision
    latency — ``ScalePolicy.observe`` over scripted summaries, the
    per-poll cost the elastic driver pays every autoscale interval; (2)
    the clean-LEAVE drain round-trip — a REAL native server + two
    controller clients, wall time from ``leave()`` on one rank to the
    survivor OBSERVING the leave notice (the control-plane half of the
    drain pipeline; the worker's batch-boundary drain dominates in
    production).  Rank-0 only, self-contained (own server on a free
    port), jax-free."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    import socket as _socket
    import threading as _threading

    import numpy as np

    from horovod_tpu.common.controller import TCPController
    from horovod_tpu.elastic.autoscale import ScalePolicy

    t_section = time.perf_counter()
    out = {}
    # (1) decision latency: a mixed diet of hold/scale/evict-shaped
    # summaries through one policy instance.
    pol = ScalePolicy(min_np=1, max_np=64, persistence=2, cooldown_s=0.0,
                      idle_s=1e9)
    n_obs = 300
    t0 = time.perf_counter()
    for i in range(n_obs):
        pol.observe({
            "slowest_rank": i % 8,
            "per_rank_cycle_us": {r: 100.0 + 40.0 * ((i + r) % 5)
                                  for r in range(8)},
            "cycle_us_spread": float(i % 13),
            "queue_depth": i % 32,
            "queue_depth_trend": (i % 9) - 4.0,
            "progress_total": i,
        }, size=8, now=float(i))
    out["decision_us"] = round(
        (time.perf_counter() - t0) / n_obs * 1e6, 2)
    out["decisions"] = pol.decisions

    # (2) drain round-trip over the real wire.
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    result = {}
    bar = _threading.Barrier(2)
    leave_evt = _threading.Event()

    class _E:
        def __init__(self, name):
            self.name = name
            self.tensor = np.zeros((2, 4), np.float32)
            self.group_id = -1

    def run(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0, cache_capacity=64)
        try:
            for step in (0, 1):            # warm: settle all work
                pending = [_E(f"warm{step}")]
                for _ in range(30):
                    ready, _errs = ctl.negotiate(pending)
                    got = {e.name for e in ready}
                    pending = [e for e in pending if e.name not in got]
                    if not pending:
                        break
            bar.wait(timeout=30)
            if rank == 1:
                result["t_leave"] = time.perf_counter()
                result["leave_sent"] = ctl.leave()
                leave_evt.set()
            else:
                leave_evt.wait(30)
                for _ in range(5000):
                    ctl.negotiate([])
                    if ctl.left_ranks:
                        break
                result["t_seen"] = time.perf_counter()
                result["left_observed"] = ctl.left_ranks == [1]
        except Exception as exc:  # noqa: BLE001 - recorded, never hangs
            result.setdefault("error", repr(exc))
            try:
                bar.abort()
            except Exception:  # noqa: BLE001
                pass
            leave_evt.set()
        finally:
            ctl.shutdown()

    t = _threading.Thread(target=run, args=(1,), daemon=True)
    t.start()
    run(0)
    t.join(timeout=30)
    if "error" in result:
        if errors is not None:
            errors["autoscale_drain"] = result["error"]
    else:
        out["leave_sent"] = bool(result.get("leave_sent"))
        out["left_observed"] = bool(result.get("left_observed"))
        out["drain_roundtrip_us"] = round(
            (result["t_seen"] - result["t_leave"]) * 1e6, 1)
    _record_timing("autoscale", warmup=2, iters=n_obs,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_serving(errors=None):
    """Closed-loop serving-plane bench (ISSUE 19, docs/serving.md), four
    claims on every JSON line:

    - **p50/p99 vs offered load** — a paced client drives the REAL
      continuous batcher + jitted replica forward at a sweep of offered
      rates; each point records achieved qps, tail latency percentiles,
      batches formed and 429 rejections (the backpressure knee).
    - **batched-vs-sequential bitwise parity** — the padded-bucket
      batched forward must produce bit-identical rows to one-at-a-time
      forwards, and batch-size churn inside the bucket menu must not
      recompile (FusedProgramCache miss count pinned).
    - **scripted ramp → scale_out → drain** — the ScalePolicy serving
      mode under an injected clock: rising request rate fires scale_out
      after the persistence window, a rate collapse below ``idle_qps``
      fires the idle scale_in; plus the LIVE drain contract on the
      batcher (in-flight requests complete, new admissions refused).
    - **13 B warm-frame guard with serving active** — a real two-rank
      controller negotiates steady-state cycles while serve traffic
      hammers the batcher and its metrics ride the monitor side-channel;
      the negotiation-critical bytes per cycle and the zero-full-announce
      invariant must hold exactly as with serving off.

    Rank-0 only, self-contained (own controller pair on a free port)."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    import socket as _socket
    import threading as _threading

    import numpy as np

    from horovod_tpu.common.controller import TCPController
    from horovod_tpu.elastic.autoscale import ScalePolicy
    from horovod_tpu.monitor.agent import MonitorAgent
    from horovod_tpu.serve.batcher import ContinuousBatcher, Draining
    from horovod_tpu.serve.replica import Replica

    t_section = time.perf_counter()
    out = {}

    def apply_fn(params, x):
        return x @ params["w"]

    rng = np.random.RandomState(7)
    rep = Replica(apply_fn)
    rep.load({"w": rng.randn(16, 8).astype(np.float32)}, version=1)
    x = rng.randn(8, 16).astype(np.float32)

    # ---- parity + recompile pin -------------------------------------
    # Row i alone (zero co-rows, same bucket-8 program) must be bitwise
    # identical to row i of the full batch: results depend only on the
    # request's own row, never its position or co-batched neighbours.
    # Cross-bucket programs are different XLA reductions and cannot be
    # pinned bitwise.
    batched = rep.forward(x)
    blank = np.zeros_like(x)
    seq = []
    for i in range(8):
        alone = blank.copy()
        alone[0] = x[i]
        seq.append(rep.forward(alone)[0])
    out["parity_bitwise"] = bool(np.array_equal(batched, np.stack(seq)))
    misses0 = rep.cache.misses
    for n in (3, 5, 7, 8, 2, 6):          # churn across the bucket menu
        rep.forward(x[:n])
    # bucket 8 compiled above; churn may add 2 and 4 — nothing else.
    out["churn_recompiles"] = rep.cache.misses - misses0
    out["churn_cache_hits"] = rep.cache.hits
    out["batch_churn_bounded"] = bool(out["churn_recompiles"] <= 2)

    # ---- p50/p99 vs offered load ------------------------------------
    n_req = int(os.environ.get("HVD_BENCH_SERVE_REQS", "120"))
    sweep = []
    for offered in (100.0, 400.0, 1600.0):
        b = ContinuousBatcher(max_batch=8, deadline_ms=2000.0,
                              max_inflight=2, queue_depth=64)
        stop = _threading.Event()
        t = _threading.Thread(target=rep.serve_loop, args=(b, stop),
                              kwargs={"poll_s": 0.005}, daemon=True)
        t.start()
        period = 1.0 / offered
        reqs, rejected = [], 0
        t0 = time.perf_counter()
        for i in range(n_req):
            lag = t0 + i * period - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            try:
                reqs.append(b.submit(x[i % 8]))
            except Exception:  # noqa: BLE001 - QueueFull = the knee
                rejected += 1
        for r in reqs:
            try:
                r.wait(10.0)
            except Exception:  # noqa: BLE001 - expiry counted below
                pass
        elapsed = time.perf_counter() - t0
        stop.set()
        t.join(5)
        st = b.stats()
        sweep.append({
            "offered_qps": offered,
            "achieved_qps": round(len(reqs) / elapsed, 1),
            "p50_ms": st["latency_p50_ms"], "p99_ms": st["latency_p99_ms"],
            "batches": st["batches_total"], "rejected_429": rejected,
            "expired": st["expired_total"],
            "padding_rows": st["padding_rows_total"],
        })
    out["load_sweep"] = sweep

    # ---- scripted ramp -> scale_out -> drain ------------------------
    pol = ScalePolicy(min_np=1, max_np=4, persistence=2, cooldown_s=5.0,
                      idle_s=10.0, rate_high=100.0,
                      latency_target_ms=50.0, idle_qps=5.0)
    size, clock, actions = 2, 0.0, []
    script = ([80.0] * 2 + [350.0] * 3       # ramp past 100/replica
              + [1.0] * 8)                   # collapse below idle_qps
    for rate in script:
        clock += 6.0                         # outpace the 5s cooldown
        d = pol.observe({"request_rate": rate, "latency_p99_ms": 12.0,
                         "queue_depth": 0}, size=size, now=clock)
        actions.append(d.action)
        if d.action == "scale_out":
            size = d.target_size
        elif d.action == "scale_in":
            size = d.target_size
            break
    out["scenario"] = {
        "actions": actions,
        "scale_out_fired": "scale_out" in actions,
        "drain_fired": "scale_in" in actions,
        "final_size": size,
    }

    # Live drain contract: queued work completes, new work is refused.
    b = ContinuousBatcher(max_batch=4, deadline_ms=5000.0, max_inflight=2)
    inflight = [b.submit(x[i % 8]) for i in range(6)]
    b.drain()
    refused = False
    try:
        b.submit(x[0])
    except Draining:
        refused = True
    served = rep.serve_loop(b)               # returns when drained + empty
    out["scenario"]["drain_completed_inflight"] = bool(
        all(r.done() and r.error is None for r in inflight))
    out["scenario"]["drain_refused_new"] = refused
    out["scenario"]["drain_batches"] = served

    # ---- 13 B warm-frame guard with serving active ------------------
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    result = {}

    class _E:
        def __init__(self, name):
            self.name = name
            self.tensor = np.zeros((2, 4), np.float32)
            self.group_id = -1

    def _steps(ctl, names, n_steps):
        for _ in range(n_steps):
            pending = [_E(n) for n in names]
            for _round in range(40):
                ready, _errs = ctl.negotiate(pending)
                got = {e.name for e in ready}
                pending = [e for e in pending if e.name not in got]
                if not pending:
                    break

    def run(rank):
        names = [f"serve_bench.grad.{i}" for i in range(8)]
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0, cache_capacity=64)
        sb = ContinuousBatcher(max_batch=4, deadline_ms=1000.0,
                               max_inflight=2)
        agent = MonitorAgent(engine=None, controller=ctl, rank=rank,
                             world=2, interval_s=0.05,
                             registry=sb.registry)
        stop = _threading.Event()

        def fake_worker():                   # jax-free: route 2x back
            while not stop.is_set():
                batch = sb.next_batch(timeout=0.01)
                if batch is not None:
                    sb.complete(batch, [np.asarray(r.inputs) * 2
                                        for r in batch.requests])

        def client():
            while not stop.is_set():
                try:
                    sb.submit(np.ones(4, np.float32)).wait(1.0)
                except Exception:  # noqa: BLE001 - load gen best effort
                    pass

        threads = [_threading.Thread(target=fake_worker, daemon=True),
                   _threading.Thread(target=client, daemon=True)]
        for th in threads:
            th.start()
        try:
            _steps(ctl, names, 3)            # warm: learn cache slots
            time.sleep(0.06)                 # arm the monitor interval
            st = ctl.cache_stats
            full_before = st.full_announces
            bytes_before = ctl.bytes_sent
            mon_before = ctl.monitor_bytes_sent
            _steps(ctl, names, 5)
            if rank == 0:
                mon_bytes = ctl.monitor_bytes_sent - mon_before
                per_cycle = (ctl.bytes_sent - bytes_before - mon_bytes) / 5
                result["full_announce_delta"] = (st.full_announces
                                                 - full_before)
                result["warm_bytes_per_cycle"] = round(per_cycle, 1)
                result["serve_requests_during_window"] = \
                    sb.stats()["requests_total"]
        except Exception as exc:  # noqa: BLE001 - recorded, never hangs
            result.setdefault("error", repr(exc))
        finally:
            stop.set()
            agent.close()
            ctl.shutdown()

    t = _threading.Thread(target=run, args=(1,), daemon=True)
    t.start()
    run(0)
    t.join(timeout=30)
    if "error" in result:
        if errors is not None:
            errors["serving_frame_guard"] = result["error"]
    else:
        out["frame_guard"] = {
            **result,
            "held": bool(result.get("full_announce_delta") == 0
                         and (result.get("warm_bytes_per_cycle") or 1e9)
                         <= 32),
        }
    _record_timing("serving", warmup=3, iters=3 * n_req,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_serving_faults(errors=None):
    """Serving-plane fault-tolerance bench (ISSUE 20, docs/serving.md):
    an injected replica fault mid-batch under concurrent load through
    the REAL front door — three claims on every JSON line:

    - **zero lost accepted requests** — every request admitted before,
      during and after the fault gets exactly one terminal response; the
      interrupted batch re-enters via front-door retries under the same
      request ids and completes correctly (``zero_lost``).
    - **availability** — terminal-200 fraction stays 1.0 across the
      fault (retryable failures are retried, never surfaced), plus the
      retry/requeue/fault counter deltas the recovery produced.
    - **recovery-time-to-ready** — wall time from the injected fault to
      the first completed post-heal batch, while the simulated heal
      window holds the dispatch loop down.

    Jax-free (scripted echo worker — the serving math is pinned in
    ``bench_serving``; this section isolates the RECOVERY plane).
    Rank-0 only, self-contained."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    import threading as _threading

    import numpy as np

    from horovod_tpu.serve.batcher import ContinuousBatcher
    from horovod_tpu.serve.frontdoor import FrontDoor
    from horovod_tpu.serve.resilience import CircuitBreaker

    t_section = time.perf_counter()
    n_req = int(os.environ.get("HVD_BENCH_SERVE_FAULT_REQS", "32"))
    fault_at = 3                       # fail the 3rd dispatched batch
    heal_s = 0.15                      # simulated re-rendezvous window

    b = ContinuousBatcher(max_batch=4, buckets=(4,), deadline_ms=10000.0,
                          max_inflight=1, queue_depth=2 * n_req)
    # Breaker effectively disabled: one bucket of simultaneous retryable
    # failures must RETRY, not fast-fail (the breaker's own behaviour is
    # pinned in tests/test_serve_faults.py).
    door = FrontDoor(b, retries=4, hedge_ms=0.0,
                     breaker=CircuitBreaker(threshold=10000))

    state = {"batches": 0, "t_fault": None, "t_ready": None}
    stop = _threading.Event()

    def worker():                      # echo replica: route 2x back
        while not stop.is_set():
            batch = b.next_batch(timeout=0.01)
            if batch is None:
                continue
            state["batches"] += 1
            if state["batches"] == fault_at:
                # The chaos moment: a peer died mid-batch.  Fail THIS
                # batch retryably (queued requests keep their deadlines)
                # and hold the loop down for the heal window.
                state["t_fault"] = time.perf_counter()
                b.fail_retryable(
                    batch, RuntimeError("injected replica fault (bench)"))
                time.sleep(heal_s)
                continue
            b.complete(batch, [np.asarray(r.inputs) * 2.0
                               for r in batch.requests])
            if state["t_fault"] is not None and state["t_ready"] is None:
                state["t_ready"] = time.perf_counter()

    th = _threading.Thread(target=worker, daemon=True)
    th.start()
    outcomes = [None] * n_req
    correct = [False] * n_req

    def client(i):
        x = np.full(4, float(i), np.float32)
        o = door.infer_detailed(x, deadline_ms=10000.0,
                                request_id=f"bench-fault-{i}")
        if o["_code"] == 200:
            correct[i] = bool(np.array_equal(
                np.asarray(o["outputs"], np.float32), x * 2.0))
        outcomes[i] = o

    clients = [_threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_req)]
    for c in clients:
        c.start()
    for c in clients:
        c.join(timeout=60)
    stop.set()
    th.join(timeout=5)

    st = door.stats()
    lost = sum(1 for o in outcomes if o is None)
    ok = sum(1 for o in outcomes if o is not None and o["_code"] == 200)
    retried = sum(1 for o in outcomes
                  if o is not None and o.get("attempts", 1) > 1)
    out = {
        "requests": n_req,
        "lost_requests": lost,
        "ok_responses": ok,
        "retried_requests": retried,
        "results_correct": bool(ok == n_req and all(correct)),
        "replica_faults": st["replica_faults_total"],
        "requeued": st["requeued_total"],
        "retries_total": st["retries_total"],
        "quarantined": st["quarantined_total"],
        "availability": st["availability"],
        "error_budget_remaining": st["error_budget_remaining"],
        "recovery_to_ready_s": (
            None if state["t_fault"] is None or state["t_ready"] is None
            else round(state["t_ready"] - state["t_fault"], 4)),
        "zero_lost": bool(lost == 0 and ok == n_req),
    }
    _record_timing("serving_faults", warmup=0, iters=n_req,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_restore_ab(errors=None, world=4, mb=None):
    """Resilient-state-plane restore A/B (ISSUE 14): wall time to recover
    a joiner's state from the DISK manifest (newest complete epoch, all
    shards read + digest-verified) vs PEER-TO-PEER from the survivors'
    in-memory shard servers — the elastic-recovery collapse this PR
    claims.  Both paths restore the identical blob (bitwise pinned); the
    peer path must do it with zero checkpoint-file reads.  Rank-0 only,
    self-contained (tmp dir + loopback shard servers), jax-free."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    import shutil
    import tempfile

    import numpy as np

    from horovod_tpu.elastic import stateplane as spl

    t_section = time.perf_counter()
    if mb is None:
        mb = float(os.environ.get("HVD_BENCH_RESTORE_MB", "4"))
    n = max(1, int(mb * (1 << 20) / 4))
    state = {"step": 1, "params": np.arange(n, dtype=np.float32)}
    ref_digest = spl.blob_digest(spl.encode_state(state))
    d = tempfile.mkdtemp(prefix="hvd_restore_ab_")
    out = {"world": world, "bytes": n * 4}
    donors = []
    try:
        donors = [spl.StatePlane(d, rank=r, world=world, serve=True)
                  for r in range(world)]
        for p in donors:
            p.commit(state=state, epoch=1, wait=True)

        # Disk path: a fresh joiner, no peers declared.
        j_disk = spl.StatePlane(d, rank=0, world=world, serve=False)
        t0 = time.perf_counter()
        _data, epoch, source = j_disk.restore()
        disk_s = time.perf_counter() - t0
        assert source == "disk" and epoch == 1, (source, epoch)
        disk_ok = j_disk.memory_state()[2] == ref_digest

        # Peer path: the survivors hold a NEWER epoch in memory.
        for p in donors:
            p.commit(state=state, epoch=2)
        j_peer = spl.StatePlane(d + ".joiner", rank=0, world=world,
                                serve=False)
        peers = [("127.0.0.1", p.server.port) for p in donors]
        t0 = time.perf_counter()
        _data, epoch, source = j_peer.restore(peers=peers)
        peer_s = time.perf_counter() - t0
        assert source == "peer" and epoch == 2, (source, epoch)
        out.update({
            "disk_restore_us": round(disk_s * 1e6, 1),
            "peer_restore_us": round(peer_s * 1e6, 1),
            "peer_vs_disk": round(disk_s / peer_s, 3) if peer_s else None,
            "peer_disk_reads": j_peer.disk_reads,
            "peer_shards_fetched": j_peer.peer_shards_fetched,
            "bitwise_identical": bool(
                disk_ok and j_peer.memory_state()[2] == ref_digest),
        })
    except Exception as exc:  # noqa: BLE001 - recorded, never fatal
        if errors is not None:
            errors["restore_ab"] = repr(exc)
    finally:
        for p in donors:
            try:
                p.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(d + ".joiner", ignore_errors=True)
    _record_timing("restore_ab", warmup=0, iters=2,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_sharded_ab(errors=None, steps=None, elems=None):
    """ZeRO sharded-optimizer A/B (ISSUE 15): the replicated adam data
    plane vs ``parallel.zero.sharded_optimizer`` over the live device
    mesh — step wall time, optimizer-state bytes **per rank** (the 1/N
    memory claim, asserted), and modeled wire bytes/step.

    Wire accounting (ring-cost model, B = gradient bytes, n = world):
    the sharded pipeline pays RS + AG = 2·B·(n-1)/n — equal to the plain
    replicated allreduce (ZeRO-1's wire cost is free; its win there is
    the 1/n optimizer state) and strictly below the
    ``wire_bytes_per_step_allreduce`` baseline an RS-less engine pays
    for the same sharded update (allreduce the grads so every rank
    holds them, update your shard, allgather the deltas =
    3·B·(n-1)/n — "allreduce bandwidth for bytes every rank
    immediately re-shards").  Single-controller section (the in-graph
    shard_map path); the eager 2-proc pipeline is pinned by
    tests/data/worker_sharded.py."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import zero

    if jax.process_count() > 1:
        return None                      # single-controller section
    t_section = time.perf_counter()
    if steps is None:
        steps = int(os.environ.get("HVD_BENCH_SHARDED_STEPS", "8"))
    if elems is None:
        elems = int(os.environ.get("HVD_BENCH_SHARDED_ELEMS",
                                   str(1 << 16)))
    mesh = hvd.mesh()
    axis = mesh.axis_names[0]
    world = mesh.shape[axis]
    params = {"w": jnp.asarray(
        np.linspace(-1.0, 1.0, elems).astype(np.float32))}
    gstack = jnp.asarray(
        np.random.RandomState(0).randn(world, elems).astype(np.float32))
    inner = optax.adam(1e-3)

    def rep_step(p, s, g):
        g = {"w": jax.lax.psum(g.reshape(-1), axis)
             / jnp.asarray(world, jnp.float32)}
        u, s = inner.update(g, s, p)
        return optax.apply_updates(p, u), s

    zopt = zero.sharded_optimizer(inner, axis_name=axis)

    def sh_step(p, s, g):
        u, s = zopt.update({"w": g.reshape(-1)}, s, p)
        return optax.apply_updates(p, u), s

    zstate, zspecs = zero.init_sharded_state(inner, params, mesh, axis)
    rep = jax.jit(shard_map(rep_step, mesh=mesh,
                            in_specs=(P(), P(), P(axis)),
                            out_specs=(P(), P()), check_vma=False))
    sh = jax.jit(shard_map(sh_step, mesh=mesh,
                           in_specs=(P(), zspecs, P(axis)),
                           out_specs=(P(), zspecs), check_vma=False))

    def per_rank_bytes(state):
        d0 = jax.devices()[0]
        total = 0
        for l in jax.tree_util.tree_leaves(state):
            if hasattr(l, "addressable_shards"):
                total += sum(
                    int(np.prod(s.data.shape)) * l.dtype.itemsize
                    for s in l.addressable_shards if s.device == d0)
            elif hasattr(l, "nbytes"):
                total += int(l.nbytes)
        return total

    def run(step, p0, s0):
        p, s = p0, s0
        p, s = step(p, s, gstack)              # compile + warm
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(steps):
            p, s = step(p, s, gstack)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / steps, p, s

    rep_ms, p_rep, s_rep = run(rep, params, inner.init(params))
    sh_ms, p_sh, s_sh = run(sh, params, zstate)
    rep_bytes = per_rank_bytes(s_rep)
    sh_bytes = per_rank_bytes(s_sh)
    diff = float(np.max(np.abs(np.asarray(p_rep["w"])
                               - np.asarray(p_sh["w"]))))
    B = elems * 4
    ring = (world - 1) / max(1, world)
    out = {
        "world": world, "grad_bytes": B, "steps": steps,
        "step_ms_replicated": round(rep_ms * 1e3, 3),
        "step_ms_sharded": round(sh_ms * 1e3, 3),
        "opt_state_bytes_per_rank_replicated": rep_bytes,
        "opt_state_bytes_per_rank": sh_bytes,
        # 1/N assertion: shard ≈ replicated/world (pad + replicated
        # scalar counters give the slack).
        "one_over_n": bool(
            sh_bytes <= rep_bytes / world + 2 * world * 4 + 64),
        "wire_bytes_per_step_sharded": int(2 * B * ring),
        "wire_bytes_per_step_replicated": int(2 * B * ring),
        "wire_bytes_per_step_allreduce": int(3 * B * ring),
        "max_abs_param_diff": diff,
        # World of 2 is bitwise; wider worlds may drift by reduction
        # order (documented caveat) — bounded tight either way.
        "params_match": bool(diff <= 1e-5),
    }
    _record_timing("sharded_ab", warmup=1, iters=steps,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_fsdp_ab(errors=None, steps=None, elems=None):
    """Full parameter sharding A/B (ISSUE 18): replicated adam vs
    ZeRO-1 (``sharded_optimizer``) vs ZeRO-3/FSDP
    (``full_sharded_optimizer``) over the live device mesh.

    The FSDP column keeps NO replicated parameters: the state's 1/world
    shards are the only resident copy, the step ignores the returned
    full updates (XLA dead-code-eliminates the delta-allgather), and the
    final parameters come from :func:`gather_full_params`.  Resident
    bytes therefore cover params + optimizer state, and the 1/N claim
    (``one_over_n``) is asserted against the replicated column's total.

    Wire accounting (ring model, B = gradient bytes): FSDP pays
    AG(params) + RS(grads) = 2·B·(n-1)/n — byte-for-byte the ZeRO-1
    pipeline's RS + delta-AG (``wire_full_eq_sharded`` asserted), both
    below the 3·B·(n-1)/n an RS-less engine would pay.  Single-
    controller in-graph section; the eager 2-proc prefetch pipeline is
    pinned by tests/data/worker_fsdp.py."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import zero

    if jax.process_count() > 1:
        return None                      # single-controller section
    t_section = time.perf_counter()
    if steps is None:
        steps = int(os.environ.get("HVD_BENCH_FSDP_STEPS", "8"))
    if elems is None:
        elems = int(os.environ.get("HVD_BENCH_FSDP_ELEMS",
                                   str(1 << 16)))
    mesh = hvd.mesh()
    axis = mesh.axis_names[0]
    world = mesh.shape[axis]
    params = {"w": jnp.asarray(
        np.linspace(-1.0, 1.0, elems).astype(np.float32))}
    gstack = jnp.asarray(
        np.random.RandomState(1).randn(world, elems).astype(np.float32))
    inner = optax.adam(1e-3)

    def rep_step(p, s, g):
        g = {"w": jax.lax.psum(g.reshape(-1), axis)
             / jnp.asarray(world, jnp.float32)}
        u, s = inner.update(g, s, p)
        return optax.apply_updates(p, u), s

    zopt = zero.sharded_optimizer(inner, axis_name=axis)

    def sh_step(p, s, g):
        u, s = zopt.update({"w": g.reshape(-1)}, s, p)
        return optax.apply_updates(p, u), s

    fopt = zero.full_sharded_optimizer(inner, axis_name=axis)

    def full_step(s, g):
        # No replicated params in, none out: the shards ARE the model.
        _, s = fopt.update({"w": g.reshape(-1)}, s, None)
        return s

    zstate, zspecs = zero.init_sharded_state(inner, params, mesh, axis)
    fstate, fspecs = zero.init_full_sharded_state(inner, params, mesh,
                                                  axis)
    rep = jax.jit(shard_map(rep_step, mesh=mesh,
                            in_specs=(P(), P(), P(axis)),
                            out_specs=(P(), P()), check_vma=False))
    sh = jax.jit(shard_map(sh_step, mesh=mesh,
                           in_specs=(P(), zspecs, P(axis)),
                           out_specs=(P(), zspecs), check_vma=False))
    full = jax.jit(shard_map(full_step, mesh=mesh,
                             in_specs=(fspecs, P(axis)),
                             out_specs=fspecs, check_vma=False))
    gather = jax.jit(shard_map(
        lambda s: zero.gather_full_params(s, params, axis), mesh=mesh,
        in_specs=(fspecs,), out_specs=P(), check_vma=False))

    def per_rank_bytes(state):
        d0 = jax.devices()[0]
        total = 0
        for l in jax.tree_util.tree_leaves(state):
            if hasattr(l, "addressable_shards"):
                total += sum(
                    int(np.prod(s.data.shape)) * l.dtype.itemsize
                    for s in l.addressable_shards if s.device == d0)
            elif hasattr(l, "nbytes"):
                total += int(l.nbytes)
        return total

    def run(step, p0, s0):
        p, s = p0, s0
        p, s = step(p, s, gstack)              # compile + warm
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(steps):
            p, s = step(p, s, gstack)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / steps, p, s

    def run_full(step, s0):
        s = step(s0, gstack)                   # compile + warm
        jax.block_until_ready(s)
        t0 = time.perf_counter()
        for _ in range(steps):
            s = step(s, gstack)
        jax.block_until_ready(s)
        return (time.perf_counter() - t0) / steps, s

    rep_ms, p_rep, s_rep = run(rep, params, inner.init(params))
    sh_ms, p_sh, s_sh = run(sh, params, zstate)
    full_ms, s_full = run_full(full, fstate)
    p_full = gather(s_full)

    rep_resident = per_rank_bytes(p_rep) + per_rank_bytes(s_rep)
    sh_resident = per_rank_bytes(p_sh) + per_rank_bytes(s_sh)
    full_resident = per_rank_bytes(s_full)
    diff = float(np.max(np.abs(np.asarray(p_rep["w"])
                               - np.asarray(p_full["w"]))))
    B = elems * 4
    ring = (world - 1) / max(1, world)
    wire_full = int(2 * B * ring)          # prefetch-AG + grad-RS
    wire_sharded = int(2 * B * ring)       # RS + delta-AG (ZeRO-1)
    out = {
        "world": world, "grad_bytes": B, "steps": steps,
        "step_ms_replicated": round(rep_ms * 1e3, 3),
        "step_ms_sharded": round(sh_ms * 1e3, 3),
        "step_ms_full": round(full_ms * 1e3, 3),
        "resident_bytes_replicated": rep_resident,
        "resident_bytes_sharded": sh_resident,
        "resident_bytes_full": full_resident,
        # 1/N assertion for the FSDP column: params + opt state shards ≈
        # replicated total / world (pad + replicated scalar step
        # counters give the slack).
        "one_over_n": bool(
            full_resident <= rep_resident / world + 2 * world * 4 + 64),
        "wire_bytes_per_step_full": wire_full,
        "wire_bytes_per_step_sharded": wire_sharded,
        "wire_bytes_per_step_allreduce": int(3 * B * ring),
        # FSDP's modeled wire == the ZeRO-1 pipeline's (the acceptance
        # criterion): full sharding is a pure memory win at equal wire.
        "wire_full_eq_sharded": bool(wire_full == wire_sharded),
        "max_abs_param_diff": diff,
        # World of 2 is bitwise; wider worlds may drift by reduction
        # order (documented caveat) — bounded tight either way.
        "params_match": bool(diff <= 1e-5),
    }
    _record_timing("fsdp_ab", warmup=1, iters=steps,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_hierarchical_ab(errors=None, steps=None, sizes=None):
    """Two-level ICI/DCN allreduce A/B (ISSUE 17): the flat world ring vs
    RS(local) → AR(cross) → AG(local) through the LIVE engine path, over
    2 simulated slices of the single-process mesh, per payload size.

    Three things land on every JSON line:

    - **wall time per dispatch**, flat vs hierarchical (on a CPU mesh the
      two-level pipeline's three launches usually lose — the measured
      ``crossover_mb``, the smallest size where it wins, is therefore
      often null here; on a real multi-slice pod the DCN byte saving
      dominates past the crossover and the autotuner's ``hier_threshold``
      coordinate learns it);
    - **modeled per-link-class wire bytes** (ring model,
      ``parallel.topology.modeled_leg_bytes``): the cross-slice leg
      carries ≤ 1/local_size of the flat ring's bytes — asserted, the
      headline claim;
    - **bitwise_identical** — integer-valued payloads, so any combination
      order must produce the same bits; a False here is a data-plane bug,
      never fp noise.
    """
    import jax
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.parallel.topology import modeled_leg_bytes

    if jax.process_count() > 1:
        return None                      # single-controller section
    world = hvd.size()
    if world < 4 or world % 2:
        return None                      # needs 2 slices of ≥ 2
    t_section = time.perf_counter()
    local = world // 2
    if steps is None:
        steps = int(os.environ.get("HVD_BENCH_HIER_STEPS", "5"))
    if sizes is None:
        sizes = [int(s) for s in os.environ.get(
            "HVD_BENCH_HIER_SIZES", "4096,65536,1048576").split(",")]

    eng = basics._get_state().engine
    saved = (eng._hier_local_size, eng.slice_map)
    eng._hier_local_size = local
    eng._slice_topos.clear()             # knob mutated: drop cached split
    d0, i0, c0 = eng.hier_dispatches, eng.hier_intra_legs, eng.hier_cross_legs
    rows = []
    try:
        for n in sizes:
            x = hvd.stack_per_rank([
                (np.arange(n, dtype=np.float32) % 7) - 3 + r
                for r in range(world)])

            def run(hier, n=n, x=x):
                name = f"hier_ab_{n}"
                out = hvd.allreduce(x, name=name, op=hvd.Sum,
                                    hierarchical=hier)   # compile + warm
                np.asarray(out)
                t0 = time.perf_counter()
                for _ in range(steps):
                    out = hvd.allreduce(x, name=name, op=hvd.Sum,
                                        hierarchical=hier)
                res = np.asarray(out)
                return (time.perf_counter() - t0) / steps, res

            flat_s, flat_out = run(False)
            hier_s, hier_out = run(True)
            legs = modeled_leg_bytes(n * 4, world, local)
            rows.append({
                "elems": n, "payload_bytes": n * 4,
                "flat_ms": round(flat_s * 1e3, 3),
                "hier_ms": round(hier_s * 1e3, 3),
                "bitwise_identical": bool(
                    np.array_equal(flat_out, hier_out)),
                "wire_bytes_flat": int(legs["flat"]),
                "wire_bytes_intra": int(legs["intra"]),
                "wire_bytes_cross": int(legs["cross"]),
                # the headline: slow links carry ≤ 1/local_size of flat
                "cross_leq_flat_over_local": bool(
                    legs["cross"] <= legs["flat"] / local + 1),
            })
    finally:
        (eng._hier_local_size, eng.slice_map) = saved
        eng._slice_topos.clear()
    crossover_mb = None
    for r in rows:
        if r["hier_ms"] <= r["flat_ms"]:
            crossover_mb = round(r["payload_bytes"] / (1 << 20), 3)
            break
    out = {
        "world": world, "num_slices": 2, "local_size": local,
        "steps": steps, "sizes": rows,
        "crossover_mb": crossover_mb,
        "hier_dispatches": eng.hier_dispatches - d0,
        "hier_intra_legs": eng.hier_intra_legs - i0,
        "hier_cross_legs": eng.hier_cross_legs - c0,
        "bitwise_identical": all(r["bitwise_identical"] for r in rows),
    }
    _record_timing("hierarchical_ab", warmup=2 * len(sizes),
                   iters=2 * steps * len(sizes),
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_zero_rtt(errors=None, world=4, warm=6, cycles=40, n_tensors=8):
    """Zero-RTT warm control plane A/B (ISSUE 11): a simulated world of
    REAL ``TCPController`` clients against the native root server, driven
    through warm steady-state cycles with speculation ON
    (``spec_ready_after=1``) vs OFF (0, today's lock-step).  Per knob:
    warm-cycle negotiation microseconds, speculation hit rate, and the
    negotiation round TRIPS per cycle (a speculative cycle sends its
    frame but returns the predicted verdict without waiting — the claim
    under test is trips < 1 in steady state).  ``orders_identical`` pins
    the bitwise story: every rank's verdict order, on-vs-off, must be
    identical — speculation may only remove the wait, never reorder
    dispatch.  Rank-0 only, self-contained (own server on a free port),
    jax-free."""
    if os.environ.get("HOROVOD_RANK", "0") not in ("", "0"):
        return None
    import threading as _threading

    import numpy as np

    from horovod_tpu.common.controller import TCPController
    from horovod_tpu.common.net import free_ports

    names = [f"zrt.grad.{i}" for i in range(n_tensors)]

    class _E:
        def __init__(self, name):
            self.name = name
            self.tensor = np.zeros((2, 4), np.float32)
            self.group_id = -1

    def run_world(spec):
        port = free_ports(1)[0]
        results, errs = {}, {}
        all_done = _threading.Event()

        def worker(rank):
            ctl = TCPController("127.0.0.1", port, rank=rank, world=world,
                                stall_warn_s=600.0, cache_capacity=256,
                                spec_ready_after=spec)
            try:
                orders = []

                def step():
                    entries = [_E(n) for n in names]
                    got = []
                    for _ in range(60):
                        if not entries:
                            break
                        ready, _e2 = ctl.negotiate(entries)
                        got += [e.name for e in ready]
                        entries = [e for e in entries
                                   if e.name not in set(got)]
                    orders.append(tuple(got))

                for _ in range(warm):
                    step()
                s0, h0, m0, r0 = (ctl.spec_rounds, ctl.spec_hits,
                                  ctl.spec_mispredicts, ctl.rounds)
                t0 = time.perf_counter()
                for _ in range(cycles):
                    step()
                dt = time.perf_counter() - t0
                results[rank] = {
                    "us_per_cycle": dt / cycles * 1e6,
                    "rounds": ctl.rounds - r0,
                    "spec_rounds": ctl.spec_rounds - s0,
                    "spec_hits": ctl.spec_hits - h0,
                    "spec_mispredicts": ctl.spec_mispredicts - m0,
                    "orders": orders,
                }
            except Exception as exc:  # noqa: BLE001 - recorded, never hangs
                errs[rank] = repr(exc)
            finally:
                if len(results) + len(errs) == world:
                    all_done.set()
                all_done.wait(timeout=60)
                ctl.shutdown()

        threads = [_threading.Thread(target=worker, args=(r,), daemon=True)
                   for r in range(1, world)]
        for t in threads:
            t.start()
        worker(0)
        for t in threads:
            t.join(timeout=60)
        if errs:
            raise RuntimeError(f"zero_rtt world failed: {errs}")
        return results

    t_section = time.perf_counter()
    res_on = run_world(1)
    res_off = run_world(0)

    def agg(res, key):
        return round(sum(r[key] for r in res.values()) / len(res), 2)

    hits = sum(r["spec_hits"] for r in res_on.values())
    miss = sum(r["spec_mispredicts"] for r in res_on.values())
    trips_on = (sum(r["rounds"] - r["spec_rounds"]
                    for r in res_on.values())
                / max(1, sum(r["rounds"] for r in res_on.values())))
    on_orders = [r["orders"] for r in res_on.values()]
    off_orders = [r["orders"] for r in res_off.values()]
    out = {
        "world": world, "cycles": cycles, "tensors": n_tensors,
        "negotiation_us_per_cycle_on": agg(res_on, "us_per_cycle"),
        "negotiation_us_per_cycle_off": agg(res_off, "us_per_cycle"),
        "spec_rounds": sum(r["spec_rounds"] for r in res_on.values()),
        "spec_hits": hits,
        "spec_mispredicts": miss,
        "spec_hit_rate": (round(hits / (hits + miss), 4)
                          if hits + miss else None),
        # Round trips the warm cycle still pays with speculation on
        # (1.0 = every cycle lock-stepped; the acceptance bar is < 1).
        "round_trips_per_cycle_on": round(trips_on, 4),
        "round_trips_per_cycle_off": 1.0,
        # Every rank's verdict order, on-vs-off: identical = speculation
        # changed WHEN verdicts returned, never what or in what order.
        "orders_identical": (
            all(o == on_orders[0] for o in on_orders)
            and all(o == off_orders[0] for o in off_orders)
            and on_orders[0] == off_orders[0]),
    }
    off_us = out["negotiation_us_per_cycle_off"]
    if off_us:
        out["speedup"] = round(off_us / out["negotiation_us_per_cycle_on"],
                               3)
    _record_timing("zero_rtt_ab", warmup=warm, iters=cycles * 2,
                   wall_s=time.perf_counter() - t_section)
    return out


def bench_response_cache(iters=30, n_tensors=8, errors=None):
    """Eager steady-state with the negotiation response cache ON vs OFF
    (client-side A/B: the slot tables stay coordinated either way): bus-bw
    for a fixed small tensor set, per-cycle negotiation microseconds, and
    the warm-path hit rate.  Multi-process only — the single-controller
    engine has no negotiation round to cache."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics

    eng = _basics._get_state().engine
    ctl = eng.controller
    out = {"available": ctl is not None}
    if ctl is None:
        return out
    elems = 1 << 14
    xs = [np.full(elems, 1.0 + j * 1e-6, np.float32)
          for j in range(n_tensors)]

    def phase(n_iter):
        us0, c0 = eng.negotiation_us_total, eng.negotiation_cycles
        h0, m0 = ctl.cache_stats.hits, ctl.cache_stats.misses
        t0 = time.perf_counter()
        for _ in range(n_iter):
            outs = hvd.grouped_allreduce(xs, name="rcache_bench",
                                         op=hvd.Sum)
        del outs
        wall = time.perf_counter() - t0
        cyc = max(1, eng.negotiation_cycles - c0)
        hits = ctl.cache_stats.hits - h0
        misses = ctl.cache_stats.misses - m0
        return {
            "step_ms": round(wall / n_iter * 1e3, 3),
            "negotiation_us_per_cycle":
                round((eng.negotiation_us_total - us0) / cyc, 2),
            "hit_rate": round(hits / max(1, hits + misses), 4),
        }

    phase(3)                                   # warm: learn the slots
    out["on"] = phase(iters)
    try:
        ctl.cache_enabled = False              # client-side A/B only: the
        out["off"] = phase(iters)              # server keeps its table, so
    finally:                                   # peers/verdicts stay sound
        ctl.cache_enabled = True
    return out


def bench_pipeline(iters=20, errors=None):
    """Pipelined data plane ON vs OFF A/B: the same eager fused-allreduce
    workload with (a) a single-chunk batch (pipeline must be ≥ parity —
    the chunked program degenerates to the legacy one) and (b) a
    multi-chunk fused batch (where chunked cast/reduce/cast overlap and
    the in-flight window should win).  Works in any mode — chunking is
    rank-local; the in-flight window additionally needs a controller."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics

    import jax

    eng = _basics._get_state().engine
    out = {"max_inflight": eng.max_inflight}
    # Two workloads: "small" fits one chunk either way; "large" splits into
    # several chunks when the pipeline is on.  Input shape follows the
    # launch mode, like bench_busbw: stacked [world, elems] in single-
    # controller mode, the local contribution per process otherwise.
    multi_proc = jax.process_count() > 1
    m = hvd.mesh()
    n_local = len([d for d in m.devices.flat
                   if d.process_index == jax.process_index()])

    def make(elems):
        shape = ((n_local, elems) if n_local > 1 else (elems,)) \
            if multi_proc else (hvd.size(), elems)
        return [np.full(shape, 1.0 + j * 1e-6, np.float32)
                for j in range(4)]

    small, large = make(1 << 12), make(1 << 20)
    chunk_on = 1 << 20            # 1 MB chunks -> 16 chunks for `large`

    def phase(xs, label, n_iter):
        d0, c0 = eng.pipeline_dispatches, eng.pipeline_chunks_total
        t0 = time.perf_counter()
        for _ in range(n_iter):
            outs = hvd.grouped_allreduce(xs, name=f"pipe_bench_{label}",
                                         op=hvd.Sum)
        del outs
        wall = time.perf_counter() - t0
        d = max(1, eng.pipeline_dispatches - d0)
        rec = {"step_ms": round(wall / n_iter * 1e3, 3),
               "chunks_per_cycle":
                   round((eng.pipeline_chunks_total - c0) / d, 2),
               "inflight_depth": (eng._inflight.high_water
                                  if eng._inflight is not None else 0)}
        _record_timing(f"pipeline_{label}", warmup=2, iters=n_iter,
                       wall_s=wall)
        return rec

    saved_chunk, saved_infl = eng.pipeline_chunk_bytes, eng.max_inflight
    try:
        for wl_name, xs in (("single_chunk", small), ("multi_chunk", large)):
            sec = {}
            eng.pipeline_chunk_bytes = 0      # off: one chunk, inline window
            eng.max_inflight = 1
            phase(xs, f"{wl_name}_off", 2)
            sec["off"] = phase(xs, f"{wl_name}_off", iters)
            eng.pipeline_chunk_bytes = chunk_on
            eng.max_inflight = max(2, saved_infl)
            phase(xs, f"{wl_name}_on", 2)
            sec["on"] = phase(xs, f"{wl_name}_on", iters)
            out[wl_name] = sec
    finally:
        eng.pipeline_chunk_bytes, eng.max_inflight = saved_chunk, saved_infl
    return out


def _ab_inputs(n_tensors, elems=1 << 14):
    """Eager A/B workload, shaped per launch mode: stacked [world, elems]
    in single-controller mode, the local contribution per process
    otherwise.  Shared by the monitor/trace A/B sections (bench_pipeline
    keeps its own variant with per-workload element counts)."""
    import jax
    import numpy as np
    import horovod_tpu as hvd
    multi_proc = jax.process_count() > 1
    m = hvd.mesh()
    n_local = len([d for d in m.devices.flat
                   if d.process_index == jax.process_index()])
    shape = ((n_local, elems) if n_local > 1 else (elems,)) \
        if multi_proc else (hvd.size(), elems)
    return [np.full(shape, 1.0 + j * 1e-6, np.float32)
            for j in range(n_tensors)]


def _ab_noise_verdict(on_ms, off_ms, errors, key, label):
    """ONE noise band for every telemetry-plane ON-vs-OFF A/B:
    ``within_noise`` while ON stays inside the jitter band repeated
    identical phases show (15% or 0.2 ms, whichever is larger).  Only a
    GROSS miss (1.5x + 1 ms) lands in ``errors[]`` — the bench never
    hard-fails, and the single-core CPU smoke tier is too jittery to
    treat the tight band as an error there; the A/B history tracks
    within_noise either way."""
    within = (on_ms <= off_ms * 1.15) or (on_ms - off_ms <= 0.2)
    if errors is not None and on_ms > off_ms * 1.5 + 1.0:
        errors[key] = (f"{label} ON step {on_ms}ms vs OFF {off_ms}ms "
                       f"(gross regression, far beyond noise)")
    return bool(within)


def bench_monitor(iters=30, n_tensors=8, errors=None):
    """Telemetry plane ON vs OFF A/B: the same eager steady-state workload
    with no MonitorAgent attached, then with one attached at an aggressive
    reporting interval (so frames actually ride the rounds during the
    measured window).  The claim under test — metrics frames never delay
    negotiation — is recorded as ``within_noise``: the ON step time must
    stay within jitter of OFF.  Works in any mode; the side-channel half
    (frame bytes) additionally needs a controller."""
    import jax
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics
    from horovod_tpu.monitor.agent import MonitorAgent

    eng = _basics._get_state().engine
    ctl = eng.controller
    preexisting = _basics._get_state().monitor
    out = {"already_enabled": preexisting is not None}
    if preexisting is not None:
        # The whole bench was launched with HOROVOD_MONITOR=1: no
        # un-monitored baseline exists, and the user's agent must survive.
        return out
    xs = _ab_inputs(n_tensors)

    def phase(n_iter):
        t0 = time.perf_counter()
        for _ in range(n_iter):
            outs = hvd.grouped_allreduce(xs, name="monitor_bench",
                                         op=hvd.Sum)
        del outs
        return round((time.perf_counter() - t0) / n_iter * 1e3, 3)

    phase(3)                                    # warm: slots + programs
    off_ms = phase(iters)
    world = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
    agent = MonitorAgent(engine=eng, controller=ctl, rank=rank,
                         world=max(1, world), interval_s=0.05)
    try:
        phase(3)
        on_ms = phase(iters)
        out.update({
            "off_step_ms": off_ms, "on_step_ms": on_ms,
            "overhead_pct": round(100.0 * (on_ms / off_ms - 1.0), 2)
            if off_ms else None,
            "frames_sent": agent.frames_sent,
            "metrics_frame_bytes":
                getattr(ctl, "monitor_bytes_sent", 0) if ctl else 0,
        })
        out["within_noise"] = _ab_noise_verdict(
            on_ms, off_ms, errors, "monitor_overhead", "monitoring")
    finally:
        agent.close()
    _record_timing("monitor_ab", warmup=3, iters=iters,
                   wall_s=(off_ms + on_ms) * iters / 1e3)
    return out


def bench_trace(iters=30, n_tensors=8, errors=None):
    """Tracing plane ON vs OFF A/B at fusion scale: the same eager
    steady-state workload with the engine's tracer detached (the disarmed
    default — every stamp site is one attribute check), then with a
    recorder attached (no file I/O: the pure span-stamping cost).

    Two claims are recorded on every JSON line:

    - **overhead bound** (``within_noise``): the disarmed path must stay
      free and the ARMED path must stay within jitter of it — the guard
      future PRs cannot silently regress (a gross miss lands in
      ``errors["trace_overhead"]``);
    - **phase breakdown** (``phases_us``/``cycle_us``/``phase_sum_us``):
      mean per-phase microseconds over the armed window, whose sum must be
      consistent with the measured mean lifecycle — the attribution the
      small-message latency war steers by (docs/timeline.md).
    """
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics
    from horovod_tpu.trace import TraceRecorder

    eng = _basics._get_state().engine
    preexisting = eng.tracer
    out = {"already_armed": preexisting is not None}
    xs = _ab_inputs(n_tensors)

    def phase(n_iter):
        t0 = time.perf_counter()
        for _ in range(n_iter):
            outs = hvd.grouped_allreduce(xs, name="trace_bench",
                                         op=hvd.Sum)
        del outs
        return round((time.perf_counter() - t0) / n_iter * 1e3, 3)

    try:
        if preexisting is None:
            eng.tracer = None
            phase(3)                            # warm: slots + programs
            off_ms = phase(iters)
        else:
            # Launched with HOROVOD_TRACE armed: no disarmed baseline
            # exists; record the armed breakdown only.
            off_ms = None
        eng.tracer = TraceRecorder(capacity=4096) \
            if preexisting is None else preexisting
        phase(3)
        on_ms = phase(iters)
        out.update({"off_step_ms": off_ms, "on_step_ms": on_ms})
        summary = eng.tracer.phase_summary()
        out.update(summary)
        if off_ms is not None:
            out["overhead_pct"] = (round(100.0 * (on_ms / off_ms - 1.0), 2)
                                   if off_ms else None)
            out["within_noise"] = _ab_noise_verdict(
                on_ms, off_ms, errors, "trace_overhead", "tracing")
        # Consistency: the five phase means must re-add to the measured
        # mean lifecycle (they partition it by construction; a drifted
        # stamp would break this).
        if summary.get("cycle_us"):
            drift = abs(summary["phase_sum_us"] - summary["cycle_us"])
            out["phase_sum_consistent"] = bool(
                drift <= max(1.0, 0.01 * summary["cycle_us"]))
    finally:
        if preexisting is None:
            eng.tracer = None
    _record_timing("trace_ab", warmup=3, iters=iters,
                   wall_s=((off_ms or 0) + on_ms) * iters / 1e3)
    return out


def bench_fast_lane(iters=40, errors=None):
    """Latency fast lane ON vs OFF A/B (ISSUE 8) — the latency-critical
    workload: ONE sub-threshold ungrouped blocking allreduce per step.

    Records on every JSON line:

    - **bitwise_identical**: the same input through both lanes produces
      byte-identical results (the fast lane skips the fusion buffer, it
      must never change the math);
    - **off/on step latency** + ``latency_ratio`` (off/on; >1 = the fast
      lane won) and a ``within_noise`` guard (the lane must never be a
      gross regression);
    - **phases_us** for both lanes from a temporarily armed tracer: on
      the fast lane ``copy_in``+``drain`` must collapse toward zero (the
      pinned program is fetched O(1) pre-launch, so the device wait is
      attributed to ``reduce`` — ``copy_in_drain_us`` carries the
      evidence), plus the engagement counters
      (``fast_lane_dispatches``/``pin_hits``)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics
    from horovod_tpu.trace import TraceRecorder

    eng = _basics._get_state().engine
    thr_on = 1 << 20
    out = {"threshold_bytes": thr_on}
    pool = _ab_inputs(8, elems=1 << 12)       # 16KB/rank: sub-threshold
    saved_thr = eng.fast_lane_threshold
    preexisting_tracer = eng.tracer

    def phase(n_iter, tag):
        t0 = time.perf_counter()
        for i in range(n_iter):
            r = hvd.allreduce(pool[i % len(pool)],
                              name=f"fastlane_bench_{tag}", op=hvd.Sum)
        del r
        return round((time.perf_counter() - t0) / n_iter * 1e3, 3)

    def traced_phases(n_iter, tag):
        if preexisting_tracer is not None:
            eng.tracer = preexisting_tracer
            return None               # can't isolate a per-lane breakdown
        eng.tracer = TraceRecorder(capacity=4096)
        phase(n_iter, tag)
        summary = eng.tracer.phase_summary()
        eng.tracer = None
        return summary

    try:
        # OFF lane: legacy fused single-entry dispatch.
        eng.fast_lane_threshold = 0
        r_off = np.asarray(hvd.to_local(hvd.allreduce(
            pool[0], name="fastlane_ab_ref", op=hvd.Sum)))
        phase(3, "off")                       # warm: program + slots
        off_ms = phase(iters, "off")
        ph_off = traced_phases(max(10, iters // 4), "off_traced")

        # ON lane: single-tensor batches through pinned programs.
        eng.fast_lane_threshold = thr_on
        r_on = np.asarray(hvd.to_local(hvd.allreduce(
            pool[0], name="fastlane_ab_ref", op=hvd.Sum)))
        d0, h0 = eng.fast_lane_dispatches, eng.fast_lane_hits
        phase(3, "on")
        on_ms = phase(iters, "on")
        ph_on = traced_phases(max(10, iters // 4), "on_traced")

        out.update({
            "bitwise_identical": bool(np.array_equal(r_off, r_on)),
            "off_step_ms": off_ms, "on_step_ms": on_ms,
            "latency_ratio": round(off_ms / on_ms, 3) if on_ms else None,
            "fast_lane_dispatches": eng.fast_lane_dispatches - d0,
            "pin_hits": eng.fast_lane_hits - h0,
        })
        out["within_noise"] = _ab_noise_verdict(
            on_ms, off_ms, errors, "fast_lane_overhead", "fast lane")
        if errors is not None and not out["bitwise_identical"]:
            errors["fast_lane_bitwise"] = (
                "fast-lane result differs from the fused path — the lane "
                "fork must be bitwise-invisible")
        for tag, ph in (("off", ph_off), ("on", ph_on)):
            if ph and ph.get("phases_us"):
                p = ph["phases_us"]
                out[f"phases_us_{tag}"] = p
                out[f"copy_in_drain_us_{tag}"] = round(
                    p["copy_in"] + p["drain"], 2)
    finally:
        eng.fast_lane_threshold = saved_thr
        eng.tracer = preexisting_tracer
    _record_timing("fast_lane_ab", warmup=3, iters=iters,
                   wall_s=(off_ms + on_ms) * iters / 1e3)
    return out


def bench_busbw(sizes_mb, iters=10, errors=None, engine_only=False):
    """Allreduce bus-bandwidth sweep over both data planes.  A failing size
    records an error and the sweep continues — partial results beat none.

    Iteration counts scale INVERSELY with payload size: each point targets
    ≥``HVD_BENCH_BUSBW_TARGET_WALL_S`` (default 0.2 s) of measured wall —
    10 iters × ~7 ms at 4 KB is noise-dominated, while 256 MB already
    fills the budget at the floor.  Distinct input buffers come from a
    memory-bounded pool cycled round-robin (repeats recur only after the
    pool, keeping the axon dispatch-cache hazard at bay without holding
    hundreds of 256 MB arrays).

    ``crossover_mb`` reports the smallest payload where the engine path's
    bus-bw ≥ raw ``psum``'s — THE small-message-latency-war scoreboard
    (engine ≥ psum everywhere ⇒ crossover at the sweep's left edge)."""
    import jax
    import numpy as np
    from jax import lax
    from horovod_tpu.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    import horovod_tpu as hvd

    n = hvd.size()
    m = hvd.mesh()
    factor = 2.0 * (n - 1) / n if n > 1 else 1.0  # n=1: report algo bw
    target_wall = float(os.environ.get("HVD_BENCH_BUSBW_TARGET_WALL_S",
                                       "0.2"))
    out = {"engine": {}, "psum": {}, "world": n,
           "formula": "2(n-1)/n*bytes/t" if n > 1 else "bytes/t (n=1)",
           # p50-ish end-to-end dispatch latency (wall/iters), the
           # small-tensor metric the GB/s figure hides (VERDICT r3 weak #3).
           "engine_latency_ms": {}, "psum_latency_ms": {},
           "iters": {}, "target_wall_s": target_wall,
           "crossover_mb": None}

    def n_iters(est_dt):
        """≥ the floor, ≤ 1000, sized to fill the wall target."""
        if est_dt <= 0:
            return iters
        return int(max(iters, min(1000, -(-target_wall // est_dt))))

    multi_proc = jax.process_count() > 1
    n_local = len([d for d in m.devices.flat
                   if d.process_index == jax.process_index()])
    for mb in sizes_mb:
        elems = max(1, int(mb * (1 << 20)) // 4)
        label = f"{mb:g}MB"
        try:
            shape = ((n_local, elems) if n_local > 1 else (elems,)) \
                if multi_proc else (n, elems)
            # DISTINCT buffer per timed iteration: repeated bit-identical
            # dispatches can be served by the axon remote-execution cache
            # instead of the interconnect (see tools/README.md — this
            # corrupted the first decode capture), and distinct inputs
            # are also what a real training step submits.
            def make(i):
                a = np.full(shape, 1.0 + i * 1e-6, np.float32)
                return a if multi_proc else jax.device_put(
                    a, NamedSharding(m, P("hvd")))
            x = make(-1)

            # Eager engine path: enqueue -> negotiate -> fused program.
            # Warm iter 1 compiles; iters 2-3 are the timing probe that
            # sizes the measured run.
            r = hvd.allreduce(x, name="busbw_warm", op=hvd.Sum)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for i in range(2):
                r = hvd.allreduce(make(-2 - i), name="busbw_warm",
                                  op=hvd.Sum)
            jax.block_until_ready(r)
            it = n_iters((time.perf_counter() - t0) / 2)
            pool = min(it, max(4, (256 << 20) // max(elems * 4, 1)))
            xs = [make(i) for i in range(pool)]
            t0 = time.perf_counter()
            for i in range(it):
                r = hvd.allreduce(xs[i % pool], name="busbw", op=hvd.Sum)
            jax.block_until_ready(r)
            wall = time.perf_counter() - t0
            dt = wall / it
            out["engine"][label] = round(
                factor * elems * 4 / dt / 1e9, 3)
            out["engine_latency_ms"][label] = round(dt * 1e3, 3)
            out["iters"][label] = it
            _record_timing(f"busbw_engine_{label}", warmup=3, iters=it,
                           wall_s=wall, bytes=elems * 4)
        except Exception as exc:  # noqa: BLE001 - record, keep sweeping
            if errors is not None:
                errors[f"busbw_engine_{label}"] = repr(exc)
            continue

        if engine_only:
            continue
        try:
            # In-graph psum path (what a jitted train step runs).
            def body(s):
                return lax.psum(s.reshape(s.shape[1:]), "hvd")

            f = jax.jit(shard_map(body, mesh=m, in_specs=P("hvd"),
                                  out_specs=P(), check_vma=False))
            if multi_proc:
                x = hvd.to_global(x)
                xs = [hvd.to_global(xi) for xi in xs]
            y = f(x)
            jax.block_until_ready(y)
            t0 = time.perf_counter()
            for xi in xs[:2]:
                y = f(xi)
            jax.block_until_ready(y)
            it = n_iters((time.perf_counter() - t0) / 2) if len(xs) >= 2 \
                else iters
            t0 = time.perf_counter()
            for i in range(it):    # distinct buffers (see engine path)
                y = f(xs[i % len(xs)])
            jax.block_until_ready(y)
            wall = time.perf_counter() - t0
            dt = wall / it
            out["psum"][label] = round(
                factor * elems * 4 / dt / 1e9, 3)
            out["psum_latency_ms"][label] = round(dt * 1e3, 3)
            _record_timing(f"busbw_psum_{label}", warmup=3, iters=it,
                           wall_s=wall, bytes=elems * 4)
        except Exception as exc:  # noqa: BLE001
            if errors is not None:
                errors[f"busbw_psum_{label}"] = repr(exc)

    for mb in sorted(sizes_mb):
        label = f"{mb:g}MB"
        e, p = out["engine"].get(label), out["psum"].get(label)
        if e is not None and p is not None and e >= p:
            out["crossover_mb"] = mb
            break
    return out


def _resnet_pieces(batch, image_size, framework: bool):
    """Build (step_fn, state, data) for the framework or raw-XLA path."""
    import jax
    import jax.numpy as jnp
    import optax
    from horovod_tpu.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.models import resnet
    import horovod_tpu as hvd

    dtype = jnp.bfloat16 if _on_tpu() else jnp.float32
    sgd = optax.sgd(0.1, momentum=0.9)
    x, y = resnet.synthetic_batch(batch, image_size=image_size)

    if framework:
        # The framework hot path: DistributedOptimizer averages gradients
        # over the hvd axis; SyncBN reduces batch statistics over it too.
        cfg = resnet.ResNetConfig(depth=50, num_classes=1000,
                                  compute_dtype=dtype, sync_bn_axis="hvd")
        opt = hvd.DistributedOptimizer(sgd, op=hvd.Average, axis_name="hvd")  # hvd-lint: disable=HVD103  (single-controller benchmark: synthetic data, no persisted model — divergent init is benign)
        mesh = hvd.mesh()
        inner = resnet.make_train_step(cfg, opt, axis_name=None)
        step = jax.jit(shard_map(inner, mesh=mesh,
                                 in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
                                 out_specs=(P(), P(), P(), P()),
                                 check_vma=False),
                       donate_argnums=(0, 1, 2))
        xs = jax.device_put(x, NamedSharding(mesh, P("hvd")))
        ys = jax.device_put(y, NamedSharding(mesh, P("hvd")))
    else:
        cfg = resnet.ResNetConfig(depth=50, num_classes=1000,
                                  compute_dtype=dtype, sync_bn_axis=None)
        step = jax.jit(resnet.make_train_step(cfg, sgd, axis_name=None),
                       donate_argnums=(0, 1, 2))
        xs, ys = jnp.asarray(x), jnp.asarray(y)

    params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
    opt_state = (opt if framework else sgd).init(params)
    return step, (params, stats, opt_state), (xs, ys)


def _timed_steps(step, state, data, steps, section=None, **extra):
    import jax
    params, stats, opt_state = state
    x, y = data
    for _ in range(2):
        params, stats, opt_state, loss = step(params, stats, opt_state, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, stats, opt_state, loss = step(params, stats, opt_state, x, y)
    jax.block_until_ready(loss)
    wall = time.perf_counter() - t0
    if section:
        _record_timing(section, warmup=2, iters=steps, wall_s=wall, **extra)
    return wall


def _compile_with_flops(step, state, data):
    """AOT-compile once (with retry — the big first compile is the call
    most exposed to compile-service outages); return (callable, per-device
    FLOPs or None, memory-analysis dict or None)."""
    params, stats, opt_state = state
    x, y = data
    try:
        compiled = _retry(
            lambda: step.lower(params, stats, opt_state, x, y).compile(),
            "resnet compile")
    except Exception:
        return step, None, None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        flops = None
    # HBM footprint of the executable: the first-class suspect for "bigger
    # batch is slower" (VERDICT r3 weak #2 — batch 256 < batch 128 img/s:
    # if temp bytes approach chip HBM, XLA spills/remats).
    try:
        m = compiled.memory_analysis()
        mem = {k: int(getattr(m, k)) for k in
               ("temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes", "generated_code_size_in_bytes")
               if hasattr(m, k)}
    except Exception:
        mem = None
    return compiled, flops, mem


def bench_resnet(batch, steps, image_size, errors):
    """Framework-path + raw-XLA ResNet-50.

    ``batch`` is the GLOBAL batch (already world-scaled by main()).
    Returns ``(ips, mfu_pct, overhead_pct, raw_ips)`` — any element may be
    None, with the reason recorded in ``errors``.
    """
    import horovod_tpu as hvd

    skip_raw = os.environ.get("HVD_BENCH_SKIP_RAW", "") == "1"
    world = max(1, hvd.size())

    ips = mfu = overhead = raw_ips = None
    try:
        step, state, data = _resnet_pieces(batch, image_size, framework=True)
        step, flops, mem = _compile_with_flops(step, state, data)
        if mem:
            _TIMING["resnet_memory"] = mem
        dt = _timed_steps(step, state, data, steps, "resnet_framework",
                          global_batch=batch, per_device_flops=flops)
        ips = batch * steps / dt

        # cost_analysis() reports the post-SPMD per-device executable, so
        # the MFU denominator is a single chip's peak.
        peak = _peak_flops()
        if flops and peak:
            mfu = round(100.0 * flops * steps / dt / peak, 2)
    except Exception as exc:  # noqa: BLE001 - keep the raw section alive
        errors["resnet_framework"] = repr(exc)

    if not skip_raw:
        try:
            # Fair per-chip comparison: the raw step runs this chip's share
            # of the global batch on one device, no hvd anywhere.
            rbatch = max(1, batch // world)
            rstep, rstate, rdata = _resnet_pieces(rbatch, image_size,
                                                  framework=False)
            rdt = _timed_steps(rstep, rstate, rdata, steps, "resnet_raw",
                               batch=rbatch)
            raw_ips = round(rbatch * steps / rdt, 2)
            if ips is not None:
                # + = framework slower than raw XLA per chip (same
                # semantics as the original (dt-rdt)/rdt step-time ratio).
                overhead = round(
                    100.0 * (raw_ips / (ips / world) - 1.0), 2)
        except Exception as exc:  # noqa: BLE001
            errors["resnet_raw"] = repr(exc)
    return ips, mfu, overhead, raw_ips


def bench_llama(batch, steps):
    """Llama decoder training through the FRAMEWORK path (like the bert
    mode): hvd.DistributedOptimizer gradient averaging inside a shard_map
    step over the hvd mesh.  ``batch`` is the GLOBAL batch.  Flash
    attention follows HVD_TPU_FLASH; auto mode is sequence-aware and at
    this mode's seq=512 picks the XLA path (crossover default 1024), so
    the flash side of the A/B needs an explicit HVD_TPU_FLASH=1 — which
    is exactly how tools/bench_self_capture.py drives both sides."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.models import llama
    from horovod_tpu.ops.flash_attention import flash_enabled

    # HVD_BENCH_EXPERTS=E swaps the dense MLP for the top-k MoE (experts
    # resident on the one chip — the einsum dispatch/combine cost A/B;
    # HVD_BENCH_TOPK picks the routing k).
    n_experts = int(os.environ.get("HVD_BENCH_EXPERTS", "0"))
    # HVD_BENCH_WINDOW=W turns on sliding-window attention — the on-chip
    # O(T·W) vs O(T^2) A/B for the kernel's whole-block skipping.
    window = int(os.environ.get("HVD_BENCH_WINDOW", "0")) or None
    # HVD_BENCH_SEQ stretches the context (default 512) — the long-context
    # regime (>=1024) is where auto routing picks the Pallas flash kernel
    # and XLA's fused attention eventually cannot even compile
    # (FLASH_SWEEP_r05: T=8192 OOMs the XLA path, flash runs).
    seq = int(os.environ.get("HVD_BENCH_SEQ", "512"))
    cfg = llama.LlamaConfig(vocab_size=8192, d_model=512, n_layers=4,
                            n_heads=8, n_kv_heads=4, d_ff=1536, max_seq=seq,
                            dtype=jnp.bfloat16 if _on_tpu() else jnp.float32,
                            dp_axis=None, tp_axis=None, sp_axis=None,
                            n_experts=n_experts, ep_axis=None,
                            sliding_window=window,
                            remat_layers=os.environ.get(
                                "HVD_BENCH_REMAT", "") == "1",
                            router_top_k=int(os.environ.get(
                                "HVD_BENCH_TOPK", "1")))
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    opt = hvd.DistributedOptimizer(optax.adam(1e-3), op=hvd.Average,
                                   axis_name="hvd")
    opt_state = opt.init(params)
    mesh = hvd.mesh()
    step = jax.jit(shard_map(
        llama.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    tokens = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        NamedSharding(mesh, P("hvd")))
    targets = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        NamedSharding(mesh, P("hvd")))
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    # Analytic train FLOPs (XLA's cost_analysis cannot see inside the
    # Pallas custom calls, so the flash side would undercount): 6*P per
    # token for the dense/MoE-active params + 12*L*T*H*Dh per token of
    # causal attention (qk+pv, fwd+bwd), halved for causality, banded
    # for sliding window.
    leaves = jax.tree_util.tree_leaves(params)
    n_params = sum(x.size for x in leaves)
    flop_params = float(n_params)
    if n_experts:
        # Experts are [E, ., .] leaves; the einsum runs over every E*C
        # capacity slot, so the per-token active multiplier is
        # top_k * capacity_factor of ONE expert, not all E.
        ep = sum(x.size for x in leaves
                 if getattr(x, "ndim", 0) == 3 and x.shape[0] == n_experts)
        cf = cfg.moe_cfg().capacity_factor
        flop_params = (n_params - ep) + ep / n_experts * cfg.router_top_k * cf
    t_eff = min(window, seq) if window else seq
    attn_frac = (t_eff / seq) * (1.0 if window else 0.5)
    attn_flops = (12 * cfg.n_layers * batch * seq * seq
                  * cfg.n_heads * cfg.head_dim * attn_frac)
    step_flops = 6.0 * flop_params * batch * seq + attn_flops
    world = max(1, len(jax.devices()))
    peak = _peak_flops()
    mfu = (step_flops / world / (dt / steps) / peak * 100
           if peak else None)
    _record_timing("llama", warmup=2, iters=steps, wall_s=dt,
                   global_batch=batch, seq=seq,
                   flash=flash_enabled(seq=seq, causal=True),
                   n_experts=n_experts, router_top_k=cfg.router_top_k,
                   sliding_window=window or 0, n_params=int(n_params),
                   analytic_step_flops=step_flops,
                   mfu_pct=round(mfu, 2) if mfu else None)
    return batch * seq * steps / dt


def bench_decode(batch, steps):
    """Inference throughput on the flagship llama (beyond-ref: Horovod
    ships no inference path): blockwise-flash prefill tokens/s and
    steady-state KV-cache decode tokens/s, single chip, greedy.  The
    prefill number is the batched-attention path (one pass over layers);
    decode is the sequential per-token path — the two regimes a serving
    stack cares about."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.models import llama
    from horovod_tpu.ops.flash_attention import flash_enabled

    cfg = llama.LlamaConfig(vocab_size=8192, d_model=512, n_layers=4,
                            n_heads=8, n_kv_heads=4, d_ff=1536, max_seq=512,
                            dtype=jnp.bfloat16 if _on_tpu() else jnp.float32,
                            dp_axis=None, tp_axis=None, sp_axis=None)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    # HVD_BENCH_DECODE_PROMPT stretches the prompt (>=512 routes the
    # blockwise prefill through the flash kernel at the causal default).
    T0 = int(os.environ.get("HVD_BENCH_DECODE_PROMPT", "256"))
    # decode time is measured as generate − prefill; on TPU the floor is
    # the per-dispatch tunnel latency (~10 ms), so the decode phase must
    # dominate it — generate enough tokens that it does.  CPU tests keep
    # the tiny budget.
    n_new = max(256 if _on_tpu() else 8, steps)
    reps = 3
    # DISTINCT prompt per timed call: the axon remote-execution path
    # serves bit-identical dispatches from cache, so timing repeats of
    # one prompt measures the cache, not the chip (see tools/README.md —
    # the first decode numbers were corrupted exactly this way).
    prompts = [jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, T0)),
                           jnp.int32) for _ in range(reps + 1)]

    # Prefill phase alone (jitted once, timed over distinct prompts).
    pf = jax.jit(lambda p, c, t: llama.prefill(p, c, t, cfg))
    cache0 = llama.init_cache(cfg, batch, T0 + n_new)
    logits, cache = pf(params, cache0, prompts[0])
    jax.block_until_ready(logits)
    t0 = time.perf_counter()
    for i in range(1, reps + 1):
        logits, cache = pf(params, cache0, prompts[i])
    jax.block_until_ready(logits)
    prefill_s = (time.perf_counter() - t0) / reps
    prefill_tps = batch * T0 / prefill_s

    # Steady-state decode: n_new sequential cached steps via generate's
    # scan (includes the sampling argmax) — distinct prompts again.
    gen = jax.jit(lambda p, t: llama.generate(p, t, n_new, cfg,
                                              max_seq=T0 + n_new))
    toks = gen(params, prompts[0])
    jax.block_until_ready(toks)
    t0 = time.perf_counter()
    for i in range(1, reps + 1):
        toks = gen(params, prompts[i])
    jax.block_until_ready(toks)
    gen_s = (time.perf_counter() - t0) / reps
    decode_s = max(1e-9, gen_s - prefill_s)   # generate = prefill + decode
    decode_tps = batch * n_new / decode_s
    _record_timing("decode", warmup=1, iters=reps, wall_s=gen_s * reps,
                   prefill_wall_s=prefill_s, batch=batch, prompt_len=T0,
                   new_tokens=n_new,
                   # Routing provenance: prefill decides on the PROMPT
                   # length (decode's per-token cached path never uses
                   # the flash kernel).
                   prefill_flash=flash_enabled(seq=T0, causal=True))
    return prefill_tps, decode_tps


def bench_bert(batch, steps):
    """BASELINE config #3: BERT MLM pretraining through the framework path —
    DistributedOptimizer with fp16-compressed fused allreduce inside a
    shard_map step over the hvd mesh.

    ``batch`` is the GLOBAL batch (already world-scaled by main()), sharded
    over the hvd axis.  ``dp_axis=None`` on the model so its own
    ``sync_grads`` is a no-op — the data-parallel reduce under test is
    exactly the optimizer's compressed allreduce, not a second psum.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.models import bert

    # HVD_BENCH_SEQ stretches the context (default 256) — the in-model
    # evidence for the NON-causal routing crossover.
    seq = int(os.environ.get("HVD_BENCH_SEQ", "256"))
    cfg = bert.tiny(vocab_size=8192, d_model=512, n_layers=4, n_heads=8,
                    d_ff=2048, max_seq=max(512, seq),
                    dtype=jnp.bfloat16 if _on_tpu() else jnp.float32,
                    dp_axis=None, tp_axis=None, sp_axis=None)
    opt = hvd.DistributedOptimizer(optax.adam(1e-4),
                                   compression=hvd.Compression.fp16)
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    mesh = hvd.mesh()
    step = jax.jit(shard_map(
        bert.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    toks = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        NamedSharding(mesh, P("hvd")))
    tgts = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        NamedSharding(mesh, P("hvd")))
    mask = jax.device_put(
        (rng.rand(batch, seq) < 0.15).astype(np.float32),
        NamedSharding(mesh, P("hvd")))
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, toks, tgts, mask)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, toks, tgts, mask)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    # Same analytic MFU accounting as bench_llama (non-causal: full
    # [T, T] attention, no banding).
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    attn_flops = (12 * cfg.n_layers * batch * seq * seq
                  * cfg.n_heads * (cfg.d_model // cfg.n_heads))
    step_flops = 6.0 * n_params * batch * seq + attn_flops
    world = max(1, len(jax.devices()))
    peak = _peak_flops()
    mfu = (step_flops / world / (dt / steps) / peak * 100
           if peak else None)
    _record_timing("bert", warmup=2, iters=steps, wall_s=dt,
                   global_batch=batch, seq=seq, n_params=int(n_params),
                   analytic_step_flops=step_flops,
                   mfu_pct=round(mfu, 2) if mfu else None)
    return batch * seq * steps / dt


def bench_vit(batch, steps):
    """ViT-Base/16 ImageNet-shape classification through the framework
    path (beyond-ref models row): DistributedOptimizer gradient
    averaging inside a shard_map step, synthetic images.  ``batch`` is
    the GLOBAL batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.models import vit

    image = int(os.environ.get("HVD_BENCH_IMAGE", "224"))
    cfg = vit.ViTConfig(image_size=image, patch_size=16,
                        n_classes=1000,
                        dtype=jnp.bfloat16 if _on_tpu() else jnp.float32,
                        dp_axis=None, tp_axis=None)
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    opt = hvd.DistributedOptimizer(optax.adam(1e-3), op=hvd.Average,
                                   axis_name="hvd")
    opt_state = opt.init(params)
    mesh = hvd.mesh()
    step = jax.jit(shard_map(
        vit.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.randn(batch, image, image, 3).astype(np.float32),
        NamedSharding(mesh, P("hvd")))
    labels = jax.device_put(
        rng.randint(0, 1000, batch).astype(np.int32),
        NamedSharding(mesh, P("hvd")))
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, images, labels)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, images, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    # Analytic MFU: 6*P per image-token over the (1 + n_patches) sequence
    # plus full non-causal attention (same accounting as bench_bert).
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    seq = cfg.n_patches + 1
    attn_flops = (12 * cfg.n_layers * batch * seq * seq
                  * cfg.n_heads * cfg.head_dim)
    step_flops = 6.0 * n_params * batch * seq + attn_flops
    world = max(1, len(jax.devices()))
    peak = _peak_flops()
    mfu = (step_flops / world / (dt / steps) / peak * 100
           if peak else None)
    _record_timing("vit", warmup=2, iters=steps, wall_s=dt,
                   global_batch=batch, image=image, seq=seq,
                   n_params=int(n_params), analytic_step_flops=step_flops,
                   mfu_pct=round(mfu, 2) if mfu else None)
    return batch * steps / dt


def bench_autotune():
    """Exercise the reference-N9 parameter manager on a real gradient
    workload and record what it buys (VERDICT r3 ask #8).

    Drives the EAGER engine path (the thing fusion-threshold/cycle-time
    tuning affects): each step submits the full ResNet-50 per-parameter
    gradient set as async grouped allreduces and waits — the reference's
    hook→background-thread regime.  Measures steps/s with default knobs,
    then re-initializes with ``HOROVOD_AUTOTUNE=1``, runs until the search
    converges, and measures again.  Returns a dict with the converged
    (fusion_threshold, cycle_time) and the throughput delta.
    """
    import jax
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.ops import eager as _eager

    on_tpu = _on_tpu()
    if on_tpu:
        from horovod_tpu.models import resnet
        cfg = resnet.ResNetConfig(depth=50, num_classes=1000,
                                  sync_bn_axis=None)
        params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
        shapes = [tuple(l.shape) for l in jax.tree_util.tree_leaves(params)]
        del params, stats
    else:
        # CPU tier: a small synthetic size mix (replicating 25M params
        # across 8 virtual ranks on one core is all collective, no signal).
        rng0 = np.random.RandomState(0)
        shapes = [tuple(int(x) for x in rng0.randint(8, 96, size=2))
                  for _ in range(24)]

    def make_inputs(value=1.0):
        if _eager.per_process_mode():
            return [np.full(s, value, np.float32) for s in shapes]
        return [hvd.to_global(np.full((hvd.size(),) + s, value, np.float32))
                for s in shapes]

    def make_sets(count):
        # DISTINCT tensor set per step: bit-identical repeated dispatches
        # can be served by the axon remote-execution cache instead of the
        # engine actually executing (see tools/README.md) — and distinct
        # gradients are what training submits anyway.
        return [make_inputs(1.0 + j * 1e-6) for j in range(count)]

    def steps_per_s(sets, n):
        t0 = time.perf_counter()
        for i in range(n):
            hs = hvd.grouped_allreduce_async(sets[i % len(sets)],
                                             name="autotune_bench",
                                             op=hvd.Sum)
            hvd.synchronize(hs)
        return n / (time.perf_counter() - t0)

    if os.environ.get("HOROVOD_AUTOTUNE", "") == "1":
        # The whole bench was launched tuned: a default-vs-tuned delta is
        # unmeasurable (the "default" engine is already autotuning), and
        # the user's opt-in must survive this section untouched.
        return {"skipped": "HOROVOD_AUTOTUNE=1 was set for the whole run; "
                           "no default-knob baseline exists to compare"}

    n = int(os.environ.get("HVD_BENCH_AUTOTUNE_STEPS",
                           "30" if on_tpu else "15"))
    sets = make_sets(n)
    steps_per_s(sets[:1], 3)                     # warm the program cache
    base = steps_per_s(sets, n)

    # Fresh engine with the tuner on; bounded so the section stays minutes.
    hvd.shutdown()
    knob_keys = ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
                 "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
                 "HOROVOD_AUTOTUNE_MAX_EVALS")
    saved = {k: os.environ.get(k) for k in knob_keys}
    os.environ["HOROVOD_AUTOTUNE"] = "1"
    os.environ.setdefault("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    os.environ.setdefault("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "4")
    os.environ.setdefault("HOROVOD_AUTOTUNE_MAX_EVALS", "16")
    try:
        hvd.init()
        from horovod_tpu.common.basics import _get_state
        eng = _get_state().engine
        # The convergence loop cycles the distinct sets (a full per-step
        # pool for 400 steps would be GBs); repeats recur only after
        # len(sets) steps, so the tuner's samples stay dominated by real
        # executions.
        sets = make_sets(n)
        for i in range(400):                     # converge (bounded)
            hs = hvd.grouped_allreduce_async(sets[i % len(sets)],
                                             name="autotune_bench",
                                             op=hvd.Sum)
            hvd.synchronize(hs)
            if eng.autotuner is None or not eng.autotuner.tuning:
                break
        tuned = steps_per_s(sets, n)
        return {
            "converged": eng.autotuner is not None
                         and not eng.autotuner.tuning,
            "fusion_threshold_bytes": int(eng.fusion_threshold),
            "cycle_time_s": round(float(eng.cycle_time_s), 6),
            "steps_per_s_default": round(base, 2),
            "steps_per_s_tuned": round(tuned, 2),
            "speedup": round(tuned / base, 3) if base else None,
            "n_tensors": len(shapes),
        }
    finally:
        # Restore the pre-section env verbatim and a default-knob engine
        # for any later section.
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        hvd.shutdown()
        hvd.init()


def bench_tf_step(steps):
    """Per-step host cost of the TF binding (VERDICT r3 missing #3).

    The reference's TF shim is an async C++ kernel with no per-step Python
    round-trip (``horovod/tensorflow/mpi_ops.cc`` — SURVEY N27); this
    repo's binding crosses TF-graph → ``tf.py_function`` → numpy → engine
    once per compiled step.  Measures a compiled ``tf.function`` train
    step on a ~600k-param MLP through ``hvd.DistributedOptimizer``
    (py_function + ONE grouped engine allreduce) vs the identical step on
    the plain optimizer (no hvd anywhere), same process.  Returns
    ``(hvd_ms, plain_ms, overhead_pct, grouped_ms)`` — per-step wall
    times, the binding's cost as a percentage of the plain step, and the
    same gradient set through the eager grouped allreduce alone (isolating
    the collective+bridge from the py_function boundary).
    """
    import tensorflow as tf
    import numpy as np
    import horovod_tpu.tensorflow as hvdtf

    tf.random.set_seed(0)
    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(256, 512).astype(np.float32))
    y = tf.constant(rng.randint(0, 10, 256).astype(np.int64))
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    def build():
        return tf.keras.Sequential([
            tf.keras.layers.Input((512,)),
            tf.keras.layers.Dense(512, activation="relu"),
            tf.keras.layers.Dense(512, activation="relu"),
            tf.keras.layers.Dense(10),
        ])

    def timed(model, opt):
        @tf.function
        def step(x, y):
            with tf.GradientTape() as tape:
                loss = loss_obj(y, model(x, training=True))
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            return loss

        for _ in range(3):
            step(x, y)
        t0 = time.perf_counter()
        for _ in range(steps):
            step(x, y)
        return (time.perf_counter() - t0) / steps

    plain = timed(build(), tf.keras.optimizers.SGD(0.01))
    hvd_opt = hvdtf.DistributedOptimizer(tf.keras.optimizers.SGD(0.01))
    hvd = timed(build(), hvd_opt)
    overhead = 100.0 * (hvd / plain - 1.0)

    # Isolate the pieces: the same gradient set through the binding's
    # eager grouped allreduce (tf→numpy bridge + ONE fused engine
    # collective, no py_function boundary).  hvd − plain − grouped ≈ the
    # tf.function/py_function crossing itself.
    model = build()
    with tf.GradientTape() as tape:
        loss = loss_obj(y, model(x, training=True))
    grads = tape.gradient(loss, model.trainable_variables)
    # Distinct gradient set per timed call (axon dispatch-cache hazard,
    # see tools/README.md) — precomputed outside the timed region.
    grad_sets = [[g + tf.constant(i * 1e-6) for g in grads]
                 for i in range(steps)]
    for _ in range(3):
        hvdtf.grouped_allreduce(grads, name="tf_step_iso")
    t0 = time.perf_counter()
    for gs in grad_sets:
        hvdtf.grouped_allreduce(gs, name="tf_step_iso")
    grouped = (time.perf_counter() - t0) / steps

    _record_timing("tf_step_hvd", warmup=3, iters=steps, wall_s=hvd * steps)
    _record_timing("tf_step_plain", warmup=3, iters=steps,
                   wall_s=plain * steps)
    _record_timing("tf_step_grouped_allreduce", warmup=3, iters=steps,
                   wall_s=grouped * steps)
    return hvd * 1e3, plain * 1e3, overhead, grouped * 1e3


def _emit(out, rank):
    if rank == 0:
        print(json.dumps(out))
        sys.stdout.flush()


def _best_busbw(busbw):
    """Largest engine-path bus-bw across the sweep (headline for minimal
    mode)."""
    if not busbw:
        return None
    vals = list(busbw.get("engine", {}).values())
    return max(vals) if vals else None


def _arm_watchdog(out, errors, budget_s):
    """Last-line-of-defense timer: guarantees the driver gets its one
    parseable JSON line no matter what wedges — including an un-killable
    probe subprocess (subprocess.run can block in its post-kill wait).  The
    message distinguishes "chip never came up" (probe phase still running)
    from "bench slow / mid-run wedge" (a probe had succeeded)."""
    import threading

    def fire():
        # No probe key at all = probing was skipped (HVD_BENCH_SKIP_PROBE);
        # only an explicit ok=False means the claim was still being probed.
        probed = out.get("probe", {"ok": True}).get("ok", False)
        if probed:
            errors["watchdog"] = (
                f"bench exceeded its {budget_s:.0f}s watchdog "
                f"(HVD_BENCH_TIMEOUT_S + slack) after the device claim was "
                f"proven/skipped — slow bench or mid-run tunnel drop; "
                f"partial results only")
        else:
            errors["watchdog"] = (
                f"bench exceeded its {budget_s:.0f}s watchdog "
                f"(HVD_BENCH_TIMEOUT_S + slack) while still PROBING the "
                f"device claim — chip never came up (probe subprocess "
                f"likely un-killably wedged); this is NOT a slow bench")
        # One line per JOB, not per rank: in multi-process worlds only the
        # rank-0 process (per the launcher env) prints.
        if os.environ.get("HOROVOD_RANK", "0") in ("", "0"):
            print(json.dumps(out))
            sys.stdout.flush()
        os._exit(0)

    t = threading.Timer(max(1.0, budget_s), fire)
    t.daemon = True
    t.start()
    return t


def main():
    errors: dict = {}
    out = {
        "metric": "resnet50_hvd_framework_images_per_sec_per_chip",
        "value": None, "unit": "images/sec/chip", "vs_baseline": None,
        "vs_baseline_def": "framework img/s ÷ raw-XLA img/s on this chip "
                           "(1.0 = zero framework overhead); MFU/100 when "
                           "raw section unavailable; null = no data",
        # Smallest busbw-sweep payload where engine ≥ psum (the latency-
        # war scoreboard); null until the busbw section runs/succeeds.
        "crossover_mb": None,
        # Control-plane scale-out scoreboard (ISSUE 9): flat-server vs
        # hierarchical negotiation_us ratio at the largest simulated world
        # in the negotiation_scaling sweep; null until that section runs.
        "flat_vs_hier": None,
        # Churned-sweep certification (ISSUE 12): True when every
        # negotiation_scaling world rode out its scripted churn (LEAVEs +
        # join epoch + agent death) without an abort; null until the
        # section runs (or with churn disabled).
        "churn_survived": None,
        "errors": errors,
    }
    budget = float(os.environ.get("HVD_BENCH_TIMEOUT_S", "900"))
    deadline = time.monotonic() + budget
    # Armed BEFORE the probe phase: even an un-killably wedged probe child
    # (subprocess.run blocking in its post-kill wait) cannot leave the
    # driver without a JSON line.  Leaves 15s of slack so the watchdog
    # fires only if the probe loop itself wedges past its own deadline.
    watchdog = _arm_watchdog(out, errors, budget + 15)
    if os.environ.get("HVD_BENCH_SKIP_PROBE", "") != "1":
        if not _probe_subprocess_loop(deadline, out):
            p = out.get("probe", {})
            errors["probe"] = (
                f"chip never came up: {p.get('attempts', 0)} subprocess "
                f"probe attempts (≤{p.get('per_attempt_timeout_s', 0):.0f}s "
                f"each) all failed within the {budget:.0f}s budget — device/"
                f"compile tunnel unreachable; this is NOT a slow bench")
            watchdog.cancel()
            _emit(out, int(os.environ.get("HOROVOD_RANK", "0") or 0))
            return
    try:
        _run(out, errors)
    except BaseException as exc:  # noqa: BLE001 - the line must still print
        errors["fatal"] = repr(exc)
        out["traceback"] = traceback.format_exc()[-2000:]
    # Control-plane trajectory keys ride EVERY JSON line (all model paths,
    # minimal mode, even partial failures): negotiation overhead is what
    # the response-cache work moves, so it must be visible per round.
    try:
        out.update(_control_plane_stats())
    except Exception:  # noqa: BLE001 - never void the line for telemetry
        pass
    # Rank is resolved on success AND failure paths so a fatal error in a
    # multi-process world still yields exactly one JSON line.
    try:
        import horovod_tpu as hvd
        rank = hvd.rank() if hvd.is_initialized() else \
            int(os.environ.get("HOROVOD_RANK", "0") or 0)
    except Exception:  # noqa: BLE001 - pre-import wedge
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
    watchdog.cancel()
    _emit(out, rank)


def _run(out, errors):
    import horovod_tpu as hvd

    # CPU multi-process smoke runs (torovodrun -np N bench.py): cross-
    # process XLA collectives need gloo — the test workers opt in
    # explicitly, and this jax build ignores the launcher's env hint — so
    # do the same here or every engine/psum section errors with
    # "Multiprocess computations aren't implemented on the CPU backend".
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") and \
            int(os.environ.get("HOROVOD_SIZE", "1") or 1) > 1:
        try:
            import jax
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - never void the line for a hint
            pass

    out["timing_evidence"] = _TIMING  # filled in-place by each section

    # init() FIRST: it may need jax.distributed.initialize(), which must run
    # before any jax.devices() query finalizes a single-process backend.
    # Retried: a transient coordinator/compile-service outage at startup
    # must not zero the bench.
    _retry(hvd.init, "hvd.init")

    # Prove the device path before committing to big compiles; a hard
    # outage yields one clear error instead of one per section.
    _retry(_probe_device, "device probe")

    minimal = os.environ.get("HVD_BENCH_MINIMAL", "") == "1"
    model = os.environ.get("HVD_BENCH_MODEL", "resnet50")
    on_tpu = _on_tpu()
    # HVD_BENCH_BATCH is the PER-CHIP batch; the global batch scales with
    # the world so per-chip work (and shard divisibility) is invariant.
    per_chip = int(os.environ.get("HVD_BENCH_BATCH",
                                  "128" if on_tpu else "8"))
    batch = per_chip * max(1, hvd.size())
    steps = int(os.environ.get("HVD_BENCH_STEPS", "50" if on_tpu else "3"))
    image = int(os.environ.get("HVD_BENCH_IMAGE", "224" if on_tpu else "64"))
    # Fractional sizes allowed: the small end measures dispatch latency
    # (4KB/64KB), the large end bus bandwidth.
    sizes = os.environ.get(
        "HVD_BENCH_SIZES_MB",
        "0.00390625,0.0625,1,4,16,64,256" if on_tpu else "1,4")
    sizes_mb = [float(s) for s in sizes.split(",") if s]

    out.update({"world": hvd.size(), "on_tpu": on_tpu})

    if minimal:
        # Smallest compile surface: eager engine allreduce only.
        busbw = bench_busbw(sizes_mb, errors=errors, engine_only=True)
        best = _best_busbw(busbw)
        out.update({
            "metric": "allreduce_engine_busbw_GBps",
            "value": best, "unit": "GB/s",
            "vs_baseline": 1.0 if best else None,
            "vs_baseline_def": "minimal mode: 1.0 = engine path executed "
                               "on device; null = no data",
            "allreduce_busbw_GBps": busbw,
            "crossover_mb": busbw.get("crossover_mb"),
        })
        try:
            out["response_cache"] = bench_response_cache(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["response_cache"] = repr(exc)
        try:
            out["pipeline"] = bench_pipeline(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["pipeline"] = repr(exc)
        try:
            out["fast_lane_ab"] = bench_fast_lane(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["fast_lane_ab"] = repr(exc)
        try:
            out["monitor_ab"] = bench_monitor(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["monitor_ab"] = repr(exc)
        try:
            out["trace_ab"] = bench_trace(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["trace_ab"] = repr(exc)
        if os.environ.get("HVD_BENCH_SKIP_NEGOTIATION", "") != "1":
            try:
                sec = bench_negotiation_scaling(errors=errors)
                out["negotiation_scaling"] = sec
                if sec:
                    out["flat_vs_hier"] = sec.get("flat_vs_hier")
                    out["churn_survived"] = sec.get("churn_survived")
            except Exception as exc:  # noqa: BLE001 - contained
                errors["negotiation_scaling"] = repr(exc)
        try:
            out["autoscale"] = bench_autoscale(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["autoscale"] = repr(exc)
        try:
            out["serving"] = bench_serving(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["serving"] = repr(exc)
        try:
            out["serving_faults"] = bench_serving_faults(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["serving_faults"] = repr(exc)
        try:
            out["restore_ab"] = bench_restore_ab(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["restore_ab"] = repr(exc)
        try:
            out["sharded_ab"] = bench_sharded_ab(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["sharded_ab"] = repr(exc)
        try:
            out["fsdp_ab"] = bench_fsdp_ab(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["fsdp_ab"] = repr(exc)
        try:
            out["hierarchical_ab"] = bench_hierarchical_ab(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["hierarchical_ab"] = repr(exc)
        try:
            out["zero_rtt_ab"] = bench_zero_rtt(errors=errors)
        except Exception as exc:  # noqa: BLE001 - contained
            errors["zero_rtt_ab"] = repr(exc)
        return

    if model == "llama":
        # Metric identity first, so a mid-compile failure is still
        # recorded under the llama metric with its own error key.
        out.update({"metric": "llama_framework_train_tokens_per_sec_per_chip",
                    "value": None, "unit": "tokens/sec",
                    "vs_baseline": None})
        try:
            world = max(1, hvd.size())
            tps = bench_llama(batch, steps)      # global batch, global tps
            out["value"] = round(tps / world, 2)
        except Exception as exc:  # noqa: BLE001 - contained like the rest
            errors["llama"] = repr(exc)
        return

    if model == "decode":
        out.update({"metric": "llama_decode_tokens_per_sec",
                    "value": None, "unit": "tokens/sec",
                    "vs_baseline": None,
                    "vs_baseline_def": "no reference analogue (Horovod "
                                       "ships no inference path)"})
        try:
            # Decode batch is a serving-shaped batch, not the training
            # per-chip batch.
            dbatch = int(os.environ.get("HVD_BENCH_DECODE_BATCH", "8"))
            prefill_tps, decode_tps = bench_decode(dbatch, steps)
            out.update({"value": round(decode_tps, 2),
                        "prefill_tokens_per_sec": round(prefill_tps, 2)})
        except Exception as exc:  # noqa: BLE001 - contained like the rest
            errors["decode"] = repr(exc)
        return

    if model == "tf_step":
        out.update({"metric": "tf_binding_step_overhead_pct",
                    "value": None, "unit": "%",
                    "vs_baseline": None,
                    "vs_baseline_def": "hvd-step ms ÷ plain-step ms "
                                       "(1.0 = free binding)"})
        try:
            hvd_ms, plain_ms, overhead, grouped_ms = bench_tf_step(steps)
            out.update({"value": round(overhead, 2),
                        "tf_step_hvd_ms": round(hvd_ms, 3),
                        "tf_step_plain_ms": round(plain_ms, 3),
                        "tf_grouped_allreduce_ms": round(grouped_ms, 3),
                        "tf_pyfunc_boundary_ms": round(
                            max(0.0, hvd_ms - plain_ms - grouped_ms), 3),
                        "vs_baseline": round(hvd_ms / plain_ms, 3)})
        except Exception as exc:  # noqa: BLE001 - contained like the rest
            errors["tf_step"] = repr(exc)
        return

    if model == "bert":
        out.update({"metric": "bert_mlm_framework_tokens_per_sec_per_chip",
                    "value": None, "unit": "tokens/sec",
                    "vs_baseline": None})
        try:
            world = max(1, hvd.size())
            tps = bench_bert(batch, steps)       # global batch, global tps
            out["value"] = round(tps / world, 2)
        except Exception as exc:  # noqa: BLE001 - contained like the rest
            errors["bert"] = repr(exc)
        return

    if model == "vit":
        out.update({"metric": "vit_b16_framework_images_per_sec_per_chip",
                    "value": None, "unit": "images/sec",
                    "vs_baseline": None})
        try:
            world = max(1, hvd.size())
            ips = bench_vit(batch, steps)        # global batch, global ips
            out["value"] = round(ips / world, 2)
        except Exception as exc:  # noqa: BLE001 - contained like the rest
            errors["vit"] = repr(exc)
        return

    busbw = None
    if os.environ.get("HVD_BENCH_SKIP_BUSBW", "") != "1":
        try:
            busbw = bench_busbw(sizes_mb, errors=errors)
        except Exception as exc:  # noqa: BLE001 - whole-section failure
            errors["busbw"] = repr(exc)
    out["allreduce_busbw_GBps"] = busbw
    if busbw is not None:
        out["crossover_mb"] = busbw.get("crossover_mb")

    try:
        out["response_cache"] = bench_response_cache(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["response_cache"] = repr(exc)

    try:
        out["pipeline"] = bench_pipeline(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["pipeline"] = repr(exc)

    try:
        out["fast_lane_ab"] = bench_fast_lane(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["fast_lane_ab"] = repr(exc)

    try:
        out["monitor_ab"] = bench_monitor(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["monitor_ab"] = repr(exc)

    try:
        out["trace_ab"] = bench_trace(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["trace_ab"] = repr(exc)

    if os.environ.get("HVD_BENCH_SKIP_NEGOTIATION", "") != "1":
        try:
            sec = bench_negotiation_scaling(errors=errors)
            out["negotiation_scaling"] = sec
            if sec:
                out["flat_vs_hier"] = sec.get("flat_vs_hier")
                out["churn_survived"] = sec.get("churn_survived")
        except Exception as exc:  # noqa: BLE001 - contained
            errors["negotiation_scaling"] = repr(exc)

    try:
        out["autoscale"] = bench_autoscale(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["autoscale"] = repr(exc)

    try:
        out["serving"] = bench_serving(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["serving"] = repr(exc)

    try:
        out["serving_faults"] = bench_serving_faults(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["serving_faults"] = repr(exc)

    try:
        out["restore_ab"] = bench_restore_ab(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["restore_ab"] = repr(exc)

    try:
        out["sharded_ab"] = bench_sharded_ab(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["sharded_ab"] = repr(exc)

    try:
        out["fsdp_ab"] = bench_fsdp_ab(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["fsdp_ab"] = repr(exc)

    try:
        out["hierarchical_ab"] = bench_hierarchical_ab(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["hierarchical_ab"] = repr(exc)

    try:
        out["zero_rtt_ab"] = bench_zero_rtt(errors=errors)
    except Exception as exc:  # noqa: BLE001 - contained
        errors["zero_rtt_ab"] = repr(exc)

    if os.environ.get("HVD_BENCH_SKIP_AUTOTUNE", "") != "1":
        try:
            out["autotune"] = bench_autotune()
        except Exception as exc:  # noqa: BLE001 - contained
            errors["autotune"] = repr(exc)

    ips, mfu, overhead, raw_ips = bench_resnet(batch, steps, image, errors)

    # Optional per-chip batch sweep (diagnosing the batch-vs-throughput
    # curve, e.g. r03's batch-256 regression): framework path only, each
    # batch recorded with its own memory analysis in timing_evidence.
    sweep = os.environ.get("HVD_BENCH_BATCH_SWEEP", "")
    if sweep:
        world = max(1, hvd.size())
        out["batch_sweep"] = {}
        for tok in [s for s in sweep.split(",") if s]:
            try:
                pb = int(tok)  # inside the try: a bad token must not void
                gbatch = pb * world  # the already-measured headline value
                step_f, state_f, data_f = _resnet_pieces(gbatch, image,
                                                         framework=True)
                step_f, flops_f, mem_f = _compile_with_flops(step_f, state_f,
                                                             data_f)
                if mem_f:
                    _TIMING[f"resnet_memory_b{pb}"] = mem_f
                dt_f = _timed_steps(step_f, state_f, data_f, steps,
                                    f"resnet_sweep_b{pb}",
                                    global_batch=gbatch)
                rec = {"images_per_sec_per_chip":
                       round(gbatch * steps / dt_f / world, 2)}
                peak = _peak_flops()
                if flops_f and peak:
                    rec["mfu_pct"] = round(
                        100.0 * flops_f * steps / dt_f / peak, 2)
                out["batch_sweep"][str(pb)] = rec
            except Exception as exc:  # noqa: BLE001 - keep sweeping
                errors[f"batch_sweep_{tok}"] = repr(exc)

    world = max(1, hvd.size())
    per_chip_ips = round(ips / world, 2) if ips is not None else None
    if per_chip_ips is not None and raw_ips:
        vs = round(per_chip_ips / raw_ips, 3)
    elif mfu is not None:
        vs = round(mfu / 100.0, 3)
    else:
        vs = None  # no data ≠ "infinitely slow" (VERDICT r3 weak #7)
    out.update({
        "value": per_chip_ips,
        "vs_baseline": vs,
        "mfu_pct": mfu,
        "batch": batch, "steps": steps, "image": image,
        "framework_path": "hvd.init+DistributedOptimizer+SyncBN(shard_map)",
        "raw_xla_images_per_sec": raw_ips,
        "framework_overhead_pct": overhead,
    })


if __name__ == "__main__":
    main()
