"""Benchmark entry point: prints ONE JSON line for the driver.

Metric: ResNet-50 synthetic training throughput (images/sec/chip), the
canonical Horovod benchmark (reference:
``examples/pytorch/pytorch_synthetic_benchmark.py``, numbers in
``docs/benchmarks.rst`` — see BASELINE.md).

``vs_baseline`` compares against 219 images/sec — the per-GPU ResNet-50
throughput on the Pascal P100 hardware Horovod's published 90%-scaling
results were measured on (docs/benchmarks.rst-era TF benchmark; see
BASELINE.md provenance caveat: the mounted reference was empty, so this is
the upstream-published figure).

Env overrides: HVD_BENCH_BATCH, HVD_BENCH_STEPS, HVD_BENCH_IMAGE (size),
HVD_BENCH_MODEL=resnet50|llama.
"""

from __future__ import annotations

import json
import os
import sys
import time

HOROVOD_P100_RESNET50_IMG_PER_SEC = 219.0


def bench_resnet(batch: int, steps: int, image_size: int):
    import jax
    import jax.numpy as jnp
    import optax
    from horovod_tpu.models import resnet

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    cfg = resnet.ResNetConfig(
        depth=50, num_classes=1000,
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        sync_bn_axis=None)
    params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    step = jax.jit(resnet.make_train_step(cfg, opt, axis_name=None),
                   donate_argnums=(0, 1, 2))

    x, y = resnet.synthetic_batch(batch, image_size=image_size)
    x, y = jnp.asarray(x), jnp.asarray(y)

    # Warmup (compile) then timed steps.
    for _ in range(2):
        params, stats, opt_state, loss = step(params, stats, opt_state, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, stats, opt_state, loss = step(params, stats, opt_state, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return batch * steps / dt


def bench_llama(batch: int, steps: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=8192, d_model=512, n_layers=4,
                            n_heads=8, n_kv_heads=4, d_ff=1536, max_seq=512,
                            dtype=jnp.bfloat16, dp_axis=None, tp_axis=None,
                            sp_axis=None)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(llama.make_train_step(cfg, opt), donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    seq = 512
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt


def main():
    model = os.environ.get("HVD_BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("HVD_BENCH_BATCH", "32"))
    steps = int(os.environ.get("HVD_BENCH_STEPS", "8"))
    image = int(os.environ.get("HVD_BENCH_IMAGE", "224"))

    if model == "llama":
        tps = bench_llama(batch, steps)
        out = {"metric": "llama_tiny_train_tokens_per_sec_per_chip",
               "value": round(tps, 2), "unit": "tokens/sec",
               "vs_baseline": 0.0}
    else:
        ips = bench_resnet(batch, steps, image)
        out = {"metric": "resnet50_synthetic_images_per_sec_per_chip",
               "value": round(ips, 2), "unit": "images/sec",
               "vs_baseline": round(ips / HOROVOD_P100_RESNET50_IMG_PER_SEC,
                                    3)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
