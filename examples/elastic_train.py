"""Elastic (fault-tolerant, auto-scaling) training with horovod_tpu.

The rebuild of the reference's ``examples/elastic/pytorch/
pytorch_mnist_elastic.py``: the training loop lives inside a function
decorated with ``@hvd.elastic.run``; training state (params, optimizer
state, epoch counter) lives in a ``JaxState``.  When a host joins or is
lost (TPU preemption, scale-up), the wrapper catches the interruption,
re-initializes the runtime over the new world, restores/syncs the state,
and resumes from the last ``state.commit()`` — no job restart.

Run with a discovery script that prints one ``hostname:slots`` per line
(here: a file you can edit while the job runs to grow/shrink it)::

    echo "localhost:2" > /tmp/hosts
    torovodrun --host-discovery-script "cat /tmp/hosts" \
        --min-np 1 --max-np 4 python examples/elastic_train.py

On a TPU pod, ``--tpu-metadata-discovery`` instead polls the TPU metadata
endpoint for slice membership and preemption notices.
"""

import argparse

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.elastic import JaxState, run
from horovod_tpu.models import mnist


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--n-train", type=int, default=1024)
    return p.parse_args()


@run
def train(state, args, optimizer):
    """Runs under elastic protection: any rank failure or host-set change
    rolls back to the last commit and re-enters here with a fresh world."""
    images, labels = mnist.synthetic_batch(args.n_train)
    # Compiled fwd/bwd; the gradient averaging runs eagerly through the
    # engine so it always spans the CURRENT world.
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, x, y: mnist.loss_fn(p, x, y, axis_name=None)))
    apply_fn = jax.jit(optax.apply_updates)

    while state.epoch < args.epochs:
        rank, size = hvd.rank(), hvd.size()
        # Re-shard for the current world size every epoch: membership can
        # have changed since the last one.
        idx = hvd.data.shard_indices(args.n_train, shuffle=True,
                                     seed=state.epoch)
        losses = []
        for lo in range(0, len(idx), args.batch_size):
            sel = idx[lo:lo + args.batch_size]
            loss, grads = grad_fn(state.params, images[sel], labels[sel])
            grads = hvd.allreduce_gradients(grads)
            updates, state.opt_state = optimizer.update(
                grads, state.opt_state, state.params)
            state.params = apply_fn(state.params, updates)
            losses.append(loss)
        state.epoch += 1
        # Commit AFTER the epoch: cheap in-memory backup; also the point
        # where pending host updates raise HostsUpdatedInterrupt.
        state.commit()
        if rank == 0:
            print(f"epoch {state.epoch}: "
                  f"loss={float(np.mean(jax.device_get(losses))):.4f} "
                  f"world={size}", flush=True)


def main():
    args = parse_args()
    hvd.init()
    optimizer = optax.adam(args.lr)
    params = mnist.init_params(jax.random.PRNGKey(0))
    state = JaxState(params=params, opt_state=optimizer.init(params), epoch=0)
    train(state, args, optimizer)
    if hvd.rank() == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
