"""Data-parallel MNIST with the PyTorch binding.

The rebuild of the reference's ``examples/pytorch/pytorch_mnist.py``: torch
defines the model and optimizer; horovod_tpu provides the collectives
(gradient averaging rides the XLA/gloo data plane via the dlpack bridge).

Run::

    torovodrun -np 2 python examples/torch_mnist.py
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/torch_mnist.py --epochs 1
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 16, 3, padding=1)
        self.conv2 = nn.Conv2d(16, 32, 3, padding=1)
        self.fc1 = nn.Linear(32 * 7 * 7, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.conv1(x)), 2)
        x = F.max_pool2d(F.relu(self.conv2(x)), 2)
        x = x.flatten(1)
        return self.fc2(F.relu(self.fc1(x)))


def synthetic_mnist(n, seed):
    g = torch.Generator().manual_seed(seed)
    x = torch.rand(n, 1, 28, 28, generator=g)
    y = torch.randint(0, 10, (n,), generator=g)
    return torch.utils.data.TensorDataset(x, y)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=2048)
    p.add_argument("--seed", type=int, default=42)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    torch.manual_seed(args.seed)
    rank, size = hvd.rank(), hvd.size()

    dataset = synthetic_mnist(args.n_train, args.seed)
    # DistributedSampler shards the dataset across ranks; set_epoch below
    # reshuffles each epoch (reference: torch.utils.data.DistributedSampler).
    sampler = torch.utils.data.DistributedSampler(
        dataset, num_replicas=size, rank=rank)
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler)

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr * size,
                                momentum=0.5)
    # Gradient averaging hooks on every .grad as backward produces it.
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    # All ranks start from rank 0's weights and optimizer state.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        model.train()
        losses = []
        for x, y in loader:
            optimizer.zero_grad()
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            optimizer.step()
            losses.append(loss.item())
        # Metric averaging across ranks.
        mean_loss = hvd.allreduce(torch.tensor(np.mean(losses)),
                                  name="epoch_loss")
        if rank == 0:
            print(f"epoch {epoch}: loss={mean_loss.item():.4f} "
                  f"(world={size})", flush=True)

    if rank == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
