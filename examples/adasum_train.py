"""Training with Adasum gradient reduction.

The rebuild of the reference's ``examples/adasum/`` usage: pass
``op=hvd.Adasum`` and gradients are combined by adaptive summation —
projection-based merging that stays scale-stable as the world grows, so
the learning rate does NOT need the usual ``* hvd.size()`` scaling
(that's the point of Adasum).

On a power-of-two world the engine lowers Adasum to true
vector-halving-doubling over ``ppermute`` rounds ordered along the ICI
torus axes; other world sizes use the gather-based tree.  See
docs/adasum.md.

Run::

    torovodrun -np 2 python examples/adasum_train.py
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/adasum_train.py --epochs 1
"""

import argparse

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedBatchIterator
from horovod_tpu.models import mnist


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3,
                   help="NOT scaled by world size — Adasum handles scale")
    p.add_argument("--n-train", type=int, default=2048)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    images, labels = mnist.synthetic_batch(args.n_train)
    it = ShardedBatchIterator((images, labels), batch_size=args.batch_size,
                              shuffle=True)

    # No LR scaling: Adasum's combine is magnitude-aware.
    optimizer = hvd.DistributedOptimizer(optax.adam(args.lr), op=hvd.Adasum)
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt_state = optimizer.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, x, y: mnist.loss_fn(p, x, y, axis_name=None)))
    apply_fn = jax.jit(optax.apply_updates)

    for epoch in range(args.epochs):
        it.set_epoch(epoch)
        losses = []
        for x, y in it:
            loss, grads = grad_fn(params, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = apply_fn(params, updates)
            losses.append(loss)
        mean_loss = hvd.to_local(hvd.allreduce(
            np.mean(jax.device_get(losses)), name="epoch_loss"))
        if rank == 0:
            print(f"epoch {epoch}: loss={float(mean_loss):.4f} "
                  f"(world={size}, adasum)", flush=True)

    if rank == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
