"""Inference on the flagship Llama: blockwise prefill + KV-cache decode.

Beyond the reference (Horovod ships no inference path at all): the same
model that trains under dp×tp×sp×pp×ep serves tokens —

- **blockwise prefill**: the prompt runs through each layer ONCE with
  causal flash attention while the KV cache fills (matmul-shaped MXU
  work, not a per-token scan),
- **KV-cache decode**: one jitted step per token against the static-shape
  cache ring,
- **sampling**: greedy by default; ``--temperature/--top-p/--top-k``
  switch to nucleus/top-k sampling (rng folded per position),
- **tensor parallelism**: ``--tp N`` runs the whole generate loop inside
  ``shard_map`` — heads split over tp, psum at the output projection, the
  cache sharded over its kv-head axis (``llama.cache_specs``) — same
  Megatron contract as training.

Run::

    python examples/llama_generate.py --n-tokens 32
    python examples/llama_generate.py --tp 2 --temperature 0.8 --top-p 0.9

CPU smoke (8 virtual devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_generate.py --tiny --tp 2 --n-tokens 8
"""

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree for decode")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument("--n-tokens", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples")
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--n-draft", type=int, default=0,
                   help=">0 = greedy speculative decoding with this many "
                        "draft tokens per verify round (tp/sampling off)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tiny", action="store_true",
                   help="tiny config for smoke tests")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import jax.numpy as jnp
    from horovod_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.models import llama

    kw = dict(dp_axis=None, sp_axis=None,
              tp_axis="tp" if args.tp > 1 else None)
    if args.tiny:
        cfg = llama.tiny(n_heads=4, n_kv_heads=2, d_model=64, d_ff=128,
                         vocab_size=256, max_seq=128,
                         dtype=jnp.float32, **kw)
    else:
        cfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                n_heads=16, n_kv_heads=8, d_ff=4096,
                                max_seq=4096, dtype=jnp.bfloat16, **kw)

    params = llama.init_params(cfg, jax.random.PRNGKey(args.seed))
    rng = np.random.RandomState(args.seed)
    prompt = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (args.batch, args.prompt_len)),
        jnp.int32)
    # Always pass a key: with temperature 0 sample_logits ignores it, and
    # a non-None arg keeps the shard_map in_specs pytree uniform.
    sample_rng = jax.random.PRNGKey(args.seed + 1)
    budget = args.prompt_len + args.n_tokens

    if args.n_draft > 0:
        if args.tp > 1 or args.temperature > 0:
            raise SystemExit("--n-draft demo runs single-device greedy")
        # Self-speculation with an independently-initialized draft: the
        # output is still EXACTLY the target model's greedy decode — the
        # draft only changes how many target forwards are needed.
        draft = llama.init_params(cfg, jax.random.PRNGKey(args.seed + 7))
        gen = jax.jit(lambda p, t: llama.speculative_generate(
            p, draft, t, args.n_tokens, cfg, n_draft=args.n_draft))
        t0 = time.time()
        out = np.asarray(gen(params, prompt))
        wall = time.time() - t0
        print(f"generated [{args.batch}, {args.n_tokens}] tokens "
              f"speculative(n_draft={args.n_draft}) in {wall:.2f}s "
              f"(incl. compile)")
        print(out)
        print(f"DONE tokens={out.size}")
        return

    def run(p, t, r):
        return llama.generate(p, t, args.n_tokens, cfg, max_seq=budget,
                              temperature=args.temperature,
                              top_p=args.top_p, top_k=args.top_k, rng=r)

    if args.tp > 1:
        if len(jax.devices()) < args.tp:
            raise SystemExit(f"need {args.tp} devices, have "
                             f"{len(jax.devices())}")
        mesh = Mesh(np.asarray(jax.devices()[:args.tp]), ("tp",))
        pspecs = llama.param_specs(cfg)
        gen = jax.jit(shard_map(
            run, mesh=mesh,
            in_specs=(pspecs, P(None, None), P()),
            out_specs=P(None, None), check_vma=False))
    else:
        gen = jax.jit(run)

    t0 = time.time()
    out = np.asarray(gen(params, prompt, sample_rng))
    wall = time.time() - t0
    mode = (f"sampled(T={args.temperature}, top_p={args.top_p}, "
            f"top_k={args.top_k})" if args.temperature > 0 else "greedy")
    print(f"generated [{args.batch}, {args.n_tokens}] tokens, tp={args.tp} "
          f"{mode} in {wall:.2f}s (incl. compile)")
    print(out)
    print(f"DONE tokens={out.size}")


if __name__ == "__main__":
    main()
