"""Flagship: Llama decoder trained with dp x tp x sp x pp sharding, SPMD.

Beyond the reference's data-parallel examples — this is the TPU-first
path for models too big (or sequences too long) for pure DP: one process
drives the whole device mesh, the train step is a single jitted
``shard_map`` combining

- **dp** — batch sharding, gradient ``psum`` (what `hvd.allreduce` does),
- **tp** — Megatron-style tensor parallelism on attention/MLP blocks,
- **sp** — ring-attention sequence parallelism for long contexts
  (`horovod_tpu/parallel/ring_attention.py`),
- **pp** — GPipe pipeline stages: the layer stack is sharded into
  contiguous slabs over the pp axis and microbatches flow stage-to-stage
  over ICI ``ppermute`` (`horovod_tpu/parallel/pipeline.py`),

and XLA schedules every collective over ICI.  See
``horovod_tpu/models/llama.py`` for the layer shardings and
``horovod_tpu/parallel/spmd.py`` for the generic step builder.

Run on a TPU slice (uses all local chips)::

    python examples/llama_spmd.py --dp 2 --tp 2 --sp 2
    python examples/llama_spmd.py --dp 2 --pp 2 --tp 2 --micro 4

CPU smoke (8 virtual devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_spmd.py --dp 2 --tp 2 --sp 2 --steps 2 --tiny
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_spmd.py --dp 2 --pp 2 --steps 2 --tiny
"""

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dp", type=int, default=1, help="data-parallel degree")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel degree")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree (ring attention)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree (GPipe layer slabs)")
    p.add_argument("--micro", type=int, default=2,
                   help="microbatches per pipeline step (with --pp)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (with --experts)")
    p.add_argument("--experts", type=int, default=0,
                   help="MoE MLP with this many experts (0 = dense)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=0,
                   help="global batch (default 2*dp)")
    p.add_argument("--seq", type=int, default=0,
                   help="sequence length (default 128*sp)")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--tiny", action="store_true",
                   help="tiny config for smoke tests")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.models import llama
    from horovod_tpu.parallel import spmd
    from horovod_tpu.parallel.mesh import infer_mesh

    n = args.dp * args.tp * args.sp * args.pp * args.ep
    if len(jax.devices()) < n:
        raise SystemExit(f"need {n} devices for dp*tp*sp*pp*ep, "
                         f"have {len(jax.devices())}")
    mesh = infer_mesh(n, tp=args.tp, sp=args.sp, pp=args.pp, ep=args.ep,
                      devices=jax.devices()[:n])

    pp_kw = dict(pp_axis="pp" if args.pp > 1 else None,
                 n_microbatches=args.micro,
                 n_experts=args.experts,
                 ep_axis="ep" if args.ep > 1 else None)
    if args.tiny:
        cfg = llama.tiny(n_heads=4, n_kv_heads=2, d_model=64, d_ff=128,
                         vocab_size=256, **pp_kw)
    else:
        cfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                n_heads=16, n_kv_heads=8, d_ff=4096,
                                max_seq=4096, dtype=jnp.bfloat16, **pp_kw)
    if args.pp > 1 and cfg.n_layers % args.pp:
        raise SystemExit(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp={args.pp}")

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)

    # With pipeline stages, every stage sees the same batch shard (the
    # schedule moves activations across pp, not data); otherwise fold the
    # free pp axis into the batch axes.  ep is always a batch axis (MoE
    # experts shard over it, tokens data-split).
    batch_axes = ("dp", "ep") if args.pp > 1 else ("dp", "ep", "pp")
    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        data_spec=P(batch_axes, "sp"))
    params = spmd.shard_params(params, pspecs, mesh)

    micro = args.micro if args.pp > 1 else 1
    batch = args.batch or 2 * args.dp * args.ep * micro
    seq = args.seq or 128 * args.sp
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)

    # Warmup/compile, then timed steps.
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    t0 = time.time()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.time() - t0
    tok_s = batch * seq * args.steps / dt
    print(f"mesh=(dp={args.dp},tp={args.tp},sp={args.sp},pp={args.pp},"
          f"ep={args.ep}) experts={args.experts} batch={batch} seq={seq}")
    print(f"loss={float(jax.device_get(loss)):.4f} "
          f"throughput={tok_s:.0f} tok/s", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
