"""Data-parallel MNIST in JAX with horovod_tpu.

The canonical first program (reference: ``examples/pytorch/pytorch_mnist.py``
and ``examples/tensorflow2/tensorflow2_mnist.py``), written TPU-first:

  1. ``hvd.init()`` — build the mesh, start the collective engine.
  2. Shard the dataset by rank (``ShardedBatchIterator``).
  3. Scale the learning rate by ``hvd.size()``.
  4. Wrap the optax optimizer in ``hvd.DistributedOptimizer`` so every
     ``update`` averages gradients across ranks.
  5. ``hvd.broadcast_parameters`` once so all ranks start identical.

The forward/backward runs under ``jax.jit``; ``optimizer.update`` runs
eagerly so its gradient allreduce goes through the collective engine
(fused, device-resident — the reference's hook→background-thread path).
For peak TPU throughput, fuse the allreduce INTO the compiled step with a
``shard_map`` over the device mesh instead — see
``horovod_tpu.models.mnist.make_sharded_train_step`` and
``examples/resnet_synthetic.py``'s docstring note.

Run on a TPU pod (one process per chip)::

    torovodrun -np 4 python examples/mnist_jax.py

or on CPU for a smoke test::

    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/mnist_jax.py --epochs 1
"""

import argparse
import time

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedBatchIterator
from horovod_tpu.models import mnist


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--lr", type=float, default=1e-3,
                   help="base learning rate (scaled by world size)")
    p.add_argument("--n-train", type=int, default=4096,
                   help="synthetic training-set size")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Synthetic MNIST (the image has no dataset downloads); swap in real
    # MNIST arrays here — the sharding/training code is unchanged.
    images, labels = mnist.synthetic_batch(args.n_train, seed=args.seed)

    # Each rank sees a disjoint 1/size shard, reshuffled every epoch.
    it = ShardedBatchIterator((images, labels), batch_size=args.batch_size,
                              shuffle=True, seed=args.seed)

    # Horovod convention: scale LR by world size since the effective batch
    # is batch_size * size (reference: docs "Usage" step 3).
    optimizer = optax.adam(args.lr * size)
    optimizer = hvd.DistributedOptimizer(optimizer)

    params = mnist.init_params(jax.random.PRNGKey(args.seed))
    opt_state = optimizer.init(params)

    # One-time sync so all ranks start from rank 0's initialization.
    params = hvd.broadcast_parameters(params, root_rank=0)

    # Forward/backward is compiled; the distributed optimizer runs eagerly
    # so its allreduce rides the engine across processes.
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, x, y: mnist.loss_fn(p, x, y, axis_name=None)))
    apply_fn = jax.jit(optax.apply_updates)

    for epoch in range(args.epochs):
        it.set_epoch(epoch)
        t0, losses = time.time(), []
        for x, y in it:
            loss, grads = grad_fn(params, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = apply_fn(params, updates)
            losses.append(loss)
        # Average the epoch metric across ranks before reporting.
        mean_loss = hvd.to_local(hvd.allreduce(
            np.mean(jax.device_get(losses)), name="epoch_loss"))
        if rank == 0:
            print(f"epoch {epoch}: loss={float(mean_loss):.4f} "
                  f"({time.time() - t0:.1f}s, world={size})", flush=True)

    if rank == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
