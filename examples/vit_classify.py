"""ViT image classification under data parallelism (synthetic data).

The vision-transformer member of the models row: patch-embed + CLS over
the shared encoder blocks (``horovod_tpu/models/vit.py``), trained with
``hvd.DistributedOptimizer`` — gradients averaged across ranks every
update, the canonical Horovod usage pattern on a transformer classifier.

Run::

    torovodrun -np 4 python examples/vit_classify.py          # ViT-Base/16
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/vit_classify.py \
        --tiny --num-iters 2 --num-warmup 1
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import vit


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-rank batch size")
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--tiny", action="store_true",
                   help="tiny config for CPU smoke tests")
    p.add_argument("--fp32", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    dtype = jnp.float32 if args.fp32 or args.tiny else jnp.bfloat16
    cfg = (vit.tiny(dtype=dtype, dp_axis=None, tp_axis=None)
           if args.tiny else
           vit.ViTConfig(dtype=dtype, dp_axis=None, tp_axis=None))

    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    params = hvd.broadcast_parameters(params, root_rank=0)
    optimizer = hvd.DistributedOptimizer(optax.adam(args.lr * size))
    opt_state = optimizer.init(params)

    rng = np.random.RandomState(rank)
    images = jnp.asarray(rng.randn(args.batch_size, cfg.image_size,
                                   cfg.image_size, cfg.channels),
                         jnp.float32)
    labels = jnp.asarray(rng.randint(0, cfg.n_classes, args.batch_size),
                         jnp.int32)

    @jax.jit
    def grads_fn(params, images, labels):
        return jax.value_and_grad(vit.loss_fn)(params, images, labels, cfg)

    apply_fn = jax.jit(optax.apply_updates)

    def step(params, opt_state):
        loss, grads = grads_fn(params, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return apply_fn(params, updates), opt_state, loss

    for _ in range(args.num_warmup):
        params, opt_state, loss = step(params, opt_state)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(args.num_iters):
        params, opt_state, loss = step(params, opt_state)
    jax.block_until_ready(loss)
    dt = time.time() - t0

    img_per_sec = args.batch_size * args.num_iters / dt
    total = hvd.to_local(hvd.allreduce(np.float32(img_per_sec),
                                       name="imgs", op=hvd.Sum))
    if rank == 0:
        name = "tiny" if args.tiny else "ViT-Base/16"
        print(f"{name} batch={args.batch_size} world={size} "
              f"loss={float(hvd.to_local(loss)):.4f}")
        print(f"per-rank: {img_per_sec:.1f} img/s")
        print(f"total:    {float(total):.1f} img/s")
        print("DONE", flush=True)


if __name__ == "__main__":
    main()
