"""Synthetic ResNet throughput benchmark (img/s) under data parallelism.

The rebuild of the reference's headline benchmark
(``examples/pytorch/pytorch_synthetic_benchmark.py``): train ResNet on
random data and report per-rank and aggregate images/sec.

Two step modes:

- ``--step-mode eager`` (default; works in every launch mode): compiled
  forward/backward, eager ``DistributedOptimizer.update`` whose allreduce
  rides the collective engine — measures the same framework path a user's
  training loop exercises.
- ``--step-mode spmd`` (single-process, >=1 local devices): the whole step —
  gradients, ``psum`` allreduce, parameter update — is one jitted
  ``shard_map`` over the device mesh, the TPU-first fused path
  (``bench.py`` measures MFU with this mode on the real chip).

Run::

    torovodrun -np 4 python examples/resnet_synthetic.py --depth 50
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/resnet_synthetic.py \
        --depth 18 --image-size 32 --batch-size 4 --num-iters 2 --num-warmup 1
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import resnet


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--depth", type=int, default=50,
                   choices=sorted(resnet.BLOCKS),
                   help="ResNet depth (18/34/50/101/152)")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-rank batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--num-iters", type=int, default=10,
                   help="timed iterations")
    p.add_argument("--num-warmup", type=int, default=3,
                   help="untimed warmup iterations (includes compile)")
    p.add_argument("--step-mode", choices=("eager", "spmd"), default="eager")
    p.add_argument("--fp32", action="store_true",
                   help="compute in float32 instead of bfloat16")
    return p.parse_args()


def make_eager_step(cfg, optimizer):
    """Compiled fwd/bwd + eager distributed update (per-process mode)."""
    @jax.jit
    def grads_fn(params, stats, images, labels):
        def loss(p, s):
            return resnet.loss_fn(p, s, images, labels, cfg, axis_name=None)
        (l, stats), grads = jax.value_and_grad(loss, has_aux=True)(
            params, stats)
        return l, stats, grads

    apply_fn = jax.jit(optax.apply_updates)

    def step(params, stats, opt_state, images, labels):
        l, stats, grads = grads_fn(params, stats, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return apply_fn(params, updates), stats, opt_state, l

    return step


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    cfg = resnet.ResNetConfig(
        depth=args.depth, num_classes=args.num_classes,
        compute_dtype=jnp.float32 if args.fp32 else jnp.bfloat16,
        sync_bn_axis=None)
    params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
    params = hvd.broadcast_parameters(params, root_rank=0)

    optimizer = hvd.DistributedOptimizer(optax.sgd(0.01 * size, momentum=0.9))
    opt_state = optimizer.init(params)
    images, labels = resnet.synthetic_batch(
        args.batch_size, image_size=args.image_size,
        num_classes=args.num_classes, seed=rank)

    if args.step_mode == "spmd":
        # One jitted shard_map step over the local device mesh: allreduce is
        # an in-graph psum XLA schedules over ICI.
        step = resnet.make_sharded_train_step(cfg, optimizer, hvd.mesh())
    else:
        step = make_eager_step(cfg, optimizer)

    for _ in range(args.num_warmup):
        params, stats, opt_state, l = step(params, stats, opt_state,
                                           images, labels)
    jax.block_until_ready(l)

    t0 = time.time()
    for _ in range(args.num_iters):
        params, stats, opt_state, l = step(params, stats, opt_state,
                                           images, labels)
    jax.block_until_ready(l)
    dt = time.time() - t0

    img_per_sec = args.batch_size * args.num_iters / dt
    total = hvd.to_local(hvd.allreduce(np.float32(img_per_sec),
                                       name="imgs", op=hvd.Sum))
    if rank == 0:
        print(f"ResNet-{args.depth} batch={args.batch_size} world={size} "
              f"mode={args.step_mode}")
        print(f"per-rank: {img_per_sec:.1f} img/s")
        print(f"total:    {float(total):.1f} img/s", flush=True)
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
