"""DLRM-style model-parallel embedding exchange with ragged ``hvd.alltoall``.

The rebuild of the reference's recommender hot path (``hvd.alltoall`` with
``splits`` — the op DLRM-scale training adds on top of allreduce): the
embedding tables are sharded by hash across ranks, so every step each rank

  1. hashes its local batch's ids to their owner ranks,
  2. ships the id lists out with one ragged alltoall (uneven row counts!),
  3. looks up its own table shard for every id it received,
  4. ships the embedding rows back with a second ragged alltoall whose
     splits are the first exchange's ``received_splits``.

The dense MLP is ordinary data parallelism (``allreduce_gradients``).

Run::

    torovodrun -np 2 python examples/dlrm_alltoall.py
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/dlrm_alltoall.py --steps 2

The single-process SPMD variant of the same model (in-graph
``lax.all_to_all`` over an ``ep`` mesh axis) lives in
``horovod_tpu/models/dlrm.py``.
"""

import argparse

import numpy as np

import horovod_tpu as hvd


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--vocab", type=int, default=1000,
                   help="global embedding rows (hash-sharded across ranks)")
    p.add_argument("--dim", type=int, default=16, help="embedding dim")
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    rng = np.random.RandomState(rank)

    # This rank's table shard: rows whose id % size == rank.
    local_rows = (args.vocab + size - 1 - rank) // size
    table = rng.randn(local_rows, args.dim).astype(np.float32) * 0.01

    for step in range(args.steps):
        ids = rng.randint(0, args.vocab, size=(args.batch_size,))

        # Group this batch's ids by owner rank. Row counts per destination
        # are UNEVEN — that's what the ragged form exists for.
        owner = ids % size
        order = np.argsort(owner, kind="stable")
        send_ids, splits = ids[order], np.bincount(owner, minlength=size)

        # Exchange 1: id lists to their owners.
        recv_ids, recv_splits = hvd.alltoall(
            send_ids.astype(np.int32), splits=splits.astype(np.int32),
            name=f"ids.{step}")
        recv_ids = np.asarray(hvd.to_local(recv_ids))
        recv_splits = np.asarray(hvd.to_local(recv_splits))

        # Local lookup: global id -> local row of this rank's shard.
        rows = table[recv_ids // size]

        # Exchange 2: embedding rows back; the return splits are exactly
        # what we received, so each rank gets rows for its own batch.
        back, _ = hvd.alltoall(rows, splits=recv_splits,
                               name=f"emb.{step}")
        back = np.asarray(hvd.to_local(back))

        # Undo the owner-grouping permutation to restore batch order.
        emb = np.empty_like(back)
        emb[order] = back
        assert emb.shape == (args.batch_size, args.dim)

        if rank == 0:
            print(f"step {step}: exchanged "
                  f"{int(np.sum(splits))}->{int(np.sum(recv_splits))} ids, "
                  f"emb norm={np.linalg.norm(emb):.4f}", flush=True)

    if rank == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
