"""Bring-your-weights: HuggingFace GPT-2 -> horovod_tpu -> generate.

The switching story in one script: build (or load) a ``transformers``
``GPT2LMHeadModel``, convert its state dict with
``gpt2.from_hf_state_dict`` (no transposes — HF's Conv1D already stores
``[in, out]``), verify logits parity against the source model, then run
the KV-cache greedy decoder. With network access you would replace the
random-init model with ``GPT2LMHeadModel.from_pretrained("gpt2")`` and
the matching ``GPT2Config``; everything below is identical.

Run::

    JAX_PLATFORMS=cpu torovodrun -np 1 python examples/gpt2_import_generate.py
"""

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    import jax.numpy as jnp
    import torch
    from transformers import GPT2Config as HFGPT2Config
    from transformers import GPT2LMHeadModel

    from horovod_tpu.models import gpt2

    # Stand-in for GPT2LMHeadModel.from_pretrained("gpt2") (no network
    # in CI): a tiny random-init model with the same architecture.
    hf_cfg = HFGPT2Config(vocab_size=256, n_positions=64, n_embd=64,
                          n_layer=2, n_head=4,
                          resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    cfg = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.from_hf_state_dict(hf.state_dict(), cfg)

    prompt = np.random.RandomState(0).randint(0, 256, (2, 8))
    with torch.no_grad():
        ref = hf(torch.tensor(prompt)).logits.numpy()
    ours = np.asarray(gpt2.forward(params, jnp.asarray(prompt), cfg))
    dev = float(np.max(np.abs(ours - ref)))
    assert dev < 2e-4, dev

    toks = gpt2.generate(params, jnp.asarray(prompt, jnp.int32), 8, cfg)
    if hvd.rank() == 0:
        print(f"logits parity vs transformers: max|dev| = {dev:.2e}")
        print(f"generated continuation: {np.asarray(toks)[0].tolist()}")
        print("DONE", flush=True)


if __name__ == "__main__":
    main()
