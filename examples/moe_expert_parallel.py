"""Mixture-of-Experts training with expert parallelism over the ep axis.

Builds on the same alltoall exchange the reference's DLRM embedding
config uses (``hvd.alltoall`` — SURVEY.md §2c config #5), promoted to a
full sparse layer: Switch-style top-1 routing with static capacity,
experts sharded over ``ep``, dispatch/return riding ``lax.all_to_all``
over ICI inside one jitted shard_map step.

CPU smoke (8 virtual devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_expert_parallel.py --ep 4 --steps 3
"""

import argparse
import time


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ep", type=int, default=4, help="expert-parallel degree")
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch", type=int, default=0, help="default 4*world")
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--d-model", type=int, default=64)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.models import moe
    from horovod_tpu.parallel import spmd
    from horovod_tpu.parallel.mesh import infer_mesh

    n = len(jax.devices())
    if n % args.ep:
        raise SystemExit(f"{n} devices not divisible by ep={args.ep}")
    mesh = infer_mesh(n, ep=args.ep)
    cfg = moe.MoELMConfig(
        vocab_size=256, d_model=args.d_model, n_layers=2,
        moe=moe.MoEConfig(d_model=args.d_model, d_ff=4 * args.d_model,
                          n_experts=args.experts, ep_axis="ep"),
        dp_axis="dp")

    params = moe.lm_init(cfg, jax.random.PRNGKey(0))
    pspecs = moe.lm_param_specs(cfg)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    step = spmd.make_sharded_train_step(
        moe.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        data_spec=P(("dp", "pp", "sp", "tp", "ep")))
    params = spmd.shard_params(params, pspecs, mesh)

    batch = args.batch or 4 * n
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, args.seq)),
                         jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, args.seq)),
                          jnp.int32)

    params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.time() - t0
    print(f"mesh=(dp={mesh.shape['dp']},ep={args.ep}) experts={args.experts} "
          f"batch={batch}")
    print(f"loss={float(jax.device_get(loss)):.4f} "
          f"throughput={batch * args.seq * args.steps / dt:.0f} tok/s")
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
