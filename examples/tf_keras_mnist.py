"""Data-parallel Keras MNIST with the TensorFlow binding.

The rebuild of the reference's ``examples/keras/keras_mnist.py`` /
``examples/tensorflow2/tensorflow2_keras_mnist.py``: a stock
``model.compile``/``model.fit`` loop made distributed by

  1. wrapping the optimizer in ``hvd.DistributedOptimizer``,
  2. the ``BroadcastGlobalVariablesCallback`` (initial weight sync),
  3. the ``MetricAverageCallback`` (cross-rank epoch metrics),
  4. an ``LearningRateWarmupCallback`` that ramps the LR from ``--lr`` up
     to ``--lr * hvd.size()`` — the large-batch recipe; the callback does
     the world-size scaling itself.

Run::

    torovodrun -np 2 python examples/tf_keras_mnist.py
    JAX_PLATFORMS=cpu torovodrun -np 2 python examples/tf_keras_mnist.py --epochs 1
"""

import argparse
import os

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np
import keras

import horovod_tpu.keras as hvd
from horovod_tpu.data import shard_indices
from horovod_tpu.keras import callbacks as hvd_callbacks


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--n-train", type=int, default=2048)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Synthetic MNIST, sharded by rank.  shard_indices guarantees EQUAL
    # per-rank sample counts, which keeps the per-batch gradient allreduce
    # in lockstep across ranks.
    rng = np.random.RandomState(0)
    x = rng.rand(args.n_train, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(args.n_train,))
    idx = shard_indices(args.n_train)
    x, y = x[idx], y[idx]

    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(16, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    opt = keras.optimizers.Adam(learning_rate=args.lr)
    opt = hvd.DistributedOptimizer(opt)
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    callbacks = [
        hvd_callbacks.BroadcastGlobalVariablesCallback(root_rank=0),
        hvd_callbacks.MetricAverageCallback(),
        # Ramps lr -> lr * size over the first epoch (the callback applies
        # the size scaling; don't also scale the optimizer's LR).
        hvd_callbacks.LearningRateWarmupCallback(
            initial_lr=args.lr, warmup_epochs=1,
            momentum_correction=False, verbose=0),
    ]

    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks,
              verbose=1 if rank == 0 else 0)  # only rank 0 prints

    if rank == 0:
        print("DONE", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
