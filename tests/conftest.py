"""Test harness: hermetic 8-virtual-device CPU mesh.

Mirrors the reference's hermetic test tier (SURVEY.md §4): where the
reference uses multi-process Gloo on localhost as the no-cluster backend, we
use JAX's virtual CPU devices (``--xla_force_host_platform_device_count=8``)
so the full enqueue → negotiate → fuse → XLA-collective path runs with 8
ranks in one process.  Must be set before jax imports anywhere.
"""

import os
import sys

# Overwrite, not setdefault: the TPU environment pins JAX_PLATFORMS=axon and
# tests must run hermetically on virtual CPU devices regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

if "jax" in sys.modules:  # pragma: no cover - belt and braces
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    # Engines/timelines are cheap; keep runtime initialized across tests for
    # speed (matching how real training uses one init per process).


@pytest.fixture(scope="session")
def world_size():
    import jax
    return jax.device_count()


@pytest.fixture()
def sim_slices():
    """The N-slice in-process harness (tests/slice_harness.py): a context
    manager arming an engine's two-level mode over a simulated N×L split
    of the 8-device CPU mesh, restoring every knob on exit."""
    from slice_harness import simulated_slices
    return simulated_slices
