"""Two-level ICI/DCN allreduce (ISSUE 17): topology derivation + engine
data-plane tests on the in-process 8-device CPU mesh.

The multi-process acceptance (real DCN hop) lives in
``test_multiprocess.py::test_torovodrun_hier_parity`` / ``worker_hier``;
here the ``sim_slices`` harness splits the single-process mesh into
simulated slices, which exercises the identical fused program builders,
cache keys and decision logic with fast turnaround.
"""

import numpy as np
import pytest

from horovod_tpu.parallel.topology import (cross_fraction, hier_bit_orders,
                                           modeled_leg_bytes,
                                           parse_slice_map, slice_topology)


# --------------------------------------------------------------- topology
def test_parse_slice_map_uniform_and_explicit():
    assert parse_slice_map("4", 8) == (0, 0, 0, 0, 1, 1, 1, 1)
    assert parse_slice_map("2,2,2,2", 8) == (0, 0, 1, 1, 2, 2, 3, 3)
    assert parse_slice_map("", 8) is None
    # non-divisor, non-uniform, wrong sum, garbage — all loud failures
    for bad in ("0", "3", "4,5", "4,4,4", "2,2,4", "x", "-2"):
        with pytest.raises(ValueError):
            parse_slice_map(bad, 8)


def test_slice_topology_from_knobs():
    st = slice_topology(None, world=8, slice_map="4")
    assert st.num_slices == 2 and st.local_size == 4
    assert st.ranks_of_slice(0) == [0, 1, 2, 3]
    assert st.ranks_of_slice(1) == [4, 5, 6, 7]
    assert st.leaders == (0, 4)
    # local_size knob and uniform per-process counts derive the same split
    assert slice_topology(None, world=8, local_size=4).leaders == (0, 4)
    assert slice_topology(None, world=8,
                          local_counts=[4, 4]).num_slices == 2
    # no derivable split (or a world too small for two levels) → flat
    assert slice_topology(None, world=8) is None
    assert slice_topology(None, world=2, slice_map="1") is None
    with pytest.raises(ValueError):
        slice_topology(None, world=8, slice_map="5")


def test_cross_ring_order_follows_coords():
    class D:  # simulated TPU device attributes
        def __init__(self, i, slice_index, coords):
            self.id = i
            self.slice_index = slice_index
            self.coords = coords
            self.core_on_chip = 0
            self.platform = "tpu"

    # Leader coords deliberately out of slice-id order: the DCN ring must
    # visit slices in physical-neighbor order (0,0,0) < (2,0,0) < (4,0,0)
    # → slice order 0, 2, 1.
    devs = [D(0, 0, (0, 0, 0)), D(1, 0, (1, 0, 0)),
            D(2, 1, (4, 0, 0)), D(3, 1, (5, 0, 0)),
            D(4, 2, (2, 0, 0)), D(5, 2, (3, 0, 0))]
    st = slice_topology(devs, world=6)
    assert st.num_slices == 3 and st.local_size == 2
    assert st.leaders == (0, 2, 4)
    assert st.cross_order == (0, 2, 1)
    assert st.leader_set_ranks() == [0, 4, 2]


def test_hier_bit_orders_power_of_two_only():
    lb, cb = hier_bit_orders(4, 2)
    assert lb == [0, 1] and cb == [0]
    assert hier_bit_orders(3, 2) is None
    assert hier_bit_orders(4, 3) is None
    assert hier_bit_orders(1, 8) is None     # one-rank slices are flat
    assert hier_bit_orders(8, 4) == ([0, 1, 2], [0, 1])


def test_modeled_leg_bytes_ratio():
    m = modeled_leg_bytes(1 << 20, world=8, local_size=4)
    # flat ring 2n(W-1)/W; cross leg 2(n/L)(C-1)/C ≤ flat/local_size
    assert m["flat"] == pytest.approx(2 * (1 << 20) * 7 / 8)
    assert m["cross"] == pytest.approx(2 * (1 << 20) / 4 / 2)
    assert m["cross"] <= m["flat"] / 4
    frac = cross_fraction(1 << 20, world=8, local_size=4)
    assert 0.0 < frac < 1.0
    assert frac == pytest.approx(m["cross"] / (m["cross"] + m["intra"]))


# ------------------------------------------------------------- data plane
def _int_stacked(hvd, world, shape=(16,), dtype=np.float32, seed=0):
    """Integer-valued per-rank payloads: every reduction order produces
    the same bits, so flat-vs-hier comparisons can demand equality."""
    rng = np.random.RandomState(seed)
    return hvd.stack_per_rank(
        [rng.randint(-3, 4, size=shape).astype(dtype) for _ in range(world)])


def _engine():
    import horovod_tpu.ops.eager as eager
    return eager._engine()


@pytest.mark.parametrize("opname", ["Sum", "Average", "Min", "Max",
                                    "Adasum"])
def test_hier_bitwise_parity(hvd, world_size, sim_slices, opname):
    """Flat and two-level dispatch agree BITWISE for every supported op
    on integer-valued fp32 payloads over 2 simulated slices."""
    eng = _engine()
    op = getattr(hvd, opname)
    x = _int_stacked(hvd, world_size, shape=(33,), seed=hash(opname) % 100)
    flat = np.asarray(hvd.allreduce(x, name=f"hp_{opname}_f", op=op))
    with sim_slices(eng, 2, world_size // 2):
        d0 = eng.hier_dispatches
        hier = np.asarray(hvd.allreduce(x, name=f"hp_{opname}_h", op=op))
        assert eng.hier_dispatches == d0 + 1, "two-level path did not run"
    np.testing.assert_array_equal(flat, hier)


def test_hier_mixed_group_and_bf16(hvd, world_size, sim_slices):
    """A fused mixed-dtype group (fp32 + bf16 + scalar-ish small tensor)
    rides ONE two-level dispatch with flat-identical bits."""
    import jax.numpy as jnp
    eng = _engine()
    a = _int_stacked(hvd, world_size, shape=(257,), seed=3)
    b = hvd.stack_per_rank(
        [np.full((2, 2), float(r - 1), np.float32).astype(jnp.bfloat16)
         for r in range(world_size)])
    c = _int_stacked(hvd, world_size, shape=(1,), seed=4)
    flat = [np.asarray(o, np.float32) for o in hvd.grouped_allreduce(
        [a, b, c], name="hg_f", op=hvd.Sum)]
    with sim_slices(eng, 2, world_size // 2):
        d0 = eng.hier_dispatches
        hier = [np.asarray(o, np.float32) for o in hvd.grouped_allreduce(
            [a, b, c], name="hg_h", op=hvd.Sum)]
        assert eng.hier_dispatches == d0 + 1
        assert eng.hier_intra_legs >= 2 and eng.hier_cross_legs >= 1
    for f, h in zip(flat, hier):
        np.testing.assert_array_equal(f, h)


def test_hier_threshold_crossover(hvd, world_size, sim_slices):
    """Payloads under HOROVOD_HIER_THRESHOLD dispatch flat; the per-call
    ``hierarchical=True`` override wins over the threshold."""
    eng = _engine()
    small = _int_stacked(hvd, world_size, shape=(8,), seed=5)
    with sim_slices(eng, 2, world_size // 2, threshold=1 << 20):
        d0 = eng.hier_dispatches
        hvd.allreduce(small, name="ht_small", op=hvd.Sum)
        assert eng.hier_dispatches == d0, "sub-threshold batch went hier"
        hvd.allreduce(small, name="ht_forced", op=hvd.Sum,
                      hierarchical=True)
        assert eng.hier_dispatches == d0 + 1, "override did not force hier"
    # knob restored + topology cache cleared by the harness
    assert eng.hier_threshold_bytes != 1 << 20 or not eng._slice_topos


def test_hier_decision_rekeys_program_cache(hvd, world_size, sim_slices):
    """The flat-vs-hier decision is a fusion-key/cache-key input: the
    same (shape, dtype, op) compiles one program per mode and neither is
    cross-served (a hier program run flat would change the wire
    schedule silently)."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(64,), seed=6)
    hvd.allreduce(x, name="hk", op=hvd.Sum)           # flat program
    misses0 = eng.cache.misses
    with sim_slices(eng, 2, world_size // 2):
        hvd.allreduce(x, name="hk", op=hvd.Sum)       # hier program
        assert eng.cache.misses == misses0 + 1
        hvd.allreduce(x, name="hk", op=hvd.Sum)       # warm hier hit
        assert eng.cache.misses == misses0 + 1
    hvd.allreduce(x, name="hk", op=hvd.Sum)           # flat again: warm
    assert eng.cache.misses == misses0 + 1


def test_hier_explicit_false_pins_flat(hvd, world_size, sim_slices):
    """``hierarchical=False`` pins a batch flat even with the mode armed
    and the payload over threshold."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(128,), seed=7)
    with sim_slices(eng, 2, world_size // 2):
        d0 = eng.hier_dispatches
        hvd.allreduce(x, name="hx", op=hvd.Sum, hierarchical=False)
        assert eng.hier_dispatches == d0


# ---------------------------------------------------- two-level allgather
def test_hier_allgather_bitwise_parity(hvd, world_size, sim_slices):
    """Flat and two-level allgather agree BITWISE (ISSUE 18 satellite:
    allgather is pure data movement — intra-slice gather after the
    cross-DCN leader exchange reassembles the identical [world, *S]
    result, no arithmetic to drift)."""
    eng = _engine()
    rng = np.random.RandomState(11)
    xs = [hvd.stack_per_rank(
        [rng.randn(*shape).astype(np.float32) + r
         for r in range(world_size)])
        for shape in ((33,), (4, 5))]
    flat = [np.asarray(o) for o in hvd.grouped_allgather(xs, name="hag_f")]
    with sim_slices(eng, 2, world_size // 2):
        eng.hierarchical_allgather = True
        try:
            d0, i0, c0 = (eng.hier_ag_dispatches, eng.hier_ag_intra_legs,
                          eng.hier_ag_cross_legs)
            hier = [np.asarray(o) for o in hvd.grouped_allgather(
                xs, name="hag_h")]
            assert eng.hier_ag_dispatches == d0 + 1, \
                "two-level allgather did not run"
            assert eng.hier_ag_intra_legs == i0 + 1
            assert eng.hier_ag_cross_legs == c0 + 1
        finally:
            eng.hierarchical_allgather = False
    for f, h in zip(flat, hier):
        np.testing.assert_array_equal(f, h)


def test_hier_allgather_knob_off_stays_flat(hvd, world_size, sim_slices):
    """With slices derivable but HOROVOD_HIERARCHICAL_ALLGATHER unset,
    allgather dispatches FLAT (the knob was a documented no-op before
    ISSUE 18; now it is the real gate) — and the per-call
    ``hierarchical=True`` override on the async API wins over it."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(16,), seed=21)
    with sim_slices(eng, 2, world_size // 2):
        assert eng.hierarchical_allgather is False
        d0 = eng.hier_ag_dispatches
        hvd.allgather(x, name="hag_off")
        assert eng.hier_ag_dispatches == d0, "knob off but AG went hier"


def test_hier_allgather_rekeys_program_cache(hvd, world_size, sim_slices):
    """The flat-vs-hier allgather decision keys the program cache: one
    program per mode for the same shapes, neither cross-served."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(64,), seed=22)
    hvd.allgather(x, name="hagk")                     # flat program
    misses0 = eng.cache.misses
    with sim_slices(eng, 2, world_size // 2):
        eng.hierarchical_allgather = True
        try:
            hvd.allgather(x, name="hagk")             # hier program
            assert eng.cache.misses == misses0 + 1
            hvd.allgather(x, name="hagk")             # warm hier hit
            assert eng.cache.misses == misses0 + 1
        finally:
            eng.hierarchical_allgather = False
    hvd.allgather(x, name="hagk")                     # flat again: warm
    assert eng.cache.misses == misses0 + 1


# ---------------------------------------------------- two-level broadcast
def test_hier_broadcast_bitwise_parity(hvd, world_size, sim_slices):
    """Flat and two-level broadcast agree BITWISE (ISSUE 19 satellite:
    broadcast is pure data movement — the cross-DCN leader exchange then
    intra-slice fan-out only ever sums the payload with zeros, so every
    dtype lands identical bits)."""
    eng = _engine()
    rng = np.random.RandomState(13)
    xs = [hvd.stack_per_rank(
        [rng.randn(*shape).astype(np.float32) * (r + 1)
         for r in range(world_size)])
        for shape in ((33,), (4, 5))]
    flat = [np.asarray(hvd.broadcast(x, root_rank=1, name=f"hbc_f{i}"))
            for i, x in enumerate(xs)]
    with sim_slices(eng, 2, world_size // 2):
        eng.hierarchical_broadcast = True
        try:
            d0, i0, c0 = (eng.hier_bcast_dispatches,
                          eng.hier_bcast_intra_legs,
                          eng.hier_bcast_cross_legs)
            hier = [np.asarray(hvd.broadcast(x, root_rank=1,
                                             name=f"hbc_h{i}"))
                    for i, x in enumerate(xs)]
            assert eng.hier_bcast_dispatches == d0 + 2, \
                "two-level broadcast did not run"
            assert eng.hier_bcast_intra_legs == i0 + 2
            assert eng.hier_bcast_cross_legs == c0 + 2
        finally:
            eng.hierarchical_broadcast = False
    for f, h in zip(flat, hier):
        np.testing.assert_array_equal(f, h)


def test_hier_broadcast_cross_slice_root(hvd, world_size, sim_slices):
    """A root living in the SECOND slice (cross index 1) fans out
    correctly — the leader-exchange leg is root-relative, not
    slice-0-relative — and bools survive the int32 psum round-trip."""
    eng = _engine()
    root = world_size // 2 + 1                        # inside slice 1
    vals = hvd.stack_per_rank(
        [np.array([r, -r, 7 * r], np.int32) for r in range(world_size)])
    flags = hvd.stack_per_rank(
        [np.array([r % 2 == 0, r == root], bool)
         for r in range(world_size)])
    with sim_slices(eng, 2, world_size // 2):
        eng.hierarchical_broadcast = True
        try:
            d0 = eng.hier_bcast_dispatches
            out_v = np.asarray(hvd.broadcast(vals, root_rank=root,
                                             name="hbc_xr_v"))
            out_f = np.asarray(hvd.broadcast(flags, root_rank=root,
                                             name="hbc_xr_f"))
            assert eng.hier_bcast_dispatches == d0 + 2
        finally:
            eng.hierarchical_broadcast = False
    np.testing.assert_array_equal(
        out_v.reshape(-1)[-3:], np.array([root, -root, 7 * root], np.int32))
    np.testing.assert_array_equal(
        out_f.reshape(-1)[-2:], np.array([root % 2 == 0, True]))


def test_hier_broadcast_knob_off_stays_flat(hvd, world_size, sim_slices):
    """With slices derivable but HOROVOD_HIERARCHICAL_BROADCAST unset,
    broadcast dispatches FLAT."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(16,), seed=24)
    with sim_slices(eng, 2, world_size // 2):
        assert eng.hierarchical_broadcast is False
        d0 = eng.hier_bcast_dispatches
        hvd.broadcast(x, root_rank=0, name="hbc_off")
        assert eng.hier_bcast_dispatches == d0, "knob off but bcast hier"


def test_hier_broadcast_rekeys_program_cache(hvd, world_size, sim_slices):
    """The flat-vs-hier broadcast decision keys the program cache: one
    program per mode for the same shapes, neither cross-served, and the
    knob flip itself costs zero control-plane bytes (fusion-key-only,
    same contract the allreduce/allgather verdicts pinned)."""
    eng = _engine()
    x = _int_stacked(hvd, world_size, shape=(64,), seed=25)
    hvd.broadcast(x, root_rank=0, name="hbck")        # flat program
    misses0 = eng.cache.misses
    with sim_slices(eng, 2, world_size // 2):
        eng.hierarchical_broadcast = True
        try:
            hvd.broadcast(x, root_rank=0, name="hbck")  # hier program
            assert eng.cache.misses == misses0 + 1
            hvd.broadcast(x, root_rank=0, name="hbck")  # warm hier hit
            assert eng.cache.misses == misses0 + 1
        finally:
            eng.hierarchical_broadcast = False
    hvd.broadcast(x, root_rank=0, name="hbck")        # flat again: warm
    assert eng.cache.misses == misses0 + 1


# ------------------------------------------------- non-uniform slice map
def test_nonuniform_slice_map_falls_back_once(hvd):
    """A non-uniform HOROVOD_SLICE_MAP must not silently disable the
    two-level path: the engine logs ONE attributed warning naming the
    offending sizes, bumps ``slice_map_fallbacks`` once (the probe is
    cached per process set), and every collective dispatches flat."""
    import logging

    from horovod_tpu.utils.logging import get_logger
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture(level=logging.WARNING)
    get_logger().addHandler(handler)      # propagate=False: attach direct
    eng = _engine()
    saved = (eng.hierarchical_allreduce, eng.slice_map,
             eng.hier_threshold_bytes)
    eng.hierarchical_allreduce = True
    eng.slice_map = "2,6"                 # sums to 8, non-uniform
    eng.hier_threshold_bytes = 0
    eng._slice_topos.clear()
    f0 = eng.slice_map_fallbacks
    try:
        assert eng._slice_topology(0) is None
        assert eng._slice_topology(0) is None         # cached: no re-probe
        assert eng.slice_map_fallbacks == f0 + 1
        warns = [r for r in records
                 if "HOROVOD_SLICE_MAP rejected" in r.getMessage()]
        assert len(warns) == 1, [r.getMessage() for r in warns]
        assert "[2, 6]" in warns[0].getMessage()      # names the sizes
        d0 = eng.hier_dispatches
        x = _int_stacked(hvd, 8, shape=(32,), seed=23)
        out = np.asarray(hvd.allreduce(x, name="numap", op=hvd.Sum))
        assert eng.hier_dispatches == d0, "fallback world dispatched hier"
        np.testing.assert_array_equal(
            out, np.asarray(x).sum(axis=0).astype(np.float32))
    finally:
        (eng.hierarchical_allreduce, eng.slice_map,
         eng.hier_threshold_bytes) = saved
        eng._slice_topos.clear()
        get_logger().removeHandler(handler)
