"""bench.py smoke tier: the driver runs bench.py at end of round and its
ONE JSON line is the round's perf record — two rounds died to bench
breakage before this guard existed.  Runs every mode on the CPU mesh with
tiny sizes and asserts the line parses with the expected fields."""

import json
import os
import subprocess
import sys

import pytest

# Integration tier: real subprocess launches (see pyproject markers);
# the fast hermetic tier excludes these with `-m 'not slow'`.
pytestmark = pytest.mark.slow

from test_examples import _example_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

_WATCHDOG_S = 600


def _run_bench(extra_env):
    env = _example_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        HVD_BENCH_TIMEOUT_S=str(_WATCHDOG_S), **extra_env)
    # Outer timeout strictly above the internal watchdog so a wedge emits
    # the watchdog's diagnostic JSON instead of an opaque TimeoutExpired.
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, text=True,
                       timeout=_WATCHDOG_S + 120)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout[-2000:]
    return json.loads(lines[0])


def test_bench_minimal_mode():
    out = _run_bench({"HVD_BENCH_MINIMAL": "1",
                      "HVD_BENCH_SIZES_MB": "0.125,1"})
    assert out["metric"] == "allreduce_engine_busbw_GBps"
    assert out["value"] and out["value"] > 0
    assert out["errors"] == {}
    assert out["world"] == 8
    # Trace A/B on every line: the armed window's phase breakdown must
    # partition the measured lifecycle (queue+negotiation+copy_in+reduce+
    # drain re-adds to cycle_us), and the overhead bound is recorded.
    ab = out["trace_ab"]
    assert set(ab["phases_us"]) == {"queue", "negotiation", "copy_in",
                                    "reduce", "drain"}
    assert ab["spans"] > 0 and ab["cycle_us"] > 0
    assert ab["phase_sum_consistent"] is True, ab
    assert "within_noise" in ab and "overhead_pct" in ab
    # Latency fast lane A/B on every line: both lanes bitwise-identical,
    # the lane + pinned-program path actually engaged, and the per-lane
    # phase breakdown carries the copy_in+drain evidence.
    fl = out["fast_lane_ab"]
    assert fl["bitwise_identical"] is True, fl
    assert fl["fast_lane_dispatches"] > 0 and fl["pin_hits"] > 0, fl
    assert "copy_in_drain_us_on" in fl and "within_noise" in fl, fl
    # crossover_mb rides every JSON line (null in engine-only sweeps),
    # and the busbw sweep scales iterations toward the wall target: the
    # small 128KB point is fast enough on the CPU mesh that a ≥200ms wall
    # needs strictly MORE than the 10-iteration floor (a probe-timing
    # regression that always returns the floor fails here).
    iters = out["allreduce_busbw_GBps"]["iters"]
    assert iters["1MB"] >= 10
    assert iters["0.125MB"] > 10, iters
    # Control-plane scale-out section (ISSUE 9) on every line: simulated
    # worlds through the real native server, flat vs hierarchical, with
    # the root-service scoreboard mirrored to the top-level flat_vs_hier.
    ns = out["negotiation_scaling"]
    assert set(ns["sizes"]) == {"8", "32", "128"}, ns
    for rec in ns["sizes"].values():
        assert rec["flat_root_us"] > 0 and rec["hier_root_us"] > 0, rec
        assert rec["flat_round_us"] > 0 and rec["hier_round_us"] > 0, rec
    assert out["flat_vs_hier"] == ns["flat_vs_hier"], (
        out["flat_vs_hier"], ns["flat_vs_hier"])
    # The tentpole's claim, measurable even on this shared box: at the
    # largest world the flat root does multiples of the hierarchical
    # root's serialized per-round work (128 connections vs 8).
    assert ns["sizes"]["128"]["flat_vs_hier"] > 1.5, ns
    # ISSUE 12: the sweep now injects churn MID-RUN (a preemption-notice
    # drain -> clean LEAVEs, the drained host's agent dying, a join
    # epoch) in BOTH planes — every world must survive it (no abort, all
    # departures clean), the verdict is mirrored onto the top-level line,
    # and the hierarchical root's slope stays ~flat THROUGH the churn
    # (post-churn phases measured separately).
    assert ns["churn_survived"] is True, ns
    assert out["churn_survived"] is True, out["churn_survived"]
    for rec in ns["sizes"].values():
        assert rec["churn_survived"] is True, rec
        assert rec["hier_root_us_post_churn"] > 0, rec
    assert ns["hier_slope_post"] is not None, ns
    # Generous bound for a shared noisy box; the real evidence rides the
    # recorded slope values (hier ~1x while flat tracks the world size).
    assert ns["hier_slope"] < ns["flat_slope"], ns
    # Autoscale section (ISSUE 10) on every line: policy decision latency
    # plus the clean-LEAVE drain round-trip through a real native server —
    # the survivor must actually OBSERVE the leave notice.
    asc = out["autoscale"]
    assert asc["decision_us"] > 0, asc
    assert asc["leave_sent"] is True, asc
    assert asc["left_observed"] is True, asc
    assert asc["drain_roundtrip_us"] > 0, asc
    # Restore A/B (ISSUE 14) on every line: disk-vs-peer recovery wall
    # time over the real state plane — both paths restore the identical
    # blob, and the peer path never opens a checkpoint file.  (No
    # which-is-faster assertion: on a local tmpfs the disk path can win;
    # the production claim is about remote/networked checkpoint storage.)
    rab = out["restore_ab"]
    assert rab["disk_restore_us"] > 0 and rab["peer_restore_us"] > 0, rab
    assert rab["bitwise_identical"] is True, rab
    assert rab["peer_disk_reads"] == 0, rab
    assert rab["peer_shards_fetched"] == rab["world"], rab
    # Sharded-optimizer A/B (ISSUE 15) on every line: optimizer-state
    # bytes/rank scale ~1/N (asserted by the section itself), the
    # sharded pipeline's modeled wire bytes sit strictly below the
    # allreduce-based sharded baseline, and both paths converge on the
    # same parameters.
    sab = out["sharded_ab"]
    assert sab["world"] == 8, sab
    assert sab["one_over_n"] is True, sab
    assert sab["opt_state_bytes_per_rank"] < \
        sab["opt_state_bytes_per_rank_replicated"] / 4, sab
    assert sab["wire_bytes_per_step_sharded"] < \
        sab["wire_bytes_per_step_allreduce"], sab
    assert sab["params_match"] is True, sab
    assert sab["step_ms_sharded"] > 0 and sab["step_ms_replicated"] > 0, sab
    # FSDP A/B (ISSUE 18) on every line: full parameter sharding keeps
    # resident params + opt state ≈ 1/N of the replicated total
    # (asserted by the section), its modeled wire bytes equal the ZeRO-1
    # pipeline's (full sharding is a memory win at equal wire), and the
    # gathered parameters match the replicated run.
    fab = out["fsdp_ab"]
    assert fab["world"] == 8, fab
    assert fab["one_over_n"] is True, fab
    assert fab["resident_bytes_full"] < \
        fab["resident_bytes_replicated"] / 4, fab
    assert fab["resident_bytes_full"] < fab["resident_bytes_sharded"], fab
    assert fab["wire_full_eq_sharded"] is True, fab
    assert fab["wire_bytes_per_step_full"] < \
        fab["wire_bytes_per_step_allreduce"], fab
    assert fab["params_match"] is True, fab
    assert fab["step_ms_full"] > 0 and fab["step_ms_replicated"] > 0, fab
    # Two-level allreduce A/B (ISSUE 17) on every line: flat-vs-hier
    # bitwise identity on integer payloads, the leg counters proving the
    # two-level path ran, the modeled cross-slice (DCN) wire bytes ≤
    # ~1/local_size of the flat ring's, and the crossover_mb key present
    # (null is legitimate: on a CPU mesh the three-launch pipeline
    # usually never beats one flat launch).
    hab = out["hierarchical_ab"]
    assert hab["world"] == 8 and hab["local_size"] == 4, hab
    assert hab["bitwise_identical"] is True, hab
    assert hab["hier_dispatches"] > 0, hab
    assert hab["hier_intra_legs"] == 2 * hab["hier_dispatches"], hab
    assert hab["hier_cross_legs"] == hab["hier_dispatches"], hab
    assert "crossover_mb" in hab, hab
    for rec in hab["sizes"]:
        assert rec["bitwise_identical"] is True, rec
        assert rec["cross_leq_flat_over_local"] is True, rec
        assert rec["wire_bytes_cross"] <= \
            rec["wire_bytes_flat"] / hab["local_size"] + 1, rec
        assert rec["flat_ms"] > 0 and rec["hier_ms"] > 0, rec
    # Zero-RTT A/B (ISSUE 11) on every line: with speculation on, warm
    # cycles stop paying the negotiation round trip (< 1 per cycle, hit
    # rate ≥ 90% on this stable workload) while every rank's verdict
    # order is identical on-vs-off — the bitwise-invariance evidence.
    zrt = out["zero_rtt_ab"]
    assert zrt["spec_hit_rate"] is not None and \
        zrt["spec_hit_rate"] >= 0.9, zrt
    assert zrt["round_trips_per_cycle_on"] < 1, zrt
    assert zrt["round_trips_per_cycle_off"] == 1.0, zrt
    assert zrt["orders_identical"] is True, zrt
    assert zrt["negotiation_us_per_cycle_on"] > 0, zrt
    assert zrt["negotiation_us_per_cycle_off"] > 0, zrt
    # ...and the live-engine stats block carries the zero_rtt keys.
    assert "zero_rtt" in out and "spec_hits" in out["zero_rtt"], out.keys()
    # Serving plane (ISSUE 19) on every line: batched-vs-sequential
    # bitwise parity through the padded-bucket jitted forward, the
    # recompile pin under batch-size churn, the p50/p99-vs-offered-load
    # sweep, the scripted ramp → scale_out → drain scenario with the live
    # drain contract, and the 13 B warm-frame guard with serving active.
    srv = out["serving"]
    assert srv["parity_bitwise"] is True, srv
    assert srv["batch_churn_bounded"] is True, srv
    assert len(srv["load_sweep"]) == 3, srv
    for pt in srv["load_sweep"]:
        assert pt["offered_qps"] > 0 and pt["achieved_qps"] > 0, pt
        assert pt["batches"] > 0, pt
    sc = srv["scenario"]
    assert sc["scale_out_fired"] is True and sc["drain_fired"] is True, sc
    assert sc["drain_completed_inflight"] is True, sc
    assert sc["drain_refused_new"] is True, sc
    fg = srv["frame_guard"]
    assert fg["held"] is True, fg
    assert fg["full_announce_delta"] == 0, fg
    assert fg["serve_requests_during_window"] > 0, fg
    # Serving fault tolerance (ISSUE 20) on every line: an injected
    # replica fault mid-batch under concurrent front-door load must lose
    # ZERO accepted requests (every one gets exactly one terminal 200,
    # bitwise-correct, the interrupted bucket via retries), availability
    # stays 1.0, and recovery-time-to-ready is recorded.
    sf = out["serving_faults"]
    assert sf["zero_lost"] is True, sf
    assert sf["lost_requests"] == 0, sf
    assert sf["ok_responses"] == sf["requests"], sf
    assert sf["results_correct"] is True, sf
    assert sf["replica_faults"] == 1 and sf["retried_requests"] > 0, sf
    assert sf["quarantined"] == 0, sf
    assert sf["availability"] == 1.0, sf
    assert sf["recovery_to_ready_s"] is not None \
        and sf["recovery_to_ready_s"] < 30, sf


def test_bench_default_resnet():
    out = _run_bench({"HVD_BENCH_BATCH": "2", "HVD_BENCH_STEPS": "2",
                      "HVD_BENCH_IMAGE": "32", "HVD_BENCH_SKIP_BUSBW": "1",
                      "HVD_BENCH_SKIP_RAW": "1"})
    assert out["metric"].startswith("resnet50")
    assert out["value"] and out["value"] > 0, out
    assert out["errors"] == {}, out


def test_bench_llama_mode():
    out = _run_bench({"HVD_BENCH_MODEL": "llama", "HVD_BENCH_BATCH": "2",
                      "HVD_BENCH_STEPS": "2"})
    assert out["metric"].startswith("llama")
    assert out["value"] and out["value"] > 0, out
    assert out["errors"] == {}, out


def test_bench_tf_step_mode():
    """TF binding per-step cost decomposition (VERDICT r3 missing #3)."""
    out = _run_bench({"HVD_BENCH_MODEL": "tf_step", "HVD_BENCH_STEPS": "5"})
    assert out["metric"] == "tf_binding_step_overhead_pct"
    assert out["value"] is not None, out
    assert out["tf_step_plain_ms"] > 0
    assert out["tf_grouped_allreduce_ms"] > 0
    assert out["errors"] == {}, out


def test_bench_bert_mode():
    out = _run_bench({"HVD_BENCH_MODEL": "bert", "HVD_BENCH_BATCH": "2",
                      "HVD_BENCH_STEPS": "2", "HVD_BENCH_SKIP_BUSBW": "1"})
    assert out["metric"].startswith("bert")
    assert out["value"] and out["value"] > 0, out
    assert out["errors"] == {}, out


def test_bench_llama_seq_and_evidence_knobs():
    """HVD_BENCH_SEQ stretches the llama context; the record carries the
    analytic-FLOPs/MFU evidence fields and the requested seq/remat."""
    out = _run_bench({"HVD_BENCH_MODEL": "llama", "HVD_BENCH_BATCH": "2",
                      "HVD_BENCH_STEPS": "2", "HVD_BENCH_SEQ": "256",
                      "HVD_BENCH_REMAT": "1"})
    assert out["value"] and out["value"] > 0, out
    te = out["timing_evidence"]["llama"]
    assert te["seq"] == 256
    assert te["n_params"] > 0
    assert te["analytic_step_flops"] > 0
    assert out["errors"] == {}, out


def test_bench_bert_seq_knob():
    """HVD_BENCH_SEQ reaches the bert mode too (the non-causal crossover
    bench vehicle) with the same evidence fields."""
    out = _run_bench({"HVD_BENCH_MODEL": "bert", "HVD_BENCH_BATCH": "2",
                      "HVD_BENCH_STEPS": "2", "HVD_BENCH_SEQ": "128",
                      "HVD_BENCH_SKIP_BUSBW": "1"})
    assert out["value"] and out["value"] > 0, out
    te = out["timing_evidence"]["bert"]
    assert te["seq"] == 128
    assert te["n_params"] > 0
    assert out["errors"] == {}, out


def test_bench_decode_mode():
    """Inference mode: prefill + KV-cache decode through the flagship."""
    out = _run_bench({"HVD_BENCH_MODEL": "decode", "HVD_BENCH_STEPS": "2",
                      "HVD_BENCH_DECODE_BATCH": "2"})
    assert out["metric"] == "llama_decode_tokens_per_sec"
    assert out["value"] and out["value"] > 0, out
    assert out["errors"] == {}, out


def test_bench_vit_mode():
    out = _run_bench({"HVD_BENCH_MODEL": "vit", "HVD_BENCH_BATCH": "1",
                      "HVD_BENCH_STEPS": "2", "HVD_BENCH_IMAGE": "32"})
    assert out["metric"].startswith("vit")
    assert out["value"] and out["value"] > 0, out
    te = out["timing_evidence"]["vit"]
    assert te["n_params"] > 0 and te["seq"] == 5  # 32/16 grid + CLS
    assert out["errors"] == {}, out
