"""Tests for the whole-package interprocedural analyzer (ISSUE 13).

Every acceptance claim has a positive AND a control: fixtures that the
whole-package mode must flag are also run through per-module mode to prove
the per-module analysis MISSES them (the gap the two-pass mode closes),
and each new rule (HVD108/HVD109) has a negative fixture that stays clean.
Plus: pragma parsing through the interprocedural path, baseline
round-trip, SARIF 2.1.0 schema validity, static-index linkage into the
runtime sanitizer, CLI exit codes, and the repo gate plumbing.

Everything here is jax-free: the whole-package mode is pure AST analysis.
"""

import json
import textwrap

import pytest

from horovod_tpu.analysis import analyze_package, lint_paths
from horovod_tpu.analysis.whole_package import build_static_index


def make_pkg(tmp_path, files, name="fixture"):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    for rel, src in files.items():
        p = d / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(d)


def rules_of(findings):
    return {f.rule for f in findings}


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ==================================================== HVD101 interprocedural
GUARDED_HELPER = {
    "__init__.py": "",
    "helpers.py": """
        import horovod_tpu as hvd

        def do_sum(x):
            return hvd.allreduce(x, name="s")
    """,
    "train.py": """
        import horovod_tpu as hvd
        from .helpers import do_sum

        def main(x):
            if hvd.rank() == 0:
                do_sum(x)
    """,
}


def test_hvd101_cross_module_guarded_helper(tmp_path):
    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    findings = analyze_package([pkg])
    hits = by_rule(findings, "HVD101")
    assert len(hits) == 1
    f = hits[0]
    assert f.path.endswith("helpers.py") and f.line == 5
    assert "rank-guarded call chain" in f.message
    assert "train.py" in f.message and "do_sum" in f.message


def test_hvd101_control_per_module_mode_misses_it(tmp_path):
    """The acceptance control: the SAME fixture is provably invisible to
    per-module analysis — the gap ISSUE 13 closes."""
    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    assert "HVD101" not in rules_of(lint_paths([pkg]))


def test_hvd101_through_alias_partial_and_transitive_helper(tmp_path):
    pkg = make_pkg(tmp_path, {
        "__init__.py": "",
        "deep.py": """
            import horovod_tpu as hvd

            def inner(x):
                return hvd.barrier()

            def outer(x):
                return inner(x)
        """,
        "main.py": """
            import functools
            import horovod_tpu as hvd
            from .deep import outer

            g = functools.partial(outer, 1)

            def run():
                if hvd.rank() != 0:
                    g()
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD101")
    assert len(hits) == 1 and hits[0].path.endswith("deep.py")
    assert "barrier" in hits[0].message


def test_hvd101_context_sensitivity_reports_only_guarded_path(tmp_path):
    """A helper called from BOTH guarded and unguarded sites reports once,
    attributing the guarded chain — guard context travels per call chain,
    it is not merged into the callee."""
    pkg = make_pkg(tmp_path, {
        "mod.py": """
            import horovod_tpu as hvd

            def both_sides(x):
                return hvd.allreduce(x, name="b")

            def caller(x):
                both_sides(x)
                if hvd.rank() == 0:
                    both_sides(x)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD101")
    assert len(hits) == 1
    assert "rank-guarded" in hits[0].message


def test_hvd101_through_nested_package_reexport(tmp_path):
    """Relative imports/re-exports inside a NESTED package's __init__.py
    resolve against the full dotted package name (an __init__ IS its
    package, not a sibling of it)."""
    pkg = make_pkg(tmp_path, {
        "__init__.py": "",
        "sub/__init__.py": "from .impl import do_sum\n",
        "sub/impl.py": """
            import horovod_tpu as hvd

            def do_sum(x):
                return hvd.allreduce(x, name="s")
        """,
        "train.py": """
            import horovod_tpu as hvd
            from .sub import do_sum

            def main(x):
                if hvd.rank() == 0:
                    do_sum(x)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD101")
    assert len(hits) == 1 and hits[0].path.endswith("impl.py")


def test_hvd101_unguarded_helper_stays_clean(tmp_path):
    pkg = make_pkg(tmp_path, {
        "mod.py": """
            import horovod_tpu as hvd

            def helper(x):
                return hvd.allreduce(x)

            def caller(x):
                return helper(x)
        """,
    })
    assert "HVD101" not in rules_of(analyze_package([pkg]))


def test_hvd101_method_resolution_through_binding_instance(tmp_path):
    """The optimizer-binding idiom: a method reached through an instance
    variable (``opt = Wrapper(); opt.apply(...)``) is resolved."""
    pkg = make_pkg(tmp_path, {
        "mod.py": """
            import horovod_tpu as hvd

            class Wrapper:
                def apply(self, g):
                    return self._reduce(g)

                def _reduce(self, g):
                    return hvd.allreduce(g, name="g")

            def main(g):
                opt = Wrapper()
                if hvd.rank() == 0:
                    opt.apply(g)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD101")
    assert len(hits) == 1 and "allreduce" in hits[0].message


def test_hvd101_pragma_suppresses_interprocedural_finding(tmp_path):
    files = dict(GUARDED_HELPER)
    files["helpers.py"] = """
        import horovod_tpu as hvd

        def do_sum(x):
            return hvd.allreduce(x, name="s")  # hvd-lint: disable=HVD101
    """
    pkg = make_pkg(tmp_path, files)
    assert "HVD101" not in rules_of(analyze_package([pkg]))


# ==================================================== HVD103 cross-module
SPLIT_TRAINING = {
    "__init__.py": "",
    "trainer.py": """
        import horovod_tpu as hvd

        def make_opt(sgd):
            return hvd.DistributedOptimizer(sgd)
    """,
    "train.py": """
        import horovod_tpu as hvd
        from .trainer import make_opt

        def main(sgd):
            hvd.init()
            opt = make_opt(sgd)
    """,
}


def test_hvd103_cross_module_missing_broadcast(tmp_path):
    """init() in the entry, DistributedOptimizer in a helper module, no
    broadcast anywhere: only the closure union sees the bug."""
    pkg = make_pkg(tmp_path, SPLIT_TRAINING)
    hits = by_rule(analyze_package([pkg]), "HVD103")
    assert len(hits) == 1 and hits[0].path.endswith("train.py")


def test_hvd103_control_per_module_mode_misses_it(tmp_path):
    pkg = make_pkg(tmp_path, SPLIT_TRAINING)
    assert "HVD103" not in rules_of(lint_paths([pkg]))


def test_hvd103_cross_module_broadcast_refutes_per_module_fp(tmp_path):
    """The other direction: per-module mode false-positives when the
    broadcast lives in a helper module; whole-package mode is quiet."""
    pkg = make_pkg(tmp_path, {
        "__init__.py": "",
        "setup.py": """
            import horovod_tpu as hvd

            def sync(params):
                return hvd.broadcast_parameters(params, root_rank=0)
        """,
        "train.py": """
            import horovod_tpu as hvd
            from .setup import sync

            def main(params, sgd):
                hvd.init()
                opt = hvd.DistributedOptimizer(sgd)
                sync(params)
        """,
    })
    per_module = lint_paths([pkg])
    assert "HVD103" in rules_of(per_module)          # the old false positive
    assert "HVD103" not in rules_of(analyze_package([pkg]))


# ==================================================== HVD102 cross-module
def test_hvd102_cross_module_process_set_registration(tmp_path):
    pkg = make_pkg(tmp_path, {
        "__init__.py": "",
        "sets.py": """
            import horovod_tpu as hvd

            def make_sets():
                return hvd.add_process_set([0, 2])
        """,
        "train.py": """
            import horovod_tpu as hvd
            from .sets import make_sets

            def main(x):
                evens = make_sets()
                return hvd.allreduce(x)
        """,
    })
    pm = [f for f in lint_paths([pkg])
          if f.rule == "HVD102" and f.path.endswith("train.py")]
    assert not pm                                    # per-module mode misses
    hits = [f for f in by_rule(analyze_package([pkg]), "HVD102")
            if f.path.endswith("train.py")]
    assert len(hits) == 1 and "another" in hits[0].message


# =========================================================== HVD108
def test_hvd108_branch_divergent_schedule(tmp_path):
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def helper(x):
                return hvd.allreduce(x, name="g")

            def step(x, fast):
                if fast:
                    y = helper(x)
                    return hvd.allgather(y)
                return hvd.allgather(x)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    assert len(hits) == 1 and hits[0].line == 8      # the `if fast:` line
    assert "allreduce, allgather" in hits[0].message
    assert not hits[0].is_error          # warning severity: needs judgement


def test_hvd108_guard_clause_with_equal_paths_stays_clean(tmp_path):
    """An early-returning arm's real alternative is the FALL-THROUGH code,
    not the empty lexical orelse: two runtime-identical paths must compare
    equal even when one is written guard-clause style."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def helper(x):
                return hvd.allreduce(x, name="g")

            def step(x, fast):
                if fast:
                    return hvd.allgather(helper(x))
                y = helper(x)
                return hvd.allgather(y)
        """,
    })
    assert "HVD108" not in rules_of(analyze_package([pkg]))


def test_hvd108_schedule_records_nested_calls_in_evaluation_order(tmp_path):
    """hvd.allgather(helper_allreduce(x)) submits the allreduce FIRST (the
    argument is evaluated before the outer call) — the schedule, and hence
    the divergence verdict, must honor evaluation order, not AST nesting."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def helper(x):
                return hvd.allreduce(x, name="g")

            def step(x, fast):
                if fast:
                    return hvd.allgather(helper(x))   # allreduce, allgather
                y = hvd.allgather(x)
                return helper(y)                      # allgather, allreduce
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    assert len(hits) == 1
    assert "[allreduce, allgather] vs [allgather, allreduce]" \
        in hits[0].message


def test_hvd108_cycle_truncation_does_not_poison_the_memo(tmp_path):
    """A schedule computed while its caller was on the recursion stack is
    truncated at the back-edge; caching that truncated summary would hide
    the callee's collectives from every later non-cyclic context."""
    pkg = make_pkg(tmp_path, {
        "mod.py": """
            import horovod_tpu as hvd

            def a(x):
                y = hvd.allreduce(x, name="g")
                return b(y)

            def b(x):
                if x > 0:
                    return a(x - 1)
                return x

            def entry(x, flag):
                if flag:
                    b(x)
                return x
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    # entry's branch reaches a()'s allreduce through b(): [allreduce...]
    # vs [] must diverge even though a<->b is cyclic.
    assert any("entry()" in f.message for f in hits), \
        [f.render() for f in hits]


def test_same_stem_modules_outside_packages_both_analyzed(tmp_path):
    """dir1/train.py and dir2/train.py share a module name; neither file's
    findings may be dropped, in either argument order."""
    files = {
        "d1/train.py": """
            import horovod_tpu as hvd

            def main(opt):
                hvd.init()
                opt = hvd.DistributedOptimizer(opt)
        """,
        "d2/train.py": """
            import horovod_tpu as hvd

            def main(x):
                return hvd.allreduce(x)
        """,
    }
    pkg = make_pkg(tmp_path, files)
    d1, d2 = f"{pkg}/d1/train.py", f"{pkg}/d2/train.py"
    for order in ([d1, d2], [d2, d1]):
        hits = by_rule(analyze_package(order), "HVD103")
        assert len(hits) == 1 and hits[0].path.endswith("d1/train.py"), \
            (order, [f.render() for f in hits])


def test_hvd108_negative_controls_stay_clean(tmp_path):
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def same_schedule(x, flag):
                if flag:
                    y = x * 2
                    y = hvd.allreduce(y)
                else:
                    y = hvd.allreduce(x)
                return y

            def uniform_branch(x):
                if hvd.size() > 1:
                    return hvd.allreduce(x)
                return x

            def uniform_via_variable(x):
                n = hvd.size()
                if n >= 2:
                    return hvd.allreduce(x)
                return x

            def rank_branch_is_hvd101_not_108(x):
                if hvd.rank() == 0:
                    return hvd.broadcast(x, root_rank=0)
                return x
        """,
    })
    findings = analyze_package([pkg])
    assert "HVD108" not in rules_of(findings)
    assert "HVD101" in rules_of(findings)     # the rank branch still fires


# =========================================================== HVD109
def test_hvd109_collective_in_transition_callback(tmp_path):
    pkg = make_pkg(tmp_path, {
        "elastic_cb.py": """
            import horovod_tpu as hvd

            def drain_stats(x):
                return hvd.allreduce(x, name="drain")

            class Hooks:
                def on_leave(self, info):
                    return drain_stats(info)

                def new_generation(self, ranks):
                    hvd.barrier()
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD109")
    assert len(hits) == 2
    assert all(f.is_error for f in hits)
    msgs = " ".join(f.message for f in hits)
    assert "on_leave" in msgs and "new_generation" in msgs
    assert "mid-transition" in msgs


def test_hvd109_registered_transition_callback(tmp_path):
    pkg = make_pkg(tmp_path, {
        "reg.py": """
            import horovod_tpu as hvd

            def flush(x):
                return hvd.allgather(x)

            def setup(driver):
                driver.register_transition_callbacks([flush])
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD109")
    assert len(hits) == 1 and "flush" in hits[0].message


def test_hvd109_negative_controls_stay_clean(tmp_path):
    pkg = make_pkg(tmp_path, {
        "cb.py": """
            import horovod_tpu as hvd

            class Hooks:
                def on_leave(self, info):
                    print("leaving", info)     # no collective: clean

                def on_reset(self):
                    # post-transition state sync is the SANCTIONED pattern
                    return hvd.broadcast_parameters({}, root_rank=0)

            def ordinary(x):
                return hvd.allreduce(x)
        """,
    })
    assert "HVD109" not in rules_of(analyze_package([pkg]))


# ========================================= ZeRO-sharded schedules (ISSUE 15)
def test_hvd108_sharded_update_schedules_reduce_scatter_allgather(tmp_path):
    """A ``DistributedOptimizer(sharded=True)`` update site schedules the
    ZeRO pipeline — reduce-scatter + allgather, NOT an allreduce: the
    divergence report against a plain-allreduce arm must spell out the
    real sharded sequence."""
    pkg = make_pkg(tmp_path, {
        "train.py": """
            import horovod_tpu as hvd
            import optax

            opt = hvd.DistributedOptimizer(optax.adam(1e-3), sharded=True)

            def step(g, s, p, use_sharded):
                if use_sharded:
                    return opt.update(g, s, p)
                return hvd.allreduce(g), s
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    assert len(hits) == 1 and hits[0].line == 8      # the `if use_sharded:`
    assert "reducescatter[sharded], allgather[sharded]" in hits[0].message
    assert "allreduce]" in hits[0].message


def test_hvd108_sharded_update_both_arms_stay_clean(tmp_path):
    """Accuracy control: two arms that both run the sharded update emit
    the SAME reduce-scatter+allgather schedule — no false divergence from
    the synthetic site expansion."""
    pkg = make_pkg(tmp_path, {
        "train.py": """
            import horovod_tpu as hvd
            import optax

            from horovod_tpu.parallel.zero import sharded_optimizer

            zopt = sharded_optimizer(optax.adam(1e-3))

            def step(g, s, p, log):
                if log:
                    u, s = zopt.update(g, s, p)
                    print("stepped")
                    return u, s
                return zopt.update(g, s, p)
        """,
    })
    assert "HVD108" not in rules_of(analyze_package([pkg]))


def test_hvd108_sharded_flag_is_a_schedule_dimension(tmp_path):
    """sharded=True rides the fusion key and the negotiation digest, so a
    sharded reduce-scatter and an unsharded one of identical spelling are
    DIFFERENT programs — branches choosing between them must diverge."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def step(x, zero):
                if zero:
                    return hvd.grouped_reducescatter([x], sharded=True)
                return hvd.grouped_reducescatter([x])
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    assert len(hits) == 1
    assert "grouped_reducescatter[sharded]" in hits[0].message


def test_hvd108_hierarchical_flag_is_a_schedule_dimension(tmp_path):
    """ISSUE 17: hierarchical=True rides the fusion key (never the
    digest), but batching groups by fusion key — a pinned two-level
    allreduce and a flat one are different batch plans, so branches
    choosing between them must diverge, exactly like [sharded]."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def step(x, big):
                if big:
                    return hvd.allreduce(x, hierarchical=True)
                return hvd.allreduce(x)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD108")
    assert len(hits) == 1
    assert "allreduce[hier]" in hits[0].message


def test_hvd108_hierarchical_both_arms_stay_clean(tmp_path):
    """Accuracy control: both arms pinning hierarchical=True emit the
    same [hier] schedule — no false divergence."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            def step(x, log):
                if log:
                    out = hvd.allreduce(x, hierarchical=True)
                    print("stepped")
                    return out
                return hvd.allreduce(x, hierarchical=True)
        """,
    })
    assert "HVD108" not in rules_of(analyze_package([pkg]))


def test_hvd110_catches_rank_derived_hierarchical_flag(tmp_path):
    """A world-divergent ``hierarchical=`` override forks the batch plan
    (batching groups by fusion key) — HVD110, same as sharded=."""
    pkg = make_pkg(tmp_path, {
        "bad.py": """
            import horovod_tpu as hvd

            def reduce(x):
                return hvd.allreduce(x, hierarchical=hvd.rank() < 4)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD110")
    assert len(hits) == 1 and hits[0].is_error
    assert "hierarchical=" in hits[0].message
    # fleet-uniform pins stay clean
    pkg2 = make_pkg(tmp_path, {
        "good.py": """
            import horovod_tpu as hvd

            def reduce(x):
                return hvd.allreduce(x, hierarchical=True)
        """,
    }, name="ok")
    assert "HVD110" not in rules_of(analyze_package([pkg2]))


def test_hvd109_sharded_update_in_transition_callback(tmp_path):
    """The sharded update is a collective program like any other: reachable
    from a mid-transition callback it must fire HVD109, named as the
    reduce-scatter+allgather it schedules."""
    pkg = make_pkg(tmp_path, {
        "cb.py": """
            import horovod_tpu as hvd
            import optax

            opt = hvd.DistributedOptimizer(optax.adam(1e-3), sharded=True)

            class Hooks:
                def on_join(self, g, s):
                    return opt.update(g, s)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD109")
    assert len(hits) == 1 and hits[0].is_error
    assert "reducescatter[sharded]" in hits[0].message
    assert "on_join" in hits[0].message


def test_sharded_opt_rebind_clears_marking(tmp_path):
    """A name rebound AWAY from a sharded optimizer (to a plain Name, not
    a Call) must drop its marking — no phantom sharded_update sites, so
    no HVD109 for the later .update()."""
    pkg = make_pkg(tmp_path, {
        "cb.py": """
            import horovod_tpu as hvd
            import optax

            class Hooks:
                def on_join(self, g, s, plain):
                    opt = hvd.DistributedOptimizer(optax.adam(1e-3),
                                                   sharded=True)
                    opt = plain
                    return opt.update(g, s)
        """,
    })
    assert "HVD109" not in rules_of(analyze_package([pkg]))


def test_hvd110_catches_injected_divergent_sharded_flag(tmp_path):
    """ISSUE 15 acceptance: a world-divergent ``sharded=`` flag — ranks
    would negotiate mismatched data planes — is an HVD110 ERROR, in
    whole-package mode and per-module mode alike."""
    src = {
        "bad.py": """
            import horovod_tpu as hvd
            import optax

            def build(inner):
                opt = hvd.DistributedOptimizer(
                    inner, sharded=hvd.rank() == 0)
                return opt

            def scatter(x):
                r = hvd.local_rank()
                return hvd.grouped_reducescatter([x], sharded=r < 2)
        """,
    }
    pkg = make_pkg(tmp_path, src)
    hits = by_rule(analyze_package([pkg]), "HVD110")
    assert len(hits) == 2
    assert all(f.is_error for f in hits)
    assert "rank identity" in hits[0].message
    assert {f.line for f in hits} == {6, 12}
    # Per-module mode sees it too (the check is purely local).
    assert len(by_rule(lint_paths([pkg]), "HVD110")) == 2


def test_hvd110_quiet_on_fleet_uniform_sharded_config(tmp_path):
    """Constants, env-derived config and world-size-derived shard counts
    are fleet-uniform: no HVD110."""
    pkg = make_pkg(tmp_path, {
        "good.py": """
            import os
            import horovod_tpu as hvd
            import optax

            def build(inner):
                return hvd.DistributedOptimizer(inner, sharded=True)

            def build_env(inner):
                flag = bool(int(os.environ.get("SHARD", "0")))
                return hvd.DistributedOptimizer(inner, sharded=flag)

            def scatter(x):
                return hvd.grouped_reducescatter(
                    [x], sharded=True, num_shards=hvd.size())
        """,
    })
    assert "HVD110" not in rules_of(analyze_package([pkg]))


# ============================================== satellite: jit unwrapping
def test_jit_assignment_wrapping_no_longer_hides_body():
    """``step = jax.jit(step_impl)`` puts step_impl in a jit context:
    HVD106/HVD107 now see through the assignment wrap (previously the
    decorated-by-assignment body hid from the jit-context rules)."""
    from horovod_tpu.analysis import lint_source

    findings = lint_source(textwrap.dedent("""
        import jax
        import horovod_tpu as hvd

        def step_impl(x):
            jax.block_until_ready(x)
            return hvd.allreduce(x)

        step = jax.jit(step_impl)
    """), "fixture.py")
    assert {"HVD106", "HVD107"} <= rules_of(findings)


def test_shard_map_partial_decorator_counts_as_jit_context():
    from horovod_tpu.analysis import lint_source

    findings = lint_source(textwrap.dedent("""
        import functools
        import horovod_tpu as hvd
        from horovod_tpu.compat import shard_map

        @functools.partial(shard_map, mesh=None, in_specs=None,
                           out_specs=None)
        def body(x):
            return hvd.allreduce(x)        # eager op at trace time
    """), "fixture.py")
    assert "HVD107" in rules_of(findings)


def test_nested_jit_shard_map_assignment_unwraps():
    from horovod_tpu.analysis import lint_source

    findings = lint_source(textwrap.dedent("""
        import jax
        from horovod_tpu.compat import shard_map

        def inner(x):
            jax.device_get(x)
            return x

        step = jax.jit(shard_map(inner, mesh=None, in_specs=None,
                                 out_specs=None))
    """), "fixture.py")
    assert "HVD106" in rules_of(findings)


# ====================================================== baseline round-trip
def test_baseline_round_trip_and_diff(tmp_path):
    from horovod_tpu.analysis.baseline import (diff_baseline, finding_key,
                                               load_baseline, write_baseline)
    from horovod_tpu.analysis.findings import Finding

    root = str(tmp_path)
    a = Finding("HVD101", str(tmp_path / "a.py"), 3, 1, "m1")
    b = Finding("HVD108", str(tmp_path / "sub" / "b.py"), 7, 1, "m2")
    path = tmp_path / "baseline.json"
    write_baseline([a, b], str(path), root=root)

    loaded = load_baseline(str(path))
    assert finding_key(a, root) in loaded
    assert ("HVD108", "sub/b.py", 7) in loaded       # forward slashes

    c = Finding("HVD104", str(tmp_path / "c.py"), 1, 1, "new one")
    diff = diff_baseline([a, c], loaded, root=root)
    assert [f.rule for f in diff.new] == ["HVD104"]
    assert [f.rule for f in diff.matched] == ["HVD101"]
    assert diff.stale == [("HVD108", "sub/b.py", 7)]   # b no longer fires


def test_baseline_missing_file_is_empty(tmp_path):
    from horovod_tpu.analysis.baseline import load_baseline
    assert load_baseline(str(tmp_path / "nope.json")) == {}


# ============================================================= SARIF output
# The structural requirements of the SARIF 2.1.0 schema that matter for CI
# ingestion (GitHub code scanning rejects logs violating any of these).
_SARIF_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object",
                            "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "rules": {"type": "array", "items": {
                                    "type": "object",
                                    "required": ["id"],
                                }},
                            },
                        }},
                    },
                    "results": {"type": "array", "items": {
                        "type": "object",
                        "required": ["message"],
                        "properties": {
                            "ruleId": {"type": "string"},
                            "level": {"enum": ["none", "note", "warning",
                                               "error"]},
                            "message": {"type": "object",
                                        "required": ["text"]},
                            "locations": {"type": "array", "items": {
                                "type": "object",
                                "properties": {"physicalLocation": {
                                    "type": "object",
                                    "properties": {
                                        "artifactLocation": {
                                            "type": "object",
                                            "properties": {"uri": {
                                                "type": "string"}}},
                                        "region": {
                                            "type": "object",
                                            "properties": {
                                                "startLine": {
                                                    "type": "integer",
                                                    "minimum": 1},
                                                "startColumn": {
                                                    "type": "integer",
                                                    "minimum": 1},
                                            }},
                                    }}},
                            }},
                        },
                    }},
                },
            },
        },
    },
}


def test_sarif_output_validates_against_schema(tmp_path):
    from horovod_tpu.analysis.sarif import to_sarif, write_sarif

    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    findings = analyze_package([pkg])
    assert findings
    log = to_sarif(findings, root=pkg)

    jsonschema = pytest.importorskip("jsonschema")
    jsonschema.validate(log, _SARIF_SUBSET_SCHEMA)

    run = log["runs"][0]
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(set(rule_ids))
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["ruleIndex"] == rule_ids.index(res["ruleId"])
        uri = res["locations"][0]["physicalLocation"]["artifactLocation"][
            "uri"]
        assert not uri.startswith("/") and "\\" not in uri   # repo-relative

    out = tmp_path / "out.sarif"
    write_sarif(findings, str(out), root=pkg)
    assert json.loads(out.read_text())["version"] == "2.1.0"


# ================================================ static index → sanitizer
def test_static_index_links_runtime_ledger_to_callgraph(tmp_path,
                                                        monkeypatch):
    from horovod_tpu.analysis.runtime_sanitizer import (CollectiveSanitizer,
                                                        StaticIndex)

    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    index = build_static_index([pkg])
    site = "helpers.py:5"
    assert site in index["sites"]
    rec = index["sites"][site]
    assert rec["op"] == "allreduce" and "helpers:do_sum" in rec["node"]
    assert "HVD101" in rec["rules"]      # the static finding at that site

    idx_path = tmp_path / "index.json"
    idx_path.write_text(json.dumps(index))
    monkeypatch.setenv("HVD_TPU_SANITIZER_STATIC_INDEX", str(idx_path))

    s = CollectiveSanitizer(capacity=8)
    assert isinstance(s.static_index, StaticIndex)

    class _E:
        name = "s"
        tensor = None
        process_set_id = 0
    # Forge the ledger entry at the static site: the runtime report must
    # name the static node AND the rule that would have caught it.
    s.observe([_E()], site=site)
    tail = s.render_tail()
    assert "helpers:do_sum" in tail
    assert "HVD101" in tail and "statically" in tail


def test_static_index_absent_env_is_none(monkeypatch):
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer
    monkeypatch.delenv("HVD_TPU_SANITIZER_STATIC_INDEX", raising=False)
    assert CollectiveSanitizer().static_index is None


# ==================================================================== CLI
def test_cli_whole_package_flag(tmp_path):
    from horovod_tpu.analysis.__main__ import main

    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    assert main([pkg]) == 0                       # per-module: misses it
    assert main(["--whole-package", pkg]) == 1    # interprocedural: error


def test_cli_internal_error_exits_3(tmp_path, monkeypatch):
    """Satellite: analyzer crashes are exit 3, distinct from findings (1)
    and usage errors (2), so CI can tell 'your code is wrong' from 'the
    linter is broken'."""
    from horovod_tpu.analysis import collective_lint
    from horovod_tpu.analysis.__main__ import main

    target = tmp_path / "x.py"
    target.write_text("import horovod_tpu as hvd\n")

    # Usage errors stay 2 (not 3): missing path is the CALLER's fault.
    assert main([str(tmp_path / "missing.py")]) == 2

    def boom(paths):
        raise RuntimeError("synthetic analyzer bug")

    monkeypatch.setattr(collective_lint, "lint_paths", boom)
    assert main([str(target)]) == 3


def test_cli_baseline_and_sarif_flow(tmp_path):
    from horovod_tpu.analysis.__main__ import main

    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    baseline = tmp_path / "base.json"
    sarif = tmp_path / "out.sarif"

    # Write a baseline of the current state, then the gate-style run with
    # that baseline is clean (exit 0) even though an error finding exists.
    assert main(["--whole-package", pkg, "--write-baseline",
                 str(baseline), "--root", pkg]) == 0
    assert main(["--whole-package", pkg, "--baseline", str(baseline),
                 "--root", pkg, "--sarif", str(sarif)]) == 0
    log = json.loads(sarif.read_text())
    assert log["runs"][0]["results"] == []        # everything baselined

    # A NEW finding (fresh file) fails the baselined run with exit 1.
    extra = tmp_path / "fixture" / "fresh.py"
    extra.write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.barrier()
    """))
    assert main(["--whole-package", pkg, "--baseline", str(baseline),
                 "--root", pkg]) == 1


def test_cli_emit_static_index(tmp_path):
    from horovod_tpu.analysis.__main__ import main

    pkg = make_pkg(tmp_path, GUARDED_HELPER)
    out = tmp_path / "index.json"
    assert main(["--whole-package", pkg,
                 "--emit-static-index", str(out)]) == 1
    data = json.loads(out.read_text())
    assert data["version"] == 1 and "helpers.py:5" in data["sites"]
    # --emit-static-index without --whole-package is a usage error.
    assert main([pkg, "--emit-static-index", str(out)]) == 2


# ========================================== process-set dataflow (ISSUE 16)
# HVD111: overlapping sets, branch-divergent interleaving (the
# cross-communicator deadlock).  World overlaps every registered set;
# named sets overlap when their literal rank lists intersect.
OVERLAP_INTERLEAVE = {
    "step.py": """
        import horovod_tpu as hvd

        tenants = hvd.add_process_set([0, 1])

        def step(x):
            if hvd.rank() == 0:
                hvd.allreduce(x, name="w")
                hvd.allreduce(x, name="t", process_set=tenants)
            else:
                hvd.allreduce(x, name="t", process_set=tenants)
                hvd.allreduce(x, name="w")
    """,
}


def test_hvd111_overlapping_sets_divergent_interleaving(tmp_path):
    pkg = make_pkg(tmp_path, OVERLAP_INTERLEAVE)
    hits = by_rule(analyze_package([pkg]), "HVD111")
    assert len(hits) == 1 and hits[0].is_error
    f = hits[0]
    assert f.line == 7                              # the `if` line
    assert "OVERLAPPING" in f.message and "deadlock" in f.message
    assert f.process_set == "tenants | world"
    # Related sites: all four collective lines ride the finding so the
    # static index can anchor runtime reports to any of them.
    assert len(f.related) == 4


def test_hvd111_named_overlap_via_shared_ranks(tmp_path):
    """Two named sets sharing rank 1: the overlap is proven from the
    literal rank lists, no world collective involved."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            left = hvd.add_process_set([0, 1])
            mid = hvd.add_process_set([1, 2])

            def step(x, flag):
                if flag:
                    hvd.allreduce(x, name="a", process_set=left)
                    hvd.allreduce(x, name="m", process_set=mid)
                else:
                    hvd.allreduce(x, name="m", process_set=mid)
                    hvd.allreduce(x, name="a", process_set=left)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD111")
    assert len(hits) == 1
    assert "ranks [0, 1]" in hits[0].message
    assert "ranks [1, 2]" in hits[0].message


def test_hvd111_disjoint_sets_interleaved_stay_clean(tmp_path):
    """The near-miss: DISJOINT sets interleaved differently are two
    independent streams — reorderable without deadlock, NOT HVD111 (the
    data-divergent schedule itself is still HVD108's call)."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            left = hvd.add_process_set([0, 1])
            right = hvd.add_process_set([2, 3])

            def step(x, flag):
                if flag:
                    hvd.allreduce(x, name="a", process_set=left)
                    hvd.allreduce(x, name="b", process_set=right)
                else:
                    hvd.allreduce(x, name="b", process_set=right)
                    hvd.allreduce(x, name="a", process_set=left)
        """,
    })
    findings = analyze_package([pkg])
    assert "HVD111" not in rules_of(findings)
    assert "HVD108" in rules_of(findings)    # still a divergent schedule


def test_hvd111_one_sided_pair_is_not_an_interleaving(tmp_path):
    """Arms that each touch ONE lane never interleave two communicators
    on a single rank's program order — HVD101/108 territory, not 111."""
    pkg = make_pkg(tmp_path, {
        "step.py": """
            import horovod_tpu as hvd

            tenants = hvd.add_process_set([0, 1])

            def step(x, flag):
                if flag:
                    hvd.allreduce(x, name="w")
                else:
                    hvd.allreduce(x, name="t", process_set=tenants)
        """,
    })
    assert "HVD111" not in rules_of(analyze_package([pkg]))


def test_property_no_false_hvd111_on_provably_disjoint_sets(tmp_path):
    """Property: random call graphs whose process sets have pairwise
    DISJOINT literal rank lists must never fire HVD111, however the arms
    interleave them (directly or through helpers)."""
    import random
    rng = random.Random(20260807)
    for trial in range(8):
        nsets = rng.randint(2, 4)
        names = [f"s{i}" for i in range(nsets)]
        lines = ["import horovod_tpu as hvd", ""]
        for i, n in enumerate(names):
            ranks = list(range(10 * i, 10 * i + rng.randint(1, 5)))
            lines.append(f"{n} = hvd.add_process_set({ranks})")
        nh = rng.randint(0, 3)
        for j in range(nh):
            s = rng.choice(names)
            lines += ["", f"def h{j}(x):",
                      f"    return hvd.allreduce(x, name='h{j}', "
                      f"process_set={s})"]

        def arm_ops():
            ops = []
            for _ in range(rng.randint(1, 4)):
                if nh and rng.random() < 0.4:
                    ops.append(f"h{rng.randrange(nh)}(x)")
                else:
                    ops.append(
                        f"hvd.allreduce(x, name='d{rng.randrange(99)}', "
                        f"process_set={rng.choice(names)})")
            return ops

        test = rng.choice(["hvd.rank() == 0", "flag"])
        lines += ["", "def step(x, flag):", f"    if {test}:"]
        lines += [f"        {op}" for op in arm_ops()]
        lines += ["    else:"]
        lines += [f"        {op}" for op in arm_ops()]
        pkg = make_pkg(tmp_path, {"step.py": "\n".join(lines) + "\n"},
                       name=f"prop{trial}")
        hits = by_rule(analyze_package([pkg]), "HVD111")
        assert not hits, (
            f"false HVD111 on provably disjoint sets (trial {trial}):\n"
            + "\n".join(lines) + "\n"
            + "\n".join(f.render() for f in hits))


# HVD113: hard-coded world collective reachable from a set-scoped region.
LEAKY_TENANT = {
    "helpers.py": """
        import horovod_tpu as hvd

        def scoped_helper(x, process_set=None):
            hvd.allreduce(x, name="g", process_set=process_set)
            hvd.barrier()

        def clean_helper(x, process_set=None):
            hvd.allreduce(x, name="g", process_set=process_set)
            hvd.barrier(process_set=process_set)
    """,
    "train.py": """
        import horovod_tpu as hvd
        from .helpers import scoped_helper, clean_helper

        tenants = hvd.add_process_set([0, 1])

        def main(x):
            scoped_helper(x, process_set=tenants)
            clean_helper(x, process_set=tenants)
    """,
}


def test_hvd113_world_collective_in_set_scoped_helper(tmp_path):
    pkg = make_pkg(tmp_path, {"__init__.py": "", **LEAKY_TENANT},
                   name="leaky")
    hits = by_rule(analyze_package([pkg]), "HVD113")
    assert len(hits) == 1 and hits[0].is_error
    f = hits[0]
    assert f.path.endswith("helpers.py") and f.line == 6   # the barrier
    assert "tenant-leak" in f.message
    assert f.process_set == "tenants"
    assert f.chain and "scoped_helper" in f.chain[0]
    # clean_helper forwards the set to every collective: refuted.
    assert all(h.line != 10 for h in hits)


def test_hvd113_intra_function_leak(tmp_path):
    """The single-function form: one collective scoped by the function's
    own process-set parameter, another silently world."""
    pkg = make_pkg(tmp_path, {
        "mix.py": """
            import horovod_tpu as hvd

            def reduce_and_sync(x, process_set=None):
                hvd.allreduce(x, name="g", process_set=process_set)
                hvd.allgather(x)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD113")
    assert len(hits) == 1 and hits[0].line == 6
    assert "WORLD" in hits[0].message


def test_hvd113_axis_variable_carries_the_set(tmp_path):
    """``axis = ps.axis_name`` then an in-graph collective over that axis
    variable is set-scoped, not a bare world site — the near-miss the
    repo's own jax/optimizer.py pattern exercises."""
    pkg = make_pkg(tmp_path, {
        "graft.py": """
            import horovod_tpu as hvd

            def allreduce_gradients(x, axis_name="hvd", process_set=None):
                if process_set is not None:
                    axis_name = process_set.axis_name
                hvd.grouped_allreduce([x], axis_name=axis_name)
                return hvd.allreduce(x, name="g",
                                     process_set=process_set)
        """,
    })
    assert "HVD113" not in rules_of(analyze_package([pkg]))


# HVD114: overlapping sets alternated with no dominating order edge.
def test_hvd114_alternation_without_order_edge(tmp_path):
    pkg = make_pkg(tmp_path, {
        "pump.py": """
            import horovod_tpu as hvd

            tenants = hvd.add_process_set([0, 1])

            def pump(x):
                hvd.allreduce(x, name="w1")
                hvd.allreduce(x, name="t", process_set=tenants)
                hvd.allreduce(x, name="w2")
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD114")
    assert len(hits) == 1 and not hits[0].is_error   # WARNING severity
    assert hits[0].line == 9                         # the returning leg
    assert "order edge" in hits[0].message


def test_hvd114_world_barrier_refutes(tmp_path):
    """The near-miss: a world barrier between the legs IS the dominating
    order edge — both sets' streams are fenced, no entanglement."""
    pkg = make_pkg(tmp_path, {
        "pump.py": """
            import horovod_tpu as hvd

            tenants = hvd.add_process_set([0, 1])

            def pump(x):
                hvd.allreduce(x, name="w1")
                hvd.allreduce(x, name="t", process_set=tenants)
                hvd.barrier()
                hvd.allreduce(x, name="w2")
        """,
    })
    assert "HVD114" not in rules_of(analyze_package([pkg]))


def test_hvd114_loop_body_alternation(tmp_path):
    """Inside a loop the back-edge closes the alternation: two
    overlapping lanes in one iteration entangle with the NEXT iteration
    even without an A-B-A in straight-line order."""
    pkg = make_pkg(tmp_path, {
        "pump.py": """
            import horovod_tpu as hvd

            tenants = hvd.add_process_set([0, 1])

            def pump(xs):
                for x in xs:
                    hvd.allreduce(x, name="w")
                    hvd.allreduce(x, name="t", process_set=tenants)
        """,
    })
    hits = by_rule(analyze_package([pkg]), "HVD114")
    assert len(hits) == 1
    assert "across loop iterations" in hits[0].message


def test_hvd114_disjoint_sets_never_warn(tmp_path):
    pkg = make_pkg(tmp_path, {
        "pump.py": """
            import horovod_tpu as hvd

            left = hvd.add_process_set([0, 1])
            right = hvd.add_process_set([2, 3])

            def pump(x):
                hvd.allreduce(x, name="a", process_set=left)
                hvd.allreduce(x, name="b", process_set=right)
                hvd.allreduce(x, name="c", process_set=left)
        """,
    })
    assert "HVD114" not in rules_of(analyze_package([pkg]))


# ------------------------------------------- explain / SARIF / static index
def test_gate_explain_prints_chain_and_process_set(tmp_path, capsys):
    from horovod_tpu.analysis.gate import explain

    make_pkg(tmp_path, {"__init__.py": "", **LEAKY_TENANT},
             name="horovod_tpu")
    f = by_rule(analyze_package([str(tmp_path / "horovod_tpu")]),
                "HVD113")[0]
    rc = explain(f"HVD113:helpers.py:{f.line}", root=str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 0
    assert "process set(s): tenants" in out
    assert "call chain:" in out and "scoped_helper" in out
    assert "related collective sites:" in out

    assert explain("HVD113:helpers.py:9999", root=str(tmp_path),
                   quiet=True) == 1
    assert explain("not-a-spec", root=str(tmp_path)) == 2


def test_sarif_carries_process_set_property(tmp_path):
    from horovod_tpu.analysis.sarif import to_sarif

    pkg = make_pkg(tmp_path, {"__init__.py": "", **LEAKY_TENANT},
                   name="leaky")
    findings = by_rule(analyze_package([pkg]), "HVD113")
    log = to_sarif(findings, root=pkg)
    props = [r.get("properties", {}) for r in log["runs"][0]["results"]]
    assert any(p.get("processSet") == "tenants" for p in props)
    assert any("callChain" in p for p in props)


def test_static_index_records_lanes_and_hvd111_anchors(tmp_path):
    pkg = make_pkg(tmp_path, OVERLAP_INTERLEAVE)
    index = build_static_index([pkg])
    lanes = {rec.get("process_set") for rec in index["sites"].values()}
    assert {"world", "tenants"} <= lanes
    # HVD111's related anchors: every involved collective line carries
    # the rule, so a runtime per-set report links back to the node.
    flagged = [s for s, rec in index["sites"].items()
               if "HVD111" in rec.get("rules", ())]
    assert len(flagged) == 4, index["sites"]


def test_gate_crash_in_process_set_pass_exits_3(tmp_path, monkeypatch,
                                                capsys):
    """Satellite: an analyzer crash inside the new process-set pass must
    surface as the gate's exit 3 (linter broken), never a silent green."""
    from horovod_tpu.analysis import gate, whole_package

    def boom(pkg):
        raise RuntimeError("synthetic process-set pass bug")

    monkeypatch.setattr(whole_package, "_hvd113", boom)
    assert gate.main([]) == 3
    assert "exit 3" in capsys.readouterr().err
