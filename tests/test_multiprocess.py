"""Multi-process integration tier: real torovodrun launches on localhost,
full negotiate (native TCP controller) -> fuse -> XLA-collective path across
processes — the rebuild's equivalent of the reference's Gloo-on-localhost
hermetic tier (SURVEY.md §4 "fake backends").
"""

import os
import subprocess
import sys

import pytest

# Integration tier: real subprocess launches (see pyproject markers);
# the fast hermetic tier excludes these with `-m 'not slow'`.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "data", "worker_collectives.py")


def _run_torovodrun(np_, script, timeout=300, extra_args=(), extra_env=None):
    env = dict(os.environ)
    # CPU workers must not load the axon TPU site hook: it initializes the
    # XLA backend at interpreter start, which breaks jax.distributed.
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_TIMELINE", None)
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "-np", str(np_), *extra_args, sys.executable, script]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_native_controller_builds():
    from horovod_tpu.common import native
    lib = native.load()
    assert lib is not None


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_controller_negotiation_unit():
    """Server + 2 client threads, no jax: readiness protocol only."""
    import threading
    from horovod_tpu.common.controller import TCPController

    port = _free_port()
    results = {}

    def worker(rank):
        class E:
            def __init__(self, name):
                self.name = name
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            if rank == 0:
                # announce a; peer announces b first, then a
                r1, _ = ctl.negotiate([E("a")])
                r2, _ = ctl.negotiate([E("a"), E("b")])
                r3, _ = ctl.negotiate([E("b")] if not any(
                    e.name == "b" for e in r2) else [])
                results[rank] = [[e.name for e in r] for r in (r1, r2, r3)]
            else:
                r1, _ = ctl.negotiate([E("b")])
                r2, _ = ctl.negotiate([E("b"), E("a")])
                r3, _ = ctl.negotiate([E("a")] if not any(
                    e.name == "a" for e in r2) else [])
                results[rank] = [[e.name for e in r] for r in (r1, r2, r3)]
        finally:
            ctl.shutdown() if rank != 0 else None
        # rank 0 keeps server alive until both done; shutdown at end
        if rank == 0:
            ctl.shutdown()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert 0 in results and 1 in results
    # Round 1: nothing globally ready (disjoint names). Round 2+: both a
    # and b become ready, in the same global order on both ranks.
    flat0 = [n for r in results[0] for n in r]
    flat1 = [n for r in results[1] for n in r]
    assert sorted(flat0) == ["a", "b"], results
    assert sorted(flat1) == ["a", "b"], results
    assert flat0 == flat1, results


def test_controller_response_cache_shrinks_steady_state():
    """Reference N8 (response_cache.cc): after the first announce of a
    (name, digest) tuple, re-announces ride a 4-byte cache id — identical
    verdicts, much smaller steady-state request frames."""
    import threading
    from horovod_tpu.common.controller import TCPController

    port = _free_port()
    results = {}

    class E:
        def __init__(self, name):
            self.name = name

    names = [f"grad.{i}.with.a.long.parameter.path" for i in range(16)]

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            per_round = []
            orders = []
            for step in range(4):
                before = ctl.bytes_sent
                got = []
                entries = [E(n) for n in names]
                while len(got) < len(names):
                    ready, errs = ctl.negotiate(entries)
                    assert not errs
                    got += [e.name for e in ready]
                    entries = [e for e in entries
                               if e.name not in set(got)]
                per_round.append(ctl.bytes_sent - before)
                orders.append(tuple(got))
            results[rank] = (per_round, orders)
        finally:
            if rank != 0:
                ctl.shutdown()
            else:
                import time
                deadline = time.time() + 30
                while len(results) < 2 and time.time() < deadline:
                    time.sleep(0.01)   # keep the server up for the peer
                ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,))
    t1.start()
    worker(0)
    t1.join(timeout=60)
    assert set(results) == {0, 1}
    for rank, (per_round, orders) in results.items():
        # Steady state (round 2+) must be far smaller than the cold round:
        # 16 cached announces ≈ 16*(4+2+2) + 8 bytes vs full names+digests.
        assert per_round[2] < per_round[0] / 3, (rank, per_round)
        assert per_round[3] <= per_round[1], (rank, per_round)
    # Verdict order identical across ranks every round.
    assert results[0][1] == results[1][1]


@pytest.mark.parametrize("np_", [2, 3])
def test_torovodrun_collectives(np_):
    res = _run_torovodrun(np_, WORKER)
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == np_, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_HIER = os.path.join(REPO, "tests", "data", "worker_hierarchical.py")


def test_hierarchical_two_slices():
    """Cross-slice emulation (VERDICT r4 next #6): 2 processes × 4 local
    devices — intra-process = one slice's ICI domain, the gloo TCP hop =
    DCN — with hierarchical allreduce RS(local)→AR(cross)→AG(local)
    end-to-end through the engine.  The worker asserts size=8, local=4,
    the engine flag, and flat-equivalent numerics (single + fused)."""
    res = _run_torovodrun(2, WORKER_HIER,
                          extra_args=("--hierarchical-allreduce",),
                          extra_env={"HOROVOD_ONE_PROC_PER_HOST": "1"})
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_HIER_PARITY = os.path.join(REPO, "tests", "data", "worker_hier.py")


@pytest.mark.parametrize("controller", ["flat", "hier"])
def test_torovodrun_hier_parity(controller):
    """ISSUE 17 acceptance: after 10 steps on a mixed fp32/bf16/scalar
    integer-valued gradient tree over 2 simulated slices (2 procs × 4
    local devices, HOROVOD_SLICE_MAP=4), parameters from the two-level
    RS(local)→AR(cross)→AG(local) pipeline are BITWISE identical to the
    flat ring's, the leg counters prove the path ran, and toggling the
    mode mid-run cost zero warm-path control bytes (assertions live in
    the worker).  Runs against both control planes — the per-host agent
    must forward the unchanged digests identically."""
    extra = (("--hierarchical-controller",) if controller == "hier"
             else ())
    res = _run_torovodrun(2, WORKER_HIER_PARITY, timeout=300,
                          extra_args=extra,
                          extra_env={"HOROVOD_ONE_PROC_PER_HOST": "1",
                                     "HOROVOD_SLICE_MAP": "4"})
    ok = res.stdout.count("HIER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_TORCH = os.path.join(REPO, "tests", "data", "worker_torch.py")


@pytest.mark.parametrize("np_", [2])
def test_torovodrun_torch_binding(np_):
    res = _run_torovodrun(np_, WORKER_TORCH)
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == np_, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_controller_digest_mismatch_unit():
    """Two client threads announce the same name with divergent shapes: both
    get a per-tensor error naming both ranks; a later consistent collective
    still negotiates (runtime survives)."""
    import threading
    import numpy as np
    from horovod_tpu.common.controller import TCPController

    port = _free_port()
    results = {}

    class E:
        def __init__(self, name, shape):
            self.name = name
            self.tensor = np.zeros((2,) + shape, np.float32)

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            shape = (4,) if rank == 0 else (8,)
            err = None
            for _ in range(20):
                ready, errored = ctl.negotiate([E("t", shape)])
                if errored:
                    err = errored[0][1]
                    break
            # after the failure, a consistent name must still become ready
            ok = []
            for _ in range(20):
                ready, errored = ctl.negotiate([E("t2", (3,))])
                if ready:
                    ok = [e.name for e in ready]
                    break
            results[rank] = (err, ok)
        finally:
            ctl.shutdown()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert 0 in results and 1 in results, results
    for rank in (0, 1):
        err, ok = results[rank]
        assert err is not None and "ranks [0]" in err and "ranks [1]" in err, \
            results
        assert "(4,)" in err and "(8,)" in err, results
        assert ok == ["t2"], results


WORKER_MISMATCH = os.path.join(REPO, "tests", "data", "worker_mismatch.py")


def test_torovodrun_shape_mismatch_fails_fast():
    """Full-stack parity with the reference controller's consistency check:
    mismatched shapes under one name fail that collective on BOTH ranks with
    rank attribution, and the world keeps working afterwards."""
    res = _run_torovodrun(2, WORKER_MISMATCH, timeout=300)
    ok = res.stdout.count("MISMATCH_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_JOIN = os.path.join(REPO, "tests", "data", "worker_join.py")


def test_torovodrun_join_uneven_batches():
    """Real hvd.join() semantics (VERDICT missing #6): rank r trains r+1
    batches then joins; peers keep reducing with the joined rank
    auto-contributing zeros; join returns the last rank; world resumes."""
    # Tiny fusion threshold: every cluster flushes its own batch, so a
    # joined rank that loses peers' group structure would split a grouped
    # collective into mismatched per-process programs (and hang).
    res = _run_torovodrun(2, WORKER_JOIN, timeout=300,
                          extra_env={"HOROVOD_FUSION_THRESHOLD": "1"})
    ok = res.stdout.count("JOIN_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_controller_join_unit():
    """Protocol-level join: rank 1 joins; rank 0's tensor becomes ready on
    both sides (rank 1 synthesizing); then rank 0 joins and both observe
    the all-joined epoch end."""
    import threading
    import numpy as np
    from horovod_tpu.common.controller import TCPController

    port = _free_port()
    results = {}

    class E:
        def __init__(self, name):
            self.name = name
            self.tensor = np.zeros((2, 3), np.float32)

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        synthesized = []
        ctl.synthesizer = lambda name, digest, gid: ("zeros", name, digest)
        try:
            # No background engine thread here: each side must keep driving
            # lock-step rounds itself until the all-joined verdict lands.
            if rank == 1:
                ctl.request_join()
                got = []
                for _ in range(60):
                    ready, _err = ctl.negotiate([])
                    got += ready
                    if ctl._join_event.is_set():
                        break
                results[1] = (got, ctl.join_wait(timeout=1))
            else:
                ready = []
                announced = False
                for _ in range(60):
                    r, _err = ctl.negotiate(
                        [E("t")] if not announced else [])
                    announced = True
                    ready += r
                    if ready and not ctl._join_pending and not ctl._joined \
                            and not ctl._join_event.is_set():
                        ctl.request_join()
                    if ctl._join_event.is_set():
                        break
                results[0] = ([e.name for e in ready],
                              ctl.join_wait(timeout=1))
        finally:
            ctl.shutdown()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert 0 in results and 1 in results, results
    names, last = results[0]
    assert names == ["t"] and last == 0, results
    syn, last1 = results[1]
    assert last1 == 0, results
    assert len(syn) == 1 and syn[0][0] == "zeros" and syn[0][1] == "t", results
    assert "float32" in syn[0][2] and "(3,)" in syn[0][2], results


WORKER_TF = os.path.join(REPO, "tests", "data", "worker_tf_keras.py")


def test_torovodrun_tensorflow_keras():
    """TF/Keras binding across real processes (VERDICT missing #2): rank-
    dependent collectives, DistributedGradientTape averaging,
    broadcast_variables, and a Keras fit that leaves ranks bit-identical."""
    res = _run_torovodrun(2, WORKER_TF, timeout=420)
    ok = res.stdout.count("TF_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_controller_group_structure_mismatch_unit():
    """Grouped on one rank, ungrouped on the other: per-tensor error naming
    both sides (batching would diverge at the fusion threshold), while
    legitimately drifted group IDS (both grouped) stay fine."""
    import threading
    import numpy as np
    from horovod_tpu.common.controller import TCPController

    port = _free_port()
    results = {}

    class E:
        def __init__(self, name, gid):
            self.name = name
            self.group_id = gid
            self.tensor = np.zeros((2, 3), np.float32)

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            # FIXED round count on both ranks: the protocol is lock-step
            # (one frame per rank per round), so break-on-verdict loops
            # would let one rank stop calling rounds while its peer still
            # needs them — the peer then blocks forever or dies when the
            # early finisher tears down.  Announce both tensors every
            # round; verdicts land within the first rounds.
            err, ok = None, []
            for _ in range(6):
                ready, errored = ctl.negotiate(
                    # "t": grouped on rank 0, ungrouped on rank 1 → error;
                    # "t2": grouped on BOTH with drifted ids → fine.
                    [E("t", 5 if rank == 0 else -1),
                     E("t2", 7 if rank == 0 else 99)])
                for e, msg in errored:
                    assert e.name == "t", (e.name, msg)
                    err = err or msg
                ok += [e.name for e in ready]
            results[rank] = (err, ok)
        finally:
            ctl.shutdown()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert 0 in results and 1 in results, results
    for r in (0, 1):
        err, ok = results[r]
        assert err is not None and "GROUPED" in err, results
        assert "ranks [0]" in err and "ranks [1]" in err, results
        # "t2" renegotiates fine every round it is (re-)announced; "t"
        # must never come back ready.
        assert ok and set(ok) == {"t2"}, results


def test_torovodrun_with_network_interface():
    """--network-interface triggers the bootstrap probe phase and selects
    the control-plane address (VERDICT missing #4: the flag used to be
    parsed and ignored)."""
    res = _run_torovodrun(2, WORKER, extra_args=("--network-interface", "lo"))
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_SANITIZER = os.path.join(REPO, "tests", "data", "worker_sanitizer.py")


def test_sanitizer_catches_divergent_collective_order():
    """HVD_TPU_SANITIZER=1 acceptance: two ranks submit identical-signature
    allreduces in opposite order from different call sites; the sanitizer's
    seq/call-site digest tag turns it into a fail-fast NegotiationError
    naming the diverging ranks and both call sites (the worker asserts the
    attribution, then prints SANITIZER_OK)."""
    res = _run_torovodrun(2, WORKER_SANITIZER, timeout=300,
                          extra_env={"HVD_TPU_SANITIZER": "1"})
    ok = res.stdout.count("SANITIZER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_sanitizer_hash_catches_divergent_content_same_site():
    """HVD_TPU_SANITIZER=hash acceptance (the same-site blind spot): two
    ranks submit divergent DATA through one call site with identical
    seq/site tags; only the content digest folded into the tag can tell
    them apart.  The worker asserts rank attribution + the hash field in
    the error, then proves a replicated control collective still
    negotiates (runtime survives)."""
    res = _run_torovodrun(2, WORKER_SANITIZER, timeout=300,
                          extra_env={"HVD_TPU_SANITIZER": "hash"})
    ok = res.stdout.count("SANITIZER_HASH_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_sanitizer_off_misses_divergent_order():
    """Control run: without the sanitizer the same divergence sails through
    negotiation (signatures match) and corrupts silently — the documented
    gap the sanitizer exists to close."""
    res = _run_torovodrun(2, WORKER_SANITIZER, timeout=300)
    ok = res.stdout.count("SANITIZER_MISSED")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_PS = os.path.join(REPO, "tests", "data", "worker_process_sets.py")


def test_process_set_namespaced_sanitizer_attribution(tmp_path):
    """ISSUE 16 acceptance: two tenant process sets run collectives
    concurrently with world traffic; the ranks deliberately swap the WORLD
    lane's submission order.  The namespaced sanitizer must attribute the
    divergence to the world namespace (seq=0:<i> tags), leave each
    tenant's per-set ledger view clean (exactly its own submission at
    seq=<set>:0), and — via HVD_TPU_SANITIZER_STATIC_INDEX — name the
    HVD111 node the whole-package analyzer pinned on these very sites
    before launch."""
    import json
    from horovod_tpu.analysis.whole_package import build_static_index

    index = build_static_index([WORKER_PS])
    flagged = [k for k, v in index["sites"].items()
               if "HVD111" in v.get("rules", ())]
    assert flagged, index  # the analyzer must flag the worker's own sites
    idx_path = tmp_path / "worker_ps_index.json"
    idx_path.write_text(json.dumps(index))

    res = _run_torovodrun(
        2, WORKER_PS, timeout=300,
        extra_env={"HVD_TPU_SANITIZER": "1",
                   "HVD_TPU_SANITIZER_STATIC_INDEX": str(idx_path)})
    ok = res.stdout.count("PROCESS_SET_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_EST = os.path.join(REPO, "tests", "data", "worker_estimator.py")


def test_torovodrun_estimator_sharded_training(tmp_path):
    """Estimator pipeline across real processes (VERDICT missing #3):
    shared-store materialization, per-rank shard reads, coordinator-avg
    gradients, identical final params on every rank."""
    res = _run_torovodrun(2, WORKER_EST, timeout=300,
                          extra_env={"EST_DIR": str(tmp_path)})
    ok = res.stdout.count("EST_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_CACHE = os.path.join(REPO, "tests", "data", "worker_cache.py")


def test_torovodrun_response_cache_steady_state():
    """PR 2 acceptance: after warm-up, steady-state cycles exchange only
    the bitvector frame (frame-count assertion inside the worker), a shape
    change falls back to full negotiation on all ranks, and bf16-wire
    allreduce matches fp32 while reusing one cached program."""
    res = _run_torovodrun(2, WORKER_CACHE, timeout=300)
    ok = res.stdout.count("CACHE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_PIPELINE = os.path.join(REPO, "tests", "data", "worker_pipeline.py")


def test_torovodrun_pipeline():
    """PR 3 acceptance: chunked fused collectives + in-flight dispatch
    window + priority drain produce bitwise-identical results vs the
    legacy single-chunk inline path (with and without bf16 wire
    compression), the steady-state response-cache frame guarantee holds
    with the pipeline on, and the FusedProgramCache stays bounded by
    chunk-count keying (assertions live in the worker)."""
    res = _run_torovodrun(2, WORKER_PIPELINE, timeout=300)
    ok = res.stdout.count("PIPELINE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_FASTLANE = os.path.join(REPO, "tests", "data", "worker_fastlane.py")


def test_torovodrun_fast_lane():
    """ISSUE 8 acceptance: the latency fast lane (single-tensor dispatch
    through slot-pinned persistent programs) + ByteScheduler partitioning
    produce bitwise-identical results vs the fused whole-tensor path
    (with and without bf16 wire compression), the steady-state response-
    cache frame guarantee holds with both knobs on, the negotiation round
    count per step is unchanged, and the pinned-program path actually
    served warm dispatches (assertions live in the worker)."""
    res = _run_torovodrun(2, WORKER_FASTLANE, timeout=300)
    ok = res.stdout.count("FASTLANE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_SHARDED = os.path.join(REPO, "tests", "data", "worker_sharded.py")


def test_torovodrun_sharded_optimizer():
    """ISSUE 15 acceptance: DistributedOptimizer(sharded=True) — per-
    bucket reduce-scatter, 1/N shard update, allgather — produces
    BITWISE-identical parameters to the replicated path after 10 steps on
    the same gradient stream, optimizer-state bytes/rank scale ~1/N, the
    steady-state warm path stays on the pinned bitvector frame, and the
    chunked scatter→update→gather pipeline engages with results unchanged
    (assertions live in the worker)."""
    res = _run_torovodrun(2, WORKER_SHARDED, timeout=300)
    ok = res.stdout.count("SHARDED_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_sharded_optimizer_hierarchical():
    """The same ZeRO acceptance through the two-level control plane: the
    per-host agent aggregates the sharded ops' warm-path frames exactly
    like allreduce's — parity, 1/N state and the frame guard must all
    hold behind an agent."""
    res = _run_torovodrun(2, WORKER_SHARDED, timeout=300,
                          extra_args=("--hierarchical-controller",))
    ok = res.stdout.count("SHARDED_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_FSDP = os.path.join(REPO, "tests", "data", "worker_fsdp.py")


def test_torovodrun_full_sharding():
    """ISSUE 18 acceptance: DistributedOptimizer(sharded="full") — the
    ZeRO-3/FSDP pipeline (prefetch-lane parameter allgather, gradient
    reduce-scatter into the resident 1/N shard, shard-local update) —
    produces BITWISE-identical parameters to the replicated path after 10
    steps on the same gradient stream, resident param+opt bytes scale
    ~1/N, bucket k+1's gather overlaps bucket k (prefetch counters), the
    warm path stays on the pinned bitvector frame with prefetch armed,
    and the shard-native saveable round-trips (assertions live in the
    worker)."""
    res = _run_torovodrun(2, WORKER_FSDP, timeout=300)
    ok = res.stdout.count("FSDP_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_full_sharding_hierarchical():
    """The same FSDP acceptance through the two-level control plane: the
    per-host agent aggregates the prefetch-lane gathers' warm-path frames
    exactly like allreduce's — parity, 1/N residency, overlap and the
    frame guard must all hold behind an agent."""
    res = _run_torovodrun(2, WORKER_FSDP, timeout=300,
                          extra_args=("--hierarchical-controller",))
    ok = res.stdout.count("FSDP_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_SERVE = os.path.join(REPO, "tests", "data", "worker_serve.py")


def test_torovodrun_serving():
    """ISSUE 19 acceptance: the data-parallel serving plane across real
    processes — version-stamped weight fan-out over the collective
    broadcast path (rank 1 starts from zeros, ends bitwise identical;
    re-delivery is a no-op; a rolling update re-broadcasts without
    restart), batched-vs-sequential forward bitwise parity with the
    per-bucket program cache pinned, the serving-mode ScalePolicy's
    scripted ramp → scale_out → drain sequence, and the drain contract
    under live load (in-flight requests complete, new admissions
    refused).  Assertions live in the worker."""
    res = _run_torovodrun(2, WORKER_SERVE, timeout=300)
    ok = res.stdout.count("SERVE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_serving_hierarchical():
    """The same serving acceptance through the two-level control plane:
    the per-host agent aggregates the broadcast fan-out's warm-path
    frames exactly like allreduce's — fan-out parity, the version-stamp
    no-op and the drain contract must all hold behind an agent."""
    res = _run_torovodrun(2, WORKER_SERVE, timeout=300,
                          extra_args=("--hierarchical-controller",))
    ok = res.stdout.count("SERVE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_SERVE_FAULTS = os.path.join(REPO, "tests", "data",
                                   "worker_serve_faults.py")


@pytest.mark.parametrize("controller", ["flat", "hierarchical"])
def test_torovodrun_serving_fault_recovery(tmp_path, controller):
    """ISSUE 20 acceptance (the scripted chaos scenario, both control
    planes): under the elastic driver, HVD_TPU_FAULT=replica_crash:1@3
    kills rank 1 uncleanly inside its 3rd dispatched batch while 24
    concurrent front-door requests are in flight.  The survivor's serve
    loop fails the interrupted batch RETRYABLY, preserves the queued
    buckets with their original deadlines, re-raises the typed verdict,
    heals through the elastic path (re-rendezvous + no-op versioned
    re-arm), and the SAME batcher resumes: the interrupted requests
    re-enter via front-door retries and complete BITWISE identical to
    their per-request references — zero accepted requests lost, exactly
    one terminal response each.  The proof is the result file the
    survivor writes; the driver exits 0."""
    import json
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("localhost:1\n127.0.0.1:1\n")
    result = tmp_path / "serve_fault_result.json"
    env = dict(os.environ)
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_TIMELINE", None)
    env.update({
        "FAULT_RESULT": str(result),
        "HVD_TPU_FAULT": "replica_crash:1@3",
        "HOROVOD_ROUND_TIMEOUT_S": "30",
    })
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--host-discovery-script", f"cat {hostfile}",
           "--min-np", "1", "--max-np", "2"]
    if controller == "hierarchical":
        cmd.append("--hierarchical-controller")
    cmd += [sys.executable, WORKER_SERVE_FAULTS]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=300)
    assert res.returncode == 0, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")
    assert result.exists(), res.stdout[-3000:]
    data = json.loads(result.read_text())
    assert data["ok"], data
    assert data["lost"] == 0, data
    assert data["retried"] == 4, data            # the interrupted bucket
    assert data["requeued"] == 8, data           # the two preserved ones
    assert data["availability"] == 1.0, data
    assert data["final_size"] == 1, data
    assert data["faults"], data
    assert data["recovery_s"] < 60, data


WORKER_MONITOR = os.path.join(REPO, "tests", "data", "worker_monitor.py")


def test_torovodrun_monitor_acceptance():
    """Monitor-subsystem acceptance (the tentpole's two-process proof):
    cross-rank snapshot aggregation through the coordinator side-channel,
    the steady-state frame guard holding with monitoring ON, a forced
    stall on rank 1 producing an HVD302 report on rank 0 that quotes rank
    1's ledger tail, and /health reflecting the stall then recovering.
    Assertions live in the worker."""
    port = _free_port()
    res = _run_torovodrun(2, WORKER_MONITOR, timeout=300, extra_env={
        "HOROVOD_MONITOR": "1",
        "HOROVOD_MONITOR_INTERVAL": "0.2",
        "HOROVOD_MONITOR_PORT": str(port),
        "HVD_TPU_SANITIZER": "1",
        "HVD_TPU_SANITIZER_TIMEOUT": "2",
    })
    ok = res.stdout.count("MONITOR_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_TRACE = os.path.join(REPO, "tests", "data", "worker_trace.py")


def test_torovodrun_trace_acceptance(tmp_path):
    """ISSUE 6 acceptance: two ranks run with --trace-filename +
    HOROVOD_MONITOR=1; in-worker assertions cover the armed tracer, the
    phase-sum/lifecycle consistency, the steady-state frame guard with
    tracing ON (digest inside the size cap) and the peer's digest arriving
    over the MON1 side-channel.  Launcher-side, `python -m
    horovod_tpu.trace` merges the two per-rank files into one chrome trace
    with a lane per rank and cycle-correlated flow arrows."""
    base = str(tmp_path / "tr")
    res = _run_torovodrun(2, WORKER_TRACE, timeout=300,
                          extra_args=("--trace-filename", base),
                          extra_env={
                              "HOROVOD_MONITOR": "1",
                              "HOROVOD_MONITOR_INTERVAL": "0.2",
                          })
    ok = res.stdout.count("TRACE_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")
    assert os.path.exists(base + ".0") and os.path.exists(base + ".1")
    merged_path = str(tmp_path / "merged.json")
    import json
    merge = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.trace", base,
         "-o", merged_path, "--report"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO})
    assert merge.returncode == 0, (merge.stdout, merge.stderr)
    assert "critical-path attribution" in merge.stdout
    with open(merged_path) as fh:
        merged = json.load(fh)
    ev = merged["traceEvents"]
    # One lane per rank...
    names = {e["args"]["name"] for e in ev
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"rank 0", "rank 1"}, names
    assert {e["pid"] for e in ev if e.get("ph") == "X"} == {0, 1}
    # ...with cycle-correlated flows: each flow id starts on one rank and
    # finishes on the other (the same lock-step round on both lanes).
    starts = {e["id"]: e["pid"] for e in ev if e.get("ph") == "s"}
    ends = {e["id"]: e["pid"] for e in ev if e.get("ph") == "f"}
    common = set(starts) & set(ends)
    assert common, (starts, ends)
    assert all(starts[c] != ends[c] for c in common)
    # Both ranks' tensor lanes carry the five phases.
    phases = {e["name"] for e in ev if e.get("ph") == "X"
              and e.get("tid", 0) != 0}
    assert {"QUEUE", "NEGOTIATION", "COPY_IN", "REDUCE",
            "DRAIN"} <= phases, phases


WORKER_FAULTS = os.path.join(REPO, "tests", "data", "worker_faults.py")


@pytest.mark.parametrize("pipeline", [1, 2], ids=["lockstep", "pipelined"])
def test_torovodrun_dead_rank_aborts_with_attribution(tmp_path, pipeline):
    """ISSUE 5 acceptance (static half): with HVD_TPU_FAULT=
    mid_round_exit:1:crash, rank 1 dies uncleanly mid-negotiation and rank
    0 raises a typed HVD303 PeerFailureError naming rank 1 within
    HOROVOD_ROUND_TIMEOUT_S — no hang, no wedged waiters (a pre-existing
    pending handle settles with the fault, new work fails fast).  The
    proof is the result file rank 0 writes before the launcher reaps it;
    the launcher's nonzero exit (rank 1's crash) is expected.  Swept with
    HOROVOD_ROUND_PIPELINE=2 (ISSUE 11): a deferred response must carry
    the typed abort to the survivor exactly like a lock-step one."""
    import json
    result = tmp_path / "fault_result.json"
    res = _run_torovodrun(2, WORKER_FAULTS, timeout=300, extra_env={
        "FAULT_MODE": "static",
        "FAULT_RESULT": str(result),
        "HVD_TPU_FAULT": "mid_round_exit:1:crash:300",
        "HOROVOD_ROUND_TIMEOUT_S": "30",
        "HOROVOD_ROUND_PIPELINE": str(pipeline),
    })
    assert res.returncode != 0, (
        "rank 1's unclean crash must fail the launch\n"
        f"stdout:\n{res.stdout[-2000:]}")
    assert result.exists(), (
        f"rank 0 never recorded the typed abort\nstdout:\n"
        f"{res.stdout[-3000:]}\nstderr:\n{res.stderr[-3000:]}")
    data = json.loads(result.read_text())
    assert data["ok"] and data["mode"] == "static", data
    assert data["dead_ranks"] == [1] and data["hvd303"], data
    assert data["elapsed_s"] < 30, data


def test_torovodrun_elastic_rerendezvous_after_crash(tmp_path):
    """ISSUE 5 acceptance (elastic half): the same mid-negotiation crash
    under the elastic driver.  Two single-slot local 'hosts' (localhost +
    127.0.0.1) so blacklisting the crashed host leaves a surviving world:
    the survivor catches the typed PeerFailureError, restores committed
    state, re-rendezvouses into the shrunk generation and completes every
    epoch; the driver exits 0."""
    import json
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("localhost:1\n127.0.0.1:1\n")
    result = tmp_path / "fault_result.json"
    env = dict(os.environ)
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_TIMELINE", None)
    env.update({
        "FAULT_MODE": "elastic",
        "FAULT_RESULT": str(result),
        "FAULT_EPOCHS": "6",
        "HVD_TPU_FAULT": "mid_round_exit:1:crash:600",
        "HOROVOD_ROUND_TIMEOUT_S": "30",
    })
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--host-discovery-script", f"cat {hostfile}",
           "--min-np", "1", "--max-np", "2",
           sys.executable, WORKER_FAULTS]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=300)
    assert res.returncode == 0, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")
    assert result.exists(), res.stdout[-3000:]
    data = json.loads(result.read_text())
    assert data["ok"] and data["mode"] == "elastic", data
    assert data["epochs"] == 6, data
    assert data["final_size"] == 1, data
    assert data["resets"] >= 1, data
    # The reset was triggered by the TYPED control-plane error, not a
    # blind socket failure.
    assert any(kind == "PeerFailureError" and ranks == [1]
               for kind, ranks in data["caught"]), data


def test_torovodrun_hierarchical_controller_collectives():
    """ISSUE 9 acceptance (happy path): the two-level control plane across
    two simulated hosts — each worker talks to its host's aggregation
    agent, the root sees one connection per host — produces the same
    collective results as flat mode (the worker's own assertions)."""
    res = _run_torovodrun(2, WORKER,
                          extra_args=("-H", "localhost:1,127.0.0.1:1",
                                      "--hierarchical-controller"))
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_hierarchical_single_host_agent():
    """Both ranks behind ONE agent (the -np 2 localhost default): the
    agent aggregates its whole world and the root negotiates with a single
    connection."""
    res = _run_torovodrun(2, WORKER, extra_args=("--hierarchical-controller",))
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


@pytest.mark.parametrize("pipeline", [1, 2], ids=["lockstep", "pipelined"])
def test_torovodrun_hierarchical_agent_crash_attributed(tmp_path, pipeline):
    """ISSUE 9 acceptance (fault half, the 2-proc/2-'host' worker): rank
    1 — alone on its simulated host — crashes mid-negotiation, killing its
    host agent with it.  The root attributes the severed AGENT connection
    to the host's ranks, and rank 0 records a typed HVD303
    PeerFailureError naming rank 1 within the round deadline — no wedged
    waiters (same contract as the flat-mode test above, now through two
    agents).  Swept with HOROVOD_ROUND_PIPELINE=2 (ISSUE 11): agent death
    must surface through a deferred read too."""
    import json
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("localhost slots=1\n127.0.0.1 slots=1\n")
    result = tmp_path / "fault_result.json"
    res = _run_torovodrun(2, WORKER_FAULTS, timeout=300,
                          extra_args=("--hostfile", str(hostfile),
                                      "--hierarchical-controller"),
                          extra_env={
                              "FAULT_MODE": "static",
                              "FAULT_RESULT": str(result),
                              "HVD_TPU_FAULT": "mid_round_exit:1:crash:300",
                              "HOROVOD_ROUND_TIMEOUT_S": "30",
                              "HOROVOD_ROUND_PIPELINE": str(pipeline),
                          })
    assert res.returncode != 0, (
        "rank 1's unclean crash must fail the launch\n"
        f"stdout:\n{res.stdout[-2000:]}")
    assert result.exists(), (
        f"rank 0 never recorded the typed abort\nstdout:\n"
        f"{res.stdout[-3000:]}\nstderr:\n{res.stderr[-3000:]}")
    data = json.loads(result.read_text())
    assert data["ok"] and data["mode"] == "static", data
    assert data["dead_ranks"] == [1] and data["hvd303"], data
    assert data["elapsed_s"] < 30, data


def test_torovodrun_hierarchical_monitor_acceptance():
    """Monitor fan-in through the agents: cross-rank aggregation, the
    HVD302 peer-ledger report and /health must all survive the MON1 blobs
    being deduplicated into per-host uplinks (worker assertions unchanged
    from the flat monitor acceptance)."""
    port = _free_port()
    res = _run_torovodrun(2, WORKER_MONITOR, timeout=300,
                          extra_args=("--hierarchical-controller",),
                          extra_env={
                              "HOROVOD_MONITOR": "1",
                              "HOROVOD_MONITOR_INTERVAL": "0.2",
                              "HOROVOD_MONITOR_PORT": str(port),
                              "HVD_TPU_SANITIZER": "1",
                              "HVD_TPU_SANITIZER_TIMEOUT": "2",
                          })
    ok = res.stdout.count("MONITOR_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_sanitizer_catches_divergence_on_cached_path():
    """PR 2 acceptance: HVD_TPU_SANITIZER=1 still catches divergent
    submission order when both ranks are on the cached/bitvector path (the
    worker asserts zero full announces during the divergent cycle)."""
    res = _run_torovodrun(2, WORKER_CACHE, timeout=300,
                          extra_env={"HVD_TPU_SANITIZER": "1"})
    ok = res.stdout.count("CACHE_SANITIZER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_LEAVE = os.path.join(REPO, "tests", "data", "worker_leave.py")


def _leave_env(result, mode, pipeline=1, spec=0):
    return {
        "LEAVE_MODE": mode,
        "LEAVE_RESULT": str(result),
        "HOROVOD_ROUND_TIMEOUT_S": "30",
        "HOROVOD_MONITOR": "1",
        "HOROVOD_MONITOR_INTERVAL": "0.2",
        "HOROVOD_ROUND_PIPELINE": str(pipeline),
        "HOROVOD_SPEC_READY_AFTER": str(spec),
    }


def _assert_clean_leave(res, result):
    import json
    assert res.returncode == 0, (
        f"clean LEAVE must not fail the launch (rc={res.returncode})\n"
        f"stdout:\n{res.stdout[-3000:]}\nstderr:\n{res.stderr[-3000:]}")
    assert result.exists(), (
        f"rank 0 never recorded the leave\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")
    data = json.loads(result.read_text())
    assert data["ok"] and data["mode"] == "clean", data
    assert data["verdict"] == "PeerLeftInterrupt", data
    assert data["left_ranks"] == [1], data
    assert data["fault"] is None, data
    assert data["health_status"] == "ok", data
    assert data["health_left"] == [1], data
    with open(str(result) + ".r1") as fh:
        r1 = json.load(fh)
    assert r1["ok"] and r1["leave_sent"] is True, r1


@pytest.mark.parametrize("pipeline,spec", [(1, 0), (2, 0), (1, 1)],
                         ids=["lockstep", "pipelined", "speculative"])
def test_torovodrun_clean_leave_vs_sever(tmp_path, pipeline, spec):
    """ISSUE 10 acceptance (both halves, one worker script): a worker that
    sends the protocol-v6 LEAVE mid-run exits 0 with the survivor
    continuing — PeerLeftInterrupt (a HostsUpdatedInterrupt), engine.fault
    None, /health ok with rank 1 reported left, launcher rc 0 — while the
    SAME sever without a LEAVE frame still produces the typed attributed
    HVD303 abort naming rank 1.  The frame, not timing luck, is what
    disambiguates.  Swept with ISSUE 11's knobs: HOROVOD_ROUND_PIPELINE=2
    (the leaver drains its in-flight window before the LEAVE goes out, so
    the v6 semantics hold with rounds in flight) and
    HOROVOD_SPEC_READY_AFTER=1 (the v7 machinery armed across a clean
    departure; the spec-dispatch-raced-a-LEAVE window is closed by the
    engine settling its in-flight ring with the same interrupt)."""
    import json
    # Half 1: clean.
    result = tmp_path / "leave_clean.json"
    res = _run_torovodrun(2, WORKER_LEAVE, timeout=300,
                          extra_env=_leave_env(result, "clean", pipeline,
                                               spec))
    _assert_clean_leave(res, result)

    # Half 2: the control — same departure point, no LEAVE frame.
    result2 = tmp_path / "leave_sever.json"
    res2 = _run_torovodrun(2, WORKER_LEAVE, timeout=300,
                           extra_env=_leave_env(result2, "sever", pipeline,
                                                spec))
    assert res2.returncode != 0, (
        "the unclean sever must fail the launch\n"
        f"stdout:\n{res2.stdout[-2000:]}")
    assert result2.exists(), (
        f"rank 0 never recorded the typed abort\nstdout:\n"
        f"{res2.stdout[-3000:]}\nstderr:\n{res2.stderr[-3000:]}")
    data = json.loads(result2.read_text())
    assert data["ok"] and data["mode"] == "sever", data
    assert data["verdict"] == "PeerFailureError", data
    assert data["dead_ranks"] == [1] and data["hvd303"], data


def test_torovodrun_clean_leave_hierarchical(tmp_path):
    """The PR 8 follow-up, end to end: the same clean LEAVE through the
    per-host agent (protocol v5 + v6 composed) — the host's uplink
    shrinks, the survivor continues, /health stays ok."""
    result = tmp_path / "leave_hier.json"
    res = _run_torovodrun(2, WORKER_LEAVE, timeout=300,
                          extra_args=("--hierarchical-controller",),
                          extra_env=_leave_env(result, "clean"))
    _assert_clean_leave(res, result)


@pytest.mark.parametrize("knobs", [
    {"HOROVOD_SPEC_READY_AFTER": "1"},
    {"HOROVOD_ROUND_PIPELINE": "2"},
    {"HOROVOD_SPEC_READY_AFTER": "1", "HOROVOD_ROUND_PIPELINE": "2"},
], ids=["spec", "pipeline", "both"])
def test_torovodrun_zero_rtt_collectives(knobs):
    """ISSUE 11 acceptance (results half): the full collective worker —
    which asserts numeric correctness of every op against the expected
    values — runs green with speculative readiness and/or pipelined
    rounds on.  Zero-RTT changes WHEN verdicts return, never what
    executes: the same assertions that pin lock-step results pin these."""
    res = _run_torovodrun(2, WORKER, extra_env=knobs)
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


def test_torovodrun_zero_rtt_hierarchical_collectives():
    """ISSUE 11 through the per-host agents: speculation's confirm-bearing
    warm frames must keep aggregating (host_agent treats an identical
    ZRT7 confirm as part of the warm core) while results stay correct."""
    res = _run_torovodrun(2, WORKER,
                          extra_args=("--hierarchical-controller",),
                          extra_env={"HOROVOD_SPEC_READY_AFTER": "1"})
    ok = res.stdout.count("WORKER_OK")
    assert res.returncode == 0 and ok == 2, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-3000:]}\n"
        f"stderr:\n{res.stderr[-3000:]}")


WORKER_AUTOSCALE = os.path.join(REPO, "tests", "data",
                                "worker_autoscale.py")


@pytest.mark.parametrize("hier", [False, True], ids=["flat", "hier"])
def test_autoscale_simulated_load_scenario(tmp_path, hier):
    """ISSUE 10 acceptance: the closed loop, end to end, over real
    processes and the real wire stack (rendezvous + native lock-step
    negotiation — flat and through real per-host agents — + MON1 monitor
    aggregation + rank-0 /health + DRAIN pings + protocol-v6 LEAVEs):

    traffic ramp → policy scales OUT (scale command adds a host, the
    world grows) → injected straggler → policy EVICTS it with monitor
    attribution (drain → clean LEAVE → exit 0, host cordoned, never
    blacklisted) → world heals → idle → policy scales IN → the run ends
    with every worker exiting 0 and the driver returning success."""
    import json
    import threading as _threading
    import time as _time

    from horovod_tpu.common.net import free_ports
    from horovod_tpu.elastic.autoscale import ScalePolicy
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver

    sdir = tmp_path / "autoscale"
    sdir.mkdir()
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1:1\n127.0.0.2:1\n")
    (sdir / "load").write_text("0")
    (sdir / "straggler").write_text("")
    scale_sh = tmp_path / "scale.sh"
    scale_sh.write_text(f"""#!/bin/sh
case "$HVD_AUTOSCALE_ACTION" in
  scale_out)
    grep -q '^127.0.0.3:' {hosts} || echo '127.0.0.3:1' >> {hosts} ;;
  evict|scale_in)
    grep -v "^$HVD_AUTOSCALE_HOST:" {hosts} > {hosts}.tmp
    mv {hosts}.tmp {hosts} ;;
esac
""")
    scale_sh.chmod(0o755)

    (monitor_port,) = free_ports(1)
    env = {k: v for k, v in os.environ.items()}
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    extra_env = {
        "PYTHONPATH": os.pathsep.join([REPO] + other_paths),
        "AUTOSCALE_DIR": str(sdir),
        "HOROVOD_MONITOR_PORT": str(monitor_port),
    }
    if hier:
        extra_env["HOROVOD_HIERARCHICAL_CONTROLLER"] = "1"

    policy = ScalePolicy(min_np=1, max_np=3, queue_high=10.0,
                         queue_trend_up=1e9,   # absolute threshold drives
                         straggler_factor=3.0, persistence=2,
                         cooldown_s=2.0, idle_s=2.0)
    logs = tmp_path / "logs"
    d = ElasticDriver(
        HostDiscoveryScript(f"cat {hosts}"),
        [sys.executable, WORKER_AUTOSCALE],
        min_np=1, max_np=3, env=extra_env,
        discovery_interval_s=0.25, start_timeout_s=120,
        autoscale_policy=policy, autoscale_interval_s=0.4,
        scale_command=f"sh {scale_sh}", verbose=1,
        output_filename=str(logs))

    rc = {}
    t = _threading.Thread(target=lambda: rc.update(code=d.run()),
                          daemon=True)
    t.start()

    def wait_for(cond, what, timeout=60):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if cond():
                return
            if rc:
                raise AssertionError(
                    f"driver exited rc={rc} while waiting for {what}; "
                    f"events={d.events}")
            _time.sleep(0.1)
        raise AssertionError(f"timed out waiting for {what}; "
                             f"events={d.events} assigned="
                             f"{sorted(d._assigned)} procs="
                             f"{sorted(d._procs)}")

    try:
        # Phase 0: the initial 2-host world forms.
        wait_for(lambda: len(d._procs) == 2, "initial world")

        # Phase 1: traffic ramp → scale out → the world grows to 3.
        (sdir / "load").write_text("40")
        wait_for(lambda: any(e["action"] == "scale_out"
                             for e in d.events), "scale_out decision")
        wait_for(lambda: len(d._assigned) == 3 and len(d._procs) == 3,
                 "world grown to 3")

        # Phase 2: straggler injected on rank 1 → attributed evict →
        # drain → clean exit → the world heals WITHOUT 127.0.0.2.
        straggler_identity = next(
            i for i, a in d._assigned.items() if a["rank"] == 1)
        straggler_host = d._assigned[straggler_identity]["hostname"]
        (sdir / "straggler").write_text("1")
        wait_for(lambda: any(e["action"] == "evict" for e in d.events),
                 "evict decision")
        ev = next(e for e in d.events if e["action"] == "evict")
        assert ev["evict_rank"] == 1, ev
        assert ev["host"] == straggler_host, ev
        assert "monitor attribution" in ev["reason"], ev["reason"]
        (sdir / "straggler").write_text("")
        wait_for(lambda: straggler_host in d._cordoned
                 and len(d._assigned) == 2
                 and straggler_host not in
                 {a["hostname"] for a in d._assigned.values()},
                 "world healed without the straggler")
        assert not d.registry.is_blacklisted(straggler_host)
        assert d.registry.state_of(straggler_identity) == "LEFT"

        # Phase 3: idle → scale in → the world shrinks.
        (sdir / "load").write_text("0")
        wait_for(lambda: any(e["action"] == "scale_in"
                             for e in d.events), "scale_in decision")
        wait_for(lambda: len(d._assigned) == 1, "world shrunk to 1")

        # Phase 4: done → every worker exits 0 → driver succeeds.
        (sdir / "done").write_text("1")
        t.join(timeout=60)
        assert not t.is_alive(), "driver never finished"
        assert rc.get("code") == 0, (rc, d.events)

        actions = [e["action"] for e in d.events]
        assert actions.index("scale_out") < actions.index("evict") \
            < actions.index("scale_in"), actions
        # Clean departures only: nothing was ever blacklisted.
        assert d.registry.blacklist() == set(), d.registry.blacklist()

        # ISSUE 12 — checkpoint pacing: every non-hold decision is
        # preceded by a COMMIT ping; at least one live worker logged the
        # paced commit request.
        all_logs = "".join(p.read_text()
                           for p in logs.glob("*/stdout") if p.exists())
        assert "commit requested by the driver" in all_logs, (
            all_logs[-3000:])
        if hier:
            # ISSUE 12 acceptance — elastic × hierarchical: the SAME
            # agent object (same process, same listen port) served >= 2
            # re-rendezvous generations on the long-lived coordinator
            # host, instead of the fleet being silently forced flat.
            coord_log = (logs / "127.0.0.1.0" / "stdout").read_text()
            assert "agent generation 1" in coord_log, coord_log[-3000:]
            assert "agent generation 2" in coord_log, coord_log[-3000:]
    finally:
        (sdir / "done").write_text("1")
        _time.sleep(0.5)
        d._shutdown_workers()


@pytest.mark.parametrize("hier", [False, True], ids=["flat", "hier"])
def test_preemption_drain_scenario(tmp_path, hier):
    """ISSUE 12 acceptance — preemption-driven drains, end to end over
    real processes and the real wire stack, flat AND hierarchical: a
    discovery preemption notice for one host makes the driver request a
    state commit (checkpoint pacing), cordon the host and DRAIN its
    worker — which finishes, sends the protocol-v6 clean LEAVE, and exits
    0 — so the departure is classified LEFT (never blacklisted, never an
    HVD303 dead-peer verdict) and the world heals without the host."""
    import json
    import threading as _threading
    import time as _time

    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver

    sdir = tmp_path / "autoscale"
    sdir.mkdir()
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1:1\n127.0.0.2:1\n")
    (sdir / "load").write_text("1")       # busy: rounds keep turning
    (sdir / "straggler").write_text("")
    notices = tmp_path / "notices"

    class _NoticeScript(HostDiscoveryScript):
        def preemption_notices(self):
            try:
                return {ln.strip() for ln in notices.read_text().split()
                        if ln.strip()}
            except OSError:
                return set()

    env = {k: v for k, v in os.environ.items()}
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    extra_env = {
        "PYTHONPATH": os.pathsep.join([REPO] + other_paths),
        "AUTOSCALE_DIR": str(sdir),
    }
    if hier:
        extra_env["HOROVOD_HIERARCHICAL_CONTROLLER"] = "1"

    logs = tmp_path / "logs"
    d = ElasticDriver(
        _NoticeScript(f"cat {hosts}"),
        [sys.executable, WORKER_AUTOSCALE],
        min_np=1, max_np=2, env=extra_env,
        discovery_interval_s=0.25, start_timeout_s=120, verbose=1,
        preempt_grace_s=30.0, output_filename=str(logs))

    rc = {}
    t = _threading.Thread(target=lambda: rc.update(code=d.run()),
                          daemon=True)
    t.start()

    def wait_for(cond, what, timeout=60):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if cond():
                return
            if rc:
                raise AssertionError(
                    f"driver exited rc={rc} while waiting for {what}; "
                    f"events={d.events}")
            _time.sleep(0.1)
        raise AssertionError(f"timed out waiting for {what}; "
                             f"events={d.events} assigned="
                             f"{sorted(d._assigned)} procs="
                             f"{sorted(d._procs)}")

    try:
        wait_for(lambda: len(d._procs) == 2, "initial world")
        # Let a few rounds turn so the drain lands mid-run, then post the
        # preemption notice for the second host.
        _time.sleep(1.0)
        notices.write_text("127.0.0.2\n")
        wait_for(lambda: any(e["action"] == "preempt_drain"
                             for e in d.events), "preempt_drain event")
        ev = next(e for e in d.events if e["action"] == "preempt_drain")
        assert ev["host"] == "127.0.0.2", ev
        assert "preemption notice" in ev["reason"], ev
        wait_for(lambda: "127.0.0.2" in d._cordoned
                 and d.registry.state_of("127.0.0.2:0") == "LEFT"
                 and len(d._assigned) == 1
                 and "127.0.0.2" not in
                 {a["hostname"] for a in d._assigned.values()},
                 "world healed without the preempted host")
        # Clean departure: LEFT, never blacklisted.
        assert not d.registry.is_blacklisted("127.0.0.2")
        assert d.registry.blacklist() == set(), d.registry.blacklist()

        (sdir / "done").write_text("1")
        t.join(timeout=60)
        assert not t.is_alive(), "driver never finished"
        assert rc.get("code") == 0, (rc, d.events)

        # The preempted worker took the PACED, CLEAN path: the commit
        # request arrived before the drain, the drain surfaced as
        # DrainRequested -> clean LEAVE, and no dead-peer verdict
        # (HVD303 / PeerFailureError) ever reached it.
        drained_log = (logs / "127.0.0.2.0" / "stdout").read_text()
        assert "commit requested by the driver" in drained_log, (
            drained_log[-3000:])
        assert "drain requested -> clean LEAVE" in drained_log, (
            drained_log[-3000:])
        assert "HVD303" not in drained_log, drained_log[-3000:]
        assert "PeerFailureError" not in drained_log, drained_log[-3000:]
        if hier:
            # The survivor's generation-surviving agent crossed into the
            # healed generation: the same object served both.
            coord_log = (logs / "127.0.0.1.0" / "stdout").read_text()
            assert "agent generation 2" in coord_log, coord_log[-3000:]
    finally:
        (sdir / "done").write_text("1")
        _time.sleep(0.5)
        d._shutdown_workers()


WORKER_STATEPLANE = os.path.join(REPO, "tests", "data",
                                 "worker_stateplane.py")
WORKER_LITE = os.path.join(REPO, "tests", "data",
                           "worker_scenario_lite.py")


@pytest.mark.parametrize("hier", [False, True], ids=["flat", "hier"])
def test_stateplane_peer_restore_scenario(tmp_path, hier):
    """ISSUE 14 acceptance: the resilient state plane end to end over
    real processes and the real wire stack, flat AND hierarchical —
    preempt notice → paced commit (acked) → drain → clean LEAVE → a
    REPLACEMENT host joins and its worker restores the committed state
    FROM THE SURVIVOR'S SHARD SERVER: source=peer, zero disk reads,
    digest bitwise-identical to the survivor's committed epoch."""
    import json
    import re as _re
    import threading as _threading
    import time as _time

    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver

    sdir = tmp_path / "stateplane"
    sdir.mkdir()
    ckpt = tmp_path / "ckpt"
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1:1\n127.0.0.2:1\n")
    notices = tmp_path / "notices"

    class _NoticeScript(HostDiscoveryScript):
        def preemption_notices(self):
            try:
                return {ln.strip() for ln in notices.read_text().split()
                        if ln.strip()}
            except OSError:
                return set()

    env = {k: v for k, v in os.environ.items()}
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    extra_env = {
        "PYTHONPATH": os.pathsep.join([REPO] + other_paths),
        "STATEPLANE_DIR": str(sdir),
        "HOROVOD_CKPT_DIR": str(ckpt),
    }
    if hier:
        extra_env["HOROVOD_HIERARCHICAL_CONTROLLER"] = "1"

    logs = tmp_path / "logs"
    d = ElasticDriver(
        _NoticeScript(f"cat {hosts}"),
        [sys.executable, WORKER_STATEPLANE],
        min_np=1, max_np=2, env=extra_env,
        discovery_interval_s=0.25, start_timeout_s=120, verbose=1,
        preempt_grace_s=30.0, output_filename=str(logs))

    rc = {}
    t = _threading.Thread(target=lambda: rc.update(code=d.run()),
                          daemon=True)
    t.start()

    def wait_for(cond, what, timeout=90):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if cond():
                return
            if rc:
                raise AssertionError(
                    f"driver exited rc={rc} while waiting for {what}; "
                    f"events={d.events}")
            _time.sleep(0.1)
        raise AssertionError(f"timed out waiting for {what}; "
                             f"events={d.events} assigned="
                             f"{sorted(d._assigned)}")

    def log_of(identity):
        p = logs / identity.replace(":", ".") / "stdout"
        return p.read_text() if p.exists() else ""

    try:
        wait_for(lambda: len(d._procs) == 2, "initial world")
        # Let both workers commit a few epochs.
        wait_for(lambda: "committed epoch=" in log_of("127.0.0.1:0")
                 and "committed epoch=" in log_of("127.0.0.2:0"),
                 "first commits")

        # Preemption notice for the second host: paced commit (acked) →
        # cordon → drain → clean LEAVE → LEFT.
        notices.write_text("127.0.0.2\n")
        wait_for(lambda: any(e["action"] == "preempt_drain"
                             for e in d.events), "preempt_drain event")
        wait_for(lambda: d.registry.state_of("127.0.0.2:0") == "LEFT"
                 and len(d._assigned) == 1,
                 "world healed without the preempted host")
        # ISSUE 14 bugfix evidence: the paced-commit fan-out recorded
        # per-worker acks BEFORE the cordon.
        ack_ev = next(e for e in d.events
                      if e["action"] == "commit_request")
        assert ack_ev["acks"], ack_ev

        # The REPLACEMENT host appears; the survivor's newest commit is
        # what the new worker must receive peer-to-peer.
        hosts.write_text("127.0.0.1:1\n127.0.0.3:1\n")
        wait_for(lambda: "restored epoch=" in log_of("127.0.0.3:0"),
                 "replacement restored")
        m = _re.search(
            r"restored epoch=(\d+) source=(\w+) digest=(\S+) "
            r"disk_reads=(\d+)", log_of("127.0.0.3:0"))
        assert m, log_of("127.0.0.3:0")[-3000:]
        epoch, source, digest, disk_reads = (
            int(m.group(1)), m.group(2), m.group(3), int(m.group(4)))
        # Zero disk reads, peer source.
        assert source == "peer", (source, log_of("127.0.0.3:0")[-2000:])
        assert disk_reads == 0
        # ...and bitwise-identical to the survivors' epoch: SOME rank
        # committed exactly this (epoch, digest) pair.
        commits = _re.findall(r"committed epoch=(\d+) digest=(\S+)",
                              log_of("127.0.0.1:0")
                              + log_of("127.0.0.2:0"))
        assert (str(epoch), digest) in commits, (
            epoch, digest, commits[-5:])

        # No dead-peer verdicts anywhere on this path.
        drained_log = log_of("127.0.0.2:0")
        assert "HVD303" not in drained_log, drained_log[-2000:]
        assert "PeerFailureError" not in drained_log, drained_log[-2000:]

        (sdir / "done").write_text("1")
        t.join(timeout=90)
        assert not t.is_alive(), "driver never finished"
        assert rc.get("code") == 0, (rc, d.events)
        assert d.registry.blacklist() == set(), d.registry.blacklist()
    finally:
        (sdir / "done").write_text("1")
        _time.sleep(0.5)
        d._shutdown_workers()


def test_many_host_churn_scenario_with_lite_workers(tmp_path):
    """ISSUE 14 satellite (carried from PR 12): the DRIVER-level churn
    scenario at 64 simulated hosts, using the lightweight jax-free
    worker — world forms, a batch of hosts is preempt-drained (clean
    LEFT, never blacklisted, commit pings acked at scale), replacements
    join, and the run ends clean.  What previously capped at 2–3 hosts
    end-to-end now runs at 64+."""
    import threading as _threading
    import time as _time

    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver

    n_hosts = 64
    drained_n = 8
    sdir = tmp_path / "scenario"
    sdir.mkdir()
    hosts = tmp_path / "hosts"
    all_hosts = [f"127.0.1.{i}" for i in range(1, n_hosts + 1)]
    hosts.write_text("".join(f"{h}:1\n" for h in all_hosts))
    notices = tmp_path / "notices"

    class _NoticeScript(HostDiscoveryScript):
        def preemption_notices(self):
            try:
                return {ln.strip() for ln in notices.read_text().split()
                        if ln.strip()}
            except OSError:
                return set()

    env = {k: v for k, v in os.environ.items()}
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    extra_env = {
        "PYTHONPATH": os.pathsep.join([REPO] + other_paths),
        "SCENARIO_DIR": str(sdir),
    }
    d = ElasticDriver(
        _NoticeScript(f"cat {hosts}"),
        [sys.executable, WORKER_LITE],
        min_np=8, max_np=n_hosts + drained_n, env=extra_env,
        discovery_interval_s=0.5, start_timeout_s=240, verbose=0,
        preempt_grace_s=60.0)

    rc = {}
    t = _threading.Thread(target=lambda: rc.update(code=d.run()),
                          daemon=True)
    t.start()

    def wait_for(cond, what, timeout=240):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if cond():
                return
            if rc:
                raise AssertionError(
                    f"driver exited rc={rc} while waiting for {what}")
            _time.sleep(0.25)
        raise AssertionError(
            f"timed out waiting for {what}; procs={len(d._procs)} "
            f"assigned={len(d._assigned)} events={d.events[-5:]}")

    try:
        wait_for(lambda: len(d._procs) == n_hosts,
                 f"initial {n_hosts}-host world")
        # READINESS, not just spawn: the notification port registers a
        # few seconds after exec (64 simultaneous interpreter startups);
        # draining before that would take the termination fallback.
        wait_for(lambda: len(d.rendezvous.notification_ports())
                 >= n_hosts, "all notification ports registered")

        # Preempt-drain a batch of hosts: every one takes the paced
        # clean path (commit ping -> DRAIN -> exit 0 -> LEFT).
        doomed = all_hosts[-drained_n:]
        notices.write_text("".join(f"{h}\n" for h in doomed))
        wait_for(lambda: sum(1 for e in d.events
                             if e["action"] == "preempt_drain")
                 == drained_n, "preempt_drain events")
        wait_for(lambda: all(
            d.registry.state_of(f"{h}:0") == "LEFT" for h in doomed)
            and len(d._assigned) == n_hosts - drained_n,
            "world healed without the drained batch")
        assert d.registry.blacklist() == set(), d.registry.blacklist()
        # Commit acks recorded at scale: the fan-out reached (and was
        # acked by) a large share of the live fleet.
        ack_ev = next(e for e in d.events
                      if e["action"] == "commit_request")
        assert len(ack_ev["acked"]) >= (n_hosts - drained_n) // 2, (
            len(ack_ev["acked"]))

        # Replacements join: the world grows back.
        extra = [f"127.0.2.{i}" for i in range(1, drained_n + 1)]
        hosts.write_text("".join(
            f"{h}:1\n" for h in all_hosts[:-drained_n] + extra))
        wait_for(lambda: len(d._assigned) == n_hosts, "world re-grown")

        (sdir / "done").write_text("1")
        t.join(timeout=120)
        assert not t.is_alive(), "driver never finished"
        assert rc.get("code") == 0, rc
    finally:
        (sdir / "done").write_text("1")
        _time.sleep(0.5)
        d._shutdown_workers()
