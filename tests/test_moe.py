"""MoE + expert parallelism: the ep-sharded layer must match the
unsharded computation numerically (same assertion pattern as
test_llama_parallel.py — SURVEY.md §4 collective-vs-local)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import moe
from horovod_tpu.parallel import spmd
from horovod_tpu.parallel.mesh import infer_mesh


def _cfg(ep_axis, dp_axis, capacity_factor=8.0, n_experts=8, top_k=1,
         z_weight=0.0):
    # capacity_factor = n_experts → zero drops, so sharded and unsharded
    # runs keep the same tokens and must agree exactly.
    return moe.MoELMConfig(
        vocab_size=64, d_model=32, n_layers=2,
        moe=moe.MoEConfig(d_model=32, d_ff=64, n_experts=n_experts,
                          capacity_factor=capacity_factor,
                          router_top_k=top_k, router_z_weight=z_weight,
                          ep_axis=ep_axis),
        dp_axis=dp_axis)


def _data(cfg, batch=16, seq=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                        jnp.int32),
            jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                        jnp.int32))


@functools.lru_cache(maxsize=None)
def _reference_run(steps=2, top_k=1, z_weight=0.0):
    cfg = _cfg(ep_axis=None, dp_axis=None, top_k=top_k, z_weight=z_weight)
    params = moe.lm_init(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(moe.make_train_step(cfg, opt))
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses, params


@pytest.mark.parametrize("ep,dp_extra,top_k,z_weight", [
    (2, 4, 1, 0.0), (4, 2, 1, 0.0), (8, 1, 1, 0.0),
    # GShard top-2 with z-loss: ep-sharded must STILL match unsharded
    # exactly (VERDICT r4 ask #3's done-bar).
    (2, 4, 2, 1e-3), (4, 2, 2, 1e-3),
])
def test_expert_parallel_matches_reference(ep, dp_extra, top_k, z_weight):
    ref_losses, ref_params = _reference_run(top_k=top_k, z_weight=z_weight)

    cfg = _cfg(ep_axis="ep", dp_axis="dp", top_k=top_k, z_weight=z_weight)
    mesh = infer_mesh(8, ep=ep)
    assert mesh.shape["dp"] == dp_extra
    params = moe.lm_init(cfg, jax.random.PRNGKey(0))
    pspecs = moe.lm_param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    # Tokens are DATA-split over dp AND ep (GShard layout).
    data_spec = P(("dp", "pp", "sp", "tp", "ep"))

    step = spmd.make_sharded_train_step(
        moe.make_train_step(cfg, opt), mesh, pspecs, os_specs, data_spec)
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    out = jax.tree_util.tree_map(np.asarray, params)
    ref = jax.tree_util.tree_map(np.asarray, ref_params)
    for (ka, a), (kb, b) in zip(jax.tree_util.tree_leaves_with_path(out),
                                jax.tree_util.tree_leaves_with_path(ref)):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5,
                                   err_msg=str(ka))


def test_capacity_drops_are_identity():
    """Over-capacity tokens contribute zero MoE output (the caller's
    residual passes them through): with capacity_factor tiny, the layer
    output must be zero for dropped tokens and finite everywhere."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=0.25, ep_axis=None)
    params = moe.init_params(cfg, jax.random.PRNGKey(1))
    x = jnp.asarray(np.random.RandomState(2).randn(32, 16), jnp.float32)
    y, aux, _ = moe.moe_ffn(x, params, cfg)
    assert np.isfinite(np.asarray(y)).all()
    assert float(aux) > 0
    # capacity(32) with cf=.25 over 4 experts = 2 slots/expert → ≤ 8 rows
    # can be nonzero.
    nonzero_rows = int(np.sum(np.any(np.asarray(y) != 0.0, axis=1)))
    assert nonzero_rows <= 4 * cfg.capacity(32)


def test_aux_loss_balances_router():
    """Training WITH the aux loss spreads tokens across experts at least
    as well as the aux_weight=0 control — proving the aux gradient is
    live, not just that this task happens to balance."""
    def train(aux_weight):
        base = _cfg(ep_axis=None, dp_axis=None)
        cfg = moe.MoELMConfig(vocab_size=base.vocab_size, d_model=32,
                              n_layers=1, moe=base.moe,
                              aux_weight=aux_weight, dp_axis=None)
        params = moe.lm_init(cfg, jax.random.PRNGKey(3))
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        step = jax.jit(moe.make_train_step(cfg, opt))
        tokens, targets = _data(cfg, batch=32, seq=8, seed=4)
        for _ in range(30):
            params, opt_state, _ = step(params, opt_state, tokens, targets)
        x = np.asarray(params["embed"])[np.asarray(tokens).reshape(-1)]
        logits = x @ np.asarray(params["layers"][0]["router"])
        return np.bincount(np.argmax(logits, axis=-1),
                           minlength=cfg.moe.n_experts)

    counts_aux = train(0.05)
    counts_ctrl = train(0.0)
    assert counts_aux.max() < 0.6 * counts_aux.sum(), counts_aux
    # The aux run must be at least as balanced as the control (both runs
    # are fully deterministic, so this cannot flake).
    assert counts_aux.max() <= counts_ctrl.max(), (counts_aux, counts_ctrl)


def test_top2_is_convex_mixture_of_experts():
    """With zero drops, top-2 output must equal g1·E_a(x) + g2·E_b(x)
    with normalized gates g1+g2=1 — checked against a dense per-expert
    computation of the same params."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=8.0, router_top_k=2, ep_axis=None)
    params = moe.init_params(cfg, jax.random.PRNGKey(5))
    x = jnp.asarray(np.random.RandomState(6).randn(24, 16), jnp.float32)
    y, aux, zl = moe.moe_ffn(x, params, cfg)

    logits = np.asarray(x @ params["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    w1, w2 = np.asarray(params["w1"]), np.asarray(params["w2"])
    xn = np.asarray(x)
    # Dense evaluation of every expert on every token.
    h = np.einsum("sd,edf->esf", xn, w1)
    h = h * (1.0 / (1.0 + np.exp(-h)))          # silu
    dense = np.einsum("esf,efd->esd", h, w2)    # [E, S, D]
    order = np.argsort(-probs, axis=-1)
    e1, e2 = order[:, 0], order[:, 1]
    g1 = probs[np.arange(24), e1]
    g2 = probs[np.arange(24), e2]
    gsum = g1 + g2
    expect = ((g1 / gsum)[:, None] * dense[e1, np.arange(24)]
              + (g2 / gsum)[:, None] * dense[e2, np.arange(24)])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-5, atol=2e-5)
    assert float(aux) > 0 and float(zl) > 0


def test_top2_capacity_scales_with_k():
    cfg1 = moe.MoEConfig(n_experts=8, capacity_factor=1.0, router_top_k=1)
    cfg2 = moe.MoEConfig(n_experts=8, capacity_factor=1.0, router_top_k=2)
    assert cfg2.capacity(64) == 2 * cfg1.capacity(64)


def test_z_loss_shrinks_router_logits():
    """Training with the z-loss must end with smaller router logits than
    the z_weight=0 control (both deterministic — cannot flake)."""
    def final_z(z_weight):
        cfg = _cfg(ep_axis=None, dp_axis=None, z_weight=z_weight)
        params = moe.lm_init(cfg, jax.random.PRNGKey(7))
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        step = jax.jit(moe.make_train_step(cfg, opt))
        tokens, targets = _data(cfg, batch=16, seq=8, seed=8)
        for _ in range(25):
            params, opt_state, _ = step(params, opt_state, tokens, targets)
        x = np.asarray(params["embed"])[np.asarray(tokens).reshape(-1)]
        logits = x @ np.asarray(params["layers"][0]["router"])
        from scipy.special import logsumexp
        return float(np.mean(logsumexp(logits, axis=-1) ** 2))

    assert final_z(1.0) < final_z(0.0)


def test_expert_choice_routing():
    """expert_choice mode: every expert serves EXACTLY its C slots (full
    static utilization), combine weights are the raw router probs of the
    chosen (token, expert) pairs, aux is 0 (balanced by construction),
    and the ep-sharded LM run still matches unsharded exactly."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=1.0, router_mode="expert_choice",
                        ep_axis=None)
    params = moe.init_params(cfg, jax.random.PRNGKey(13))
    S = 32
    x = jnp.asarray(np.random.RandomState(14).randn(S, 16), jnp.float32)
    dispatch, combine, aux, zl = moe._route(x, params["router"], cfg, None)
    C = cfg.capacity(S)
    # Exactly C tokens per expert, every slot filled exactly once.
    np.testing.assert_array_equal(
        np.asarray(dispatch.sum(axis=(0, 2))), np.full(4, C))
    np.testing.assert_array_equal(
        np.asarray(dispatch.sum(axis=0)), np.ones((4, C)))
    assert float(aux) == 0.0 and float(zl) > 0.0
    # Combine weight of each chosen pair equals its router prob.
    logits = np.asarray(x @ params["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    d = np.asarray(dispatch)
    cw = np.asarray(combine).sum(-1)   # [S, E]
    chosen = d.sum(-1) > 0
    np.testing.assert_allclose(cw[chosen],
                               probs[chosen], rtol=1e-5)
    y, aux2, _ = moe.moe_ffn(x, params, cfg)
    assert np.isfinite(np.asarray(y)).all()

    # ep-sharded EC == the SHARD-EQUIVALENT local computation.  Unlike
    # token-choice (per-token argmax ⇒ sharded == full-batch unsharded
    # when nothing drops), expert-choice selection depends on the token
    # set — each (dp, ep) coordinate picks top-C over ITS shard.  The
    # exactness contract is therefore: the sharded loss equals the mean
    # of per-shard losses computed locally with all experts resident —
    # which pins the alltoall dispatch/return path to exact math.
    ec_local = moe.MoELMConfig(
        vocab_size=64, d_model=32, n_layers=2,
        moe=moe.MoEConfig(d_model=32, d_ff=64, n_experts=8,
                          capacity_factor=2.0,
                          router_mode="expert_choice", ep_axis=None),
        dp_axis=None)
    rp0 = moe.lm_init(ec_local, jax.random.PRNGKey(0))
    tokens, targets = _data(ec_local)
    # Mesh (dp=4, ep=2) flattened in data-spec order = 8 equal row
    # shards in index order.
    shard_losses = [
        float(moe.lm_loss(rp0, tokens[2 * i:2 * i + 2],
                          targets[2 * i:2 * i + 2], ec_local))
        for i in range(8)]

    ec_cfg = moe.MoELMConfig(
        vocab_size=64, d_model=32, n_layers=2,
        moe=moe.MoEConfig(d_model=32, d_ff=64, n_experts=8,
                          capacity_factor=2.0,
                          router_mode="expert_choice", ep_axis="ep"),
        dp_axis="dp")
    mesh = infer_mesh(8, ep=2)
    opt = optax.sgd(0.1)
    sp = moe.lm_init(ec_cfg, jax.random.PRNGKey(0))
    pspecs = moe.lm_param_specs(ec_cfg)
    sst = opt.init(sp)
    os_specs = spmd.infer_specs_like(sst, sp, pspecs)
    step = spmd.make_sharded_train_step(
        moe.make_train_step(ec_cfg, opt), mesh, pspecs, os_specs,
        P(("dp", "pp", "sp", "tp", "ep")))
    sp = spmd.shard_params(sp, pspecs, mesh)
    _, _, loss = step(sp, sst, tokens, targets)
    np.testing.assert_allclose(float(loss), np.mean(shard_losses),
                               rtol=2e-4)

    # Guardrails.
    with pytest.raises(ValueError, match="router_top_k must stay 1"):
        moe._route(x, params["router"],
                   moe.MoEConfig(d_model=16, n_experts=4,
                                 router_mode="expert_choice",
                                 router_top_k=2, ep_axis=None), None)
    with pytest.raises(ValueError, match="router_mode"):
        moe._route(x, params["router"],
                   moe.MoEConfig(d_model=16, n_experts=4,
                                 router_mode="bogus", ep_axis=None), None)


def test_router_jitter_rng_threading():
    """router_noise > 0: rng is REQUIRED (clear error without), changes
    routing between different keys, and the with_rng train step runs."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=2.0, router_noise=5.0, ep_axis=None)
    params = moe.init_params(cfg, jax.random.PRNGKey(9))
    x = jnp.asarray(np.random.RandomState(10).randn(32, 16), jnp.float32)
    with pytest.raises(ValueError, match="router_noise"):
        moe.moe_ffn(x, params, cfg)
    y1, _, _ = moe.moe_ffn(x, params, cfg, rng=jax.random.PRNGKey(1))
    y2, _, _ = moe.moe_ffn(x, params, cfg, rng=jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(y1), np.asarray(y2))

    lm = _cfg(ep_axis=None, dp_axis=None)
    lm = moe.MoELMConfig(
        vocab_size=lm.vocab_size, d_model=32, n_layers=2,
        moe=moe.MoEConfig(d_model=32, d_ff=64, n_experts=8,
                          capacity_factor=2.0, router_noise=1.0,
                          ep_axis=None),
        dp_axis=None)
    params = moe.lm_init(lm, jax.random.PRNGKey(11))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(moe.make_train_step(lm, opt, with_rng=True))
    tokens, targets = _data(lm)
    p2, _, loss = step(params, opt_state, tokens, targets,
                       jax.random.PRNGKey(12))
    assert np.isfinite(float(loss))
