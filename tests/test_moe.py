"""MoE + expert parallelism: the ep-sharded layer must match the
unsharded computation numerically (same assertion pattern as
test_llama_parallel.py — SURVEY.md §4 collective-vs-local)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import moe
from horovod_tpu.parallel import spmd
from horovod_tpu.parallel.mesh import infer_mesh


def _cfg(ep_axis, dp_axis, capacity_factor=8.0, n_experts=8):
    # capacity_factor = n_experts → zero drops, so sharded and unsharded
    # runs keep the same tokens and must agree exactly.
    return moe.MoELMConfig(
        vocab_size=64, d_model=32, n_layers=2,
        moe=moe.MoEConfig(d_model=32, d_ff=64, n_experts=n_experts,
                          capacity_factor=capacity_factor,
                          ep_axis=ep_axis),
        dp_axis=dp_axis)


def _data(cfg, batch=16, seq=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                        jnp.int32),
            jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                        jnp.int32))


@functools.lru_cache(maxsize=None)
def _reference_run(steps=2):
    cfg = _cfg(ep_axis=None, dp_axis=None)
    params = moe.lm_init(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(moe.make_train_step(cfg, opt))
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses, params


@pytest.mark.parametrize("ep,dp_extra", [(2, 4), (4, 2), (8, 1)])
def test_expert_parallel_matches_reference(ep, dp_extra):
    ref_losses, ref_params = _reference_run()

    cfg = _cfg(ep_axis="ep", dp_axis="dp")
    mesh = infer_mesh(8, ep=ep)
    assert mesh.shape["dp"] == dp_extra
    params = moe.lm_init(cfg, jax.random.PRNGKey(0))
    pspecs = moe.lm_param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    # Tokens are DATA-split over dp AND ep (GShard layout).
    data_spec = P(("dp", "pp", "sp", "tp", "ep"))

    step = spmd.make_sharded_train_step(
        moe.make_train_step(cfg, opt), mesh, pspecs, os_specs, data_spec)
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    out = jax.tree_util.tree_map(np.asarray, params)
    ref = jax.tree_util.tree_map(np.asarray, ref_params)
    for (ka, a), (kb, b) in zip(jax.tree_util.tree_leaves_with_path(out),
                                jax.tree_util.tree_leaves_with_path(ref)):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5,
                                   err_msg=str(ka))


def test_capacity_drops_are_identity():
    """Over-capacity tokens contribute zero MoE output (the caller's
    residual passes them through): with capacity_factor tiny, the layer
    output must be zero for dropped tokens and finite everywhere."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=0.25, ep_axis=None)
    params = moe.init_params(cfg, jax.random.PRNGKey(1))
    x = jnp.asarray(np.random.RandomState(2).randn(32, 16), jnp.float32)
    y, aux = moe.moe_ffn(x, params, cfg)
    assert np.isfinite(np.asarray(y)).all()
    assert float(aux) > 0
    # capacity(32) with cf=.25 over 4 experts = 2 slots/expert → ≤ 8 rows
    # can be nonzero.
    nonzero_rows = int(np.sum(np.any(np.asarray(y) != 0.0, axis=1)))
    assert nonzero_rows <= 4 * cfg.capacity(32)


def test_aux_loss_balances_router():
    """Training WITH the aux loss spreads tokens across experts at least
    as well as the aux_weight=0 control — proving the aux gradient is
    live, not just that this task happens to balance."""
    def train(aux_weight):
        base = _cfg(ep_axis=None, dp_axis=None)
        cfg = moe.MoELMConfig(vocab_size=base.vocab_size, d_model=32,
                              n_layers=1, moe=base.moe,
                              aux_weight=aux_weight, dp_axis=None)
        params = moe.lm_init(cfg, jax.random.PRNGKey(3))
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        step = jax.jit(moe.make_train_step(cfg, opt))
        tokens, targets = _data(cfg, batch=32, seq=8, seed=4)
        for _ in range(30):
            params, opt_state, _ = step(params, opt_state, tokens, targets)
        x = np.asarray(params["embed"])[np.asarray(tokens).reshape(-1)]
        logits = x @ np.asarray(params["layers"][0]["router"])
        return np.bincount(np.argmax(logits, axis=-1),
                           minlength=cfg.moe.n_experts)

    counts_aux = train(0.05)
    counts_ctrl = train(0.0)
    assert counts_aux.max() < 0.6 * counts_aux.sum(), counts_aux
    # The aux run must be at least as balanced as the control (both runs
    # are fully deterministic, so this cannot flake).
    assert counts_aux.max() <= counts_ctrl.max(), (counts_aux, counts_ctrl)
