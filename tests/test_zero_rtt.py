"""Protocol-v7 downgrade matrix (tier-1, no jax / no spawns).

The zero-RTT warm path must be INVISIBLE to older peers: a v7 client
against a v5/v6-era server (simulated faithfully at the wire level — the
native server is always current, so the old server is a Python fake
speaking the pre-v7 response format), pre-v7 clients against the v7
server, and mixed-version fleets must all negotiate cleanly with
speculation and pipelining silently disabled and no wire bytes changed
for the old side.  The positive-path frame guards live in
``tests/test_response_cache.py``; the cross-process fault sweep with
pipelining on lives in ``tests/test_multiprocess.py``.
"""

import socket
import struct
import threading

import numpy as np

from horovod_tpu.common.controller import TCPController

_MON_MAGIC = 0x314E4F4D
_FLT_MAGIC = 0x31544C46
_AGG_MAGIC = 0x35474741
_LVE_MAGIC = 0x3645564C


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class E:
    """Minimal negotiable entry (the controller only getattr-probes it)."""

    def __init__(self, name, shape=(4,)):
        self.name = name
        self.tensor = np.zeros((2,) + tuple(shape), np.float32)
        self.group_id = -1


def _steps(ctl, make_entries, n_steps, max_rounds=20):
    orders = []
    for _ in range(n_steps):
        entries = list(make_entries())
        got = []
        for _round in range(max_rounds):
            if not entries:
                break
            ready, errs = ctl.negotiate(entries)
            assert not errs, errs
            got += [e.name for e in ready]
            entries = [e for e in entries if e.name not in set(got)]
        assert not entries, f"never became ready: {[e.name for e in entries]}"
        orders.append(tuple(got))
    return orders


def _pair(fn, per_rank=None, **ctl_kwargs):
    """Two controller clients against the REAL native server; shared
    kwargs via ``ctl_kwargs``, or per-rank dicts via ``per_rank`` (a
    {rank: kwargs} mapping — the mixed-version matrix case)."""
    port = _free_port()
    results, errors = {}, {}
    peer_done = threading.Event()

    def kwargs_for(rank):
        if per_rank is not None:
            return per_rank.get(rank, {})
        return ctl_kwargs

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0, **kwargs_for(rank))
        try:
            results[rank] = fn(ctl, rank)
        except Exception as exc:  # noqa: BLE001 - surfaced by the assert
            errors[rank] = exc
        finally:
            if rank == 1:
                peer_done.set()
                ctl.shutdown()
            else:
                peer_done.wait(timeout=20)
                ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(timeout=20)
    assert not errors, errors
    assert set(results) == {0, 1}, results
    return results


# --------------------------------------------- pre-v7 clients, v7 server
def test_pre_v7_clients_against_v7_server():
    """Old clients (no ZRT7 ad, trailing walk stops at unknown magics)
    against the current native server: negotiation is clean, the warm
    path stays the exact pre-v7 13 bytes, and nothing speculative ever
    engages — the server only predicts once EVERY rank latched v7."""

    def fn(ctl, rank):
        mk = lambda: [E("t")]                        # noqa: E731
        _steps(ctl, mk, 2)
        # The old client never latches (its walk treats ZRT7 as unknown)
        # and the v4/v5/v6 latches it understands still land.
        assert not ctl.peer_zero_rtt_proto
        assert ctl.peer_fault_proto and ctl.peer_leave_proto
        b0, r0 = ctl.bytes_sent, ctl.rounds
        orders = _steps(ctl, mk, 4)
        per_round = (ctl.bytes_sent - b0) / (ctl.rounds - r0)
        assert per_round == 13, per_round
        assert ctl.spec_rounds == 0 and not ctl._predicted
        return orders

    # spec armed server-side: it must still never predict to old clients.
    res = _pair(fn, zero_rtt=False, spec_ready_after=1)
    assert res[0] == res[1]


def test_mixed_version_fleet_silently_disables_speculation():
    """One v7 rank + one pre-v7 rank: the server withholds predictions
    (the all-ranks-v7 gate), so the v7 rank never speculates, no response
    byte changes for the old rank, and verdicts stay identical."""

    def fn(ctl, rank):
        mk = lambda: [E("t"), E("u")]                # noqa: E731
        orders = _steps(ctl, mk, 5)
        if rank == 0:
            assert ctl.peer_zero_rtt_proto           # ad latched fine...
            assert ctl.spec_rounds == 0              # ...but never predicted
            assert not ctl._predicted
        else:
            assert not ctl.peer_zero_rtt_proto
        b0, r0 = ctl.bytes_sent, ctl.rounds
        _steps(ctl, mk, 3)
        per_round = (ctl.bytes_sent - b0) / (ctl.rounds - r0)
        assert per_round == 13, (rank, per_round)    # no confirm ever sent
        return orders

    res = _pair(fn, per_rank={0: dict(spec_ready_after=1),
                              1: dict(zero_rtt=False, spec_ready_after=1)})
    assert res[0] == res[1]


def test_spec_ready_after_gates_engagement_conservatively():
    """The knob is live on BOTH sides: the server waits k
    ready-on-first-announce rounds before predicting, and the client
    waits k consecutive prediction-bearing responses before consuming —
    so a larger k engages speculation strictly later (the conservatism
    axis the autotune coordinate walks), while both eventually engage on
    a stable workload."""
    counts = {}
    for k in (1, 3):
        def fn(ctl, rank):
            _steps(ctl, lambda: [E("t")], 10)
            return ctl.spec_rounds

        res = _pair(fn, spec_ready_after=k)
        assert res[0] == res[1], res
        counts[k] = res[0]
    assert counts[1] > counts[3] >= 1, counts


# ------------------------------------ per-slot withholding (ISSUE 12)
class _Script:
    """Scripted lock-step round driver: tracks announced-but-pending
    entries so a tensor resolving across rounds keeps being passed back
    into negotiate (the engine's requeue contract)."""

    def __init__(self, ctl):
        self.ctl = ctl
        self.pending = {}

    def round(self, new_names):
        entries = list(self.pending.values())
        entries += [E(n) for n in new_names if n not in self.pending]
        ready, errs = self.ctl.negotiate(entries)
        assert not errs, errs
        for e in entries:
            self.pending[e.name] = e
        for e in ready:
            self.pending.pop(e.name, None)
        return [e.name for e in ready]


def test_unstable_slot_withheld_while_stable_slot_keeps_speculating():
    """ISSUE 12 per-slot speculation opt-out: tensor B's announce pattern
    is unstable (rank 1 periodically announces it one round late), tensor
    A is rock-stable.  The server must WITHHOLD only B from predictions —
    per-slot mispredict backoff with slow decay — so B stops triggering
    mispredicts (each of which zeroes the speculating client's engagement
    streak for ALL slots), and rounds announcing only A keep speculating.
    Without the backoff B re-qualifies after every short stable stretch
    and every cycle costs another fleet-wide disengagement."""

    def fn(ctl, rank):
        s = _Script(ctl)
        # Warmup: A and B both stable -> both predicted (k=1).
        for _ in range(4):
            s.round(["A", "B"])
        # Churn cycles: 3 stable rounds, then B resolves across TWO
        # rounds (rank 1 announces it one round late) — same round count
        # on both ranks, so the fleet stays lock-step.
        for _cyc in range(5):
            for _ in range(3):
                s.round(["A", "B"])
            if rank == 0:
                s.round(["A", "B"])    # B pending: rank 1 skipped it
                s.round([])            # B resolves when rank 1 announces
            else:
                s.round(["A"])
                s.round(["B"])
        mis_after_churn = ctl.spec_mispredicts
        spec_before_tail = ctl.spec_rounds
        # Tail: A-only steady state — the STABLE slot must still
        # speculate (B's instability was withheld per-slot, not fleet-
        # wide).
        for _ in range(8):
            s.round(["A"])
        # Drain the final deferred response so counters settle.
        s.round([])
        return (mis_after_churn, ctl.spec_mispredicts,
                ctl.spec_rounds - spec_before_tail, ctl.spec_rounds)

    res = _pair(fn, spec_ready_after=1)
    for rank in (0, 1):
        mis_churn, mis_total, tail_spec, total_spec = res[rank]
        # The backoff caps the damage: B is predicted (and mispredicted)
        # at most twice — once from the warmup, once after its first
        # short re-qualification — then stays withheld for good (the
        # slow valid_run decay cannot be earned inside a 3-round stable
        # stretch).  Without the per-slot penalty this is ~1 per cycle.
        assert mis_total <= 3, res
        assert mis_total == mis_churn, res        # tail adds none
        # ...and the stable slot kept speculating through the tail.
        assert tail_spec >= 4, res
        assert total_spec > 0, res


# ------------------------------------- streak carryover (ISSUE 12)
def test_streak_carryover_reengages_speculation_in_o1_rounds():
    """Elastic streak carryover: seeding the server's fresh slots
    (``spec_seed``) and the client consumption gate
    (``spec_streak_hint``) with the previous generation's engagement hint
    re-engages warm speculation in O(1) rounds — strictly more
    speculative rounds than a cold start relearning k rounds from zero,
    on the identical workload."""
    counts = {}
    for seed in (0, 3):
        def fn(ctl, rank):
            _steps(ctl, lambda: [E("t")], 8)
            return ctl.spec_rounds

        res = _pair(fn, spec_ready_after=3, spec_seed=seed,
                    spec_streak_hint=seed)
        assert res[0] == res[1], res
        counts[seed] = res[0]
    assert counts[3] > counts[0] >= 0, counts
    # O(1): the seeded run speculates on nearly every step (the first
    # step learns the slot; prediction + consumption engage immediately
    # after), while the cold run pays ~2k rounds of relearning first.
    assert counts[3] >= 5, counts


def test_spec_carry_hint_captures_engagement():
    """The hint a re-rendezvous survivor carries (basics.shutdown →
    init): non-zero exactly when speculation was armed, advertised, and
    actually engaged in this generation."""

    def fn(ctl, rank):
        _steps(ctl, lambda: [E("t")], 6)
        return ctl.spec_carry_hint()

    res = _pair(fn, spec_ready_after=1)
    assert res[0] >= 1 and res[1] >= 1, res

    # Control: speculation disabled -> nothing to carry.
    def fn_off(ctl, rank):
        _steps(ctl, lambda: [E("t")], 3)
        return ctl.spec_carry_hint()

    res_off = _pair(fn_off, spec_ready_after=0)
    assert res_off == {0: 0, 1: 0}, res_off


# --------------------------------------------- v7 client, pre-v7 server
class _FakeV6Server:
    """A wire-faithful v5/v6-era coordinator for ONE client: full-string
    negotiation (no slot assignments — pre-v7 servers had them, but
    withholding them exercises the client's permanent full-announce
    path), round-1 FLT1/AGG5/LVE6 ads, and NO ZRT7 anything.  Ignores
    request trailing sections it does not understand — the documented
    old-peers-ignore-trailing-bytes contract the v7 ad rides on."""

    def __init__(self):
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(1)
        self.port = self._lsock.getsockname()[1]
        self.rounds = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _read_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _run(self):
        try:
            conn, _ = self._lsock.accept()
            self._read_exact(conn, 4)                # rank handshake
            while True:
                hdr = self._read_exact(conn, 4)
                if hdr is None:
                    return
                (ln,) = struct.unpack("<I", hdr)
                data = self._read_exact(conn, ln) if ln else b""
                if data is None:
                    return
                self.rounds += 1
                conn.sendall(self._respond(data))
        except OSError:
            pass
        finally:
            try:
                self._lsock.close()
            except OSError:
                pass

    def _respond(self, data):
        # Parse the announce section only; world=1, so everything
        # announced is immediately ready.  Trailing request sections
        # (including a v7 ad or confirm) are simply never parsed.
        off = 0
        (n_ann,) = struct.unpack_from("<I", data, off)
        off += 4
        ready = []
        for _ in range(n_ann):
            off += 2                                  # required
            fields = []
            for _f in range(5):
                (fl,) = struct.unpack_from("<H", data, off)
                off += 2
                fields.append(data[off:off + fl])
                off += fl
            name, digest, group = fields[0], fields[1], fields[2]
            ready.append((name, digest, group))
        resp = struct.pack("<I", len(ready))
        for name, digest, group in ready:
            for f in (name, digest, group):
                resp += struct.pack("<H", len(f)) + f
        resp += struct.pack("<I", 0)                  # warns
        resp += struct.pack("<I", 0)                  # errors
        resp += struct.pack("<I", 0)                  # assigns
        resp += struct.pack("<I", 0)                  # ready bitvector
        resp += struct.pack("<I", 0)                  # evictions
        resp += struct.pack("<II", _MON_MAGIC, 0)     # v3 ad
        if self.rounds == 1:
            resp += struct.pack("<II", _FLT_MAGIC, 0)       # v4 ad
            resp += struct.pack("<II", _AGG_MAGIC, 0)       # v5 ad
            resp += struct.pack("<III", _LVE_MAGIC, 4, 0)   # v6 ad
        return struct.pack("<I", len(resp)) + resp

    def stop(self):
        try:
            self._lsock.close()
        except OSError:
            pass


def test_v7_client_against_pre_v6_server_downgrades_cleanly():
    """A v7 client (speculation armed, ads sent) against a v5/v6-era
    server: the old server ignores the trailing ZRT7 ad, never predicts,
    and the client silently stays lock-step — clean verdicts, zero
    speculative rounds, no prediction state."""
    srv = _FakeV6Server()
    try:
        ctl = TCPController("127.0.0.1", srv.port, rank=1, world=2,
                            stall_warn_s=60.0, spec_ready_after=1)
        try:
            orders = _steps(ctl, lambda: [E("t"), E("u")], 4)
            assert orders and all(set(o) == {"t", "u"} for o in orders)
            # v4/v5/v6 latched from the old server's ads; v7 never.
            assert ctl.peer_fault_proto and ctl.peer_hier_proto
            assert ctl.peer_leave_proto
            assert not ctl.peer_zero_rtt_proto
            assert ctl.spec_rounds == 0 and not ctl._predicted
            assert ctl.spec_hits == 0 and ctl.spec_mispredicts == 0
        finally:
            ctl.shutdown()
    finally:
        srv.stop()


def test_v7_pipelined_client_against_pre_v6_server():
    """Round pipelining is purely client-side (the server's reassembly
    buffer already accepts early frames — true of the old server too, it
    reads frames sequentially), so depth 2 against the pre-v7 server
    still negotiates every verdict, one call late."""
    srv = _FakeV6Server()
    try:
        ctl = TCPController("127.0.0.1", srv.port, rank=1, world=2,
                            stall_warn_s=60.0, round_pipeline=2)
        try:
            orders = _steps(ctl, lambda: [E("t")], 5)
            assert all(o == ("t",) for o in orders)
            assert ctl.inflight_high_water >= 1
        finally:
            ctl.shutdown()
    finally:
        srv.stop()
