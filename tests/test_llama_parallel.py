"""Flagship-model parallelism correctness: dp/tp/sp sharded training must
match the single-device reference run numerically.

This is the rebuild's analogue of the reference's collective-vs-local
assertions (SURVEY.md §4) applied at full-model scale: if the Megatron tp
operators, ring attention, and gradient psums are right, a sharded step is
bit-compatible (up to fp tolerance) with the unsharded one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models import llama
from horovod_tpu.parallel import spmd
from horovod_tpu.parallel.mesh import infer_mesh
from jax.sharding import PartitionSpec as P


def _data(cfg, batch=8, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    targets = rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


def _reference_run(steps=2, batch=8, seq=16, n_layers=2):
    """Unsharded single-device ground truth (all axes disabled, f32)."""
    cfg = llama.tiny(dtype=jnp.float32, n_layers=n_layers, dp_axis=None,
                     tp_axis=None, sp_axis=None)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(llama.make_train_step(cfg, opt))
    tokens, targets = _data(cfg, batch, seq)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses, params


@pytest.mark.parametrize("tp,sp", [(1, 1), (2, 1), (1, 2), (2, 2)])
def test_sharded_matches_reference(tp, sp):
    ref_losses, ref_params = _reference_run()

    cfg = llama.tiny(dtype=jnp.float32)
    mesh = infer_mesh(8, tp=tp, sp=sp)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    data_spec = P(("dp", "ep", "pp"), "sp")  # batch over dp, seq over sp

    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs, data_spec)

    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    # Parameters after 2 steps must agree leaf-for-leaf.
    ref_leaves = jax.tree_util.tree_leaves(ref_params)
    out_leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, params))
    for a, b in zip(out_leaves, ref_leaves):
        np.testing.assert_allclose(a, np.asarray(b), rtol=3e-3, atol=3e-5)


@pytest.mark.parametrize("sp,tp,heads,kv_heads", [
    (2, 1, 4, 2),
    (2, 2, 8, 4),   # per-tp-shard kv heads (2) still divide by sp
    (4, 1, 8, 4),
])
def test_ulysses_sp_matches_reference(sp, tp, heads, kv_heads):
    """sp_impl="ulysses" (head-exchange sequence parallelism) trains
    numerics-identical to the unsharded reference, like the ring path.
    Ulysses needs (kv_heads / tp) % sp == 0 — GQA kv travels un-repeated."""
    hkw = dict(n_heads=heads, n_kv_heads=kv_heads)
    cfg_ref = llama.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None,
                         sp_axis=None, **hkw)
    params = llama.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    rstep = jax.jit(llama.make_train_step(cfg_ref, opt))
    tokens, targets = _data(cfg_ref)
    ref_losses = []
    for _ in range(2):
        params, opt_state, loss = rstep(params, opt_state, tokens, targets)
        ref_losses.append(float(loss))

    cfg = llama.tiny(dtype=jnp.float32, sp_impl="ulysses", **hkw)
    mesh = infer_mesh(8, tp=tp, sp=sp)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        P(("dp", "ep", "pp"), "sp"))
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)


@pytest.mark.parametrize("pp,tp,sp,n_micro,pp_loss", [
    (2, 1, 1, 2, "broadcast"),   # pure pp
    (2, 1, 1, 4, "broadcast"),   # more microbatches than stages
    (4, 1, 1, 2, "broadcast"),   # deeper pipeline (1-layer slabs, 4 layers)
    (2, 2, 1, 2, "broadcast"),   # pp × tp
    (2, 1, 2, 2, "broadcast"),   # pp × sp (ring attention inside a stage)
    # last_stage loss: no [M,mb,T,D] activation broadcast — only the
    # scalar partial rides the psum (VERDICT r4 weak #5); must be
    # numerics-identical to broadcast AND the unsharded reference.
    (2, 1, 1, 2, "last_stage"),
    (4, 1, 1, 2, "last_stage"),
    (2, 2, 1, 2, "last_stage"),
    (2, 1, 2, 2, "last_stage"),
])
def test_pipeline_matches_reference(pp, tp, sp, n_micro, pp_loss):
    """pp=k training ≡ unsharded reference: stacked layer slabs over the pp
    axis, GPipe schedule, grads reassembled by sync_grads (VERDICT r3 weak
    #5a: pipeline parallelism must compose with the flagship model)."""
    n_layers = 4 if pp == 4 else 2
    # batch 16: per-shard batch stays divisible by n_micro at every dp size.
    ref_losses, ref_params = _reference_run(n_layers=n_layers, batch=16)

    cfg = llama.tiny(dtype=jnp.float32, n_layers=n_layers,
                     pp_axis="pp", n_microbatches=n_micro,
                     pp_loss=pp_loss)
    mesh = infer_mesh(8, tp=tp, sp=sp, pp=pp)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    # Batch over dp/ep only — every pipeline stage sees the same tokens.
    data_spec = P(("dp", "ep"), "sp")

    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs, data_spec)

    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg, batch=16)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    # Stacked slab layout vs the reference's per-layer list: compare
    # layer-by-layer through the stack axis.
    stacked = jax.tree_util.tree_map(np.asarray, params)
    for i, ref_layer in enumerate(ref_params["layers"]):
        for k, ref_w in ref_layer.items():
            np.testing.assert_allclose(
                stacked["layers"][k][i], np.asarray(ref_w),
                rtol=3e-3, atol=3e-5, err_msg=f"layer {i} {k}")
    for k in ("embed", "final_norm", "lm_head"):
        np.testing.assert_allclose(stacked[k], np.asarray(ref_params[k]),
                                   rtol=3e-3, atol=3e-5, err_msg=k)


def test_pipeline_remat_matches_reference():
    """remat_stages=True (jax.checkpoint around each stage) must be
    numerics-identical to the stored-activation pipeline AND the unsharded
    reference — remat changes memory, never math."""
    ref_losses, ref_params = _reference_run(batch=16)

    cfg = llama.tiny(dtype=jnp.float32, pp_axis="pp", n_microbatches=2,
                     remat_stages=True)
    mesh = infer_mesh(8, pp=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        P(("dp", "ep"), "sp"))
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg, batch=16)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)


@pytest.mark.parametrize("ep,tp", [(2, 1), (4, 1), (2, 2)])
def test_llama_moe_matches_reference(ep, tp):
    """MoE llama with experts sharded over ep (tokens data-split over
    dp×ep, alltoall dispatch) == the unsharded MoE run.  capacity_factor
    = n_experts ⇒ zero drops, so both layouts keep every token.
    aux_weight=0 because the router-balance loss is PER-SHARD by design
    (Switch/GShard semantics: token_frac·prob_frac is nonlinear, so the
    shard mean differs from the global value — a modeling choice, not an
    implementation error); the exact-math contract covers everything
    else."""
    kw = dict(dtype=jnp.float32, n_experts=4, capacity_factor=4.0,
              aux_weight=0.0)
    cfg_ref = llama.tiny(dp_axis=None, tp_axis=None, sp_axis=None, **kw)
    params = llama.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(llama.make_train_step(cfg_ref, opt))
    tokens, targets = _data(cfg_ref, batch=16)
    ref_losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        ref_losses.append(float(loss))
    ref_params = params

    cfg = llama.tiny(ep_axis="ep", **kw)
    mesh = infer_mesh(8, tp=tp, ep=ep)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    step = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        P(("dp", "ep", "pp"), "sp"))   # batch over dp AND ep
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg, batch=16)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(
                jax.tree_util.tree_map(np.asarray, params)),
            jax.tree_util.tree_leaves_with_path(
                jax.tree_util.tree_map(np.asarray, ref_params))):
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=3e-5,
                                   err_msg=str(ka))


def test_llama_moe_pp_composes():
    """MoE + pipeline parallelism: the aux loss rides the pipeline carry
    (per-stage partials, psum'd over pp).  Exact-math check at
    aux_weight=0 vs the unsharded MoE run, plus an aux>0 run proving the
    composition trains (finite loss, params move)."""
    kw = dict(dtype=jnp.float32, n_experts=4, capacity_factor=4.0,
              aux_weight=0.0)
    cfg_ref = llama.tiny(dp_axis=None, tp_axis=None, sp_axis=None, **kw)
    params = llama.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(llama.make_train_step(cfg_ref, opt))
    tokens, targets = _data(cfg_ref, batch=16)
    ref_losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        ref_losses.append(float(loss))

    cfg = llama.tiny(ep_axis="ep", pp_axis="pp", n_microbatches=2, **kw)
    mesh = infer_mesh(8, pp=2, ep=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    pspecs = llama.param_specs(cfg)
    opt_state = opt.init(params)
    os_specs = spmd.infer_specs_like(opt_state, params, pspecs)
    pstep = spmd.make_sharded_train_step(
        llama.make_train_step(cfg, opt), mesh, pspecs, os_specs,
        P(("dp", "ep"), None))
    params = spmd.shard_params(params, pspecs, mesh)
    tokens, targets = _data(cfg, batch=16)
    losses = []
    for _ in range(2):
        params, opt_state, loss = pstep(params, opt_state, tokens, targets)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)

    # aux>0: prove the aux actually rides the pipeline carry into the
    # loss with the right magnitude.  Switch aux ∈ [1, E] per layer (1 at
    # perfect balance, E at collapse), and the pp path averages over
    # microbatches, so (loss_w − loss_0)/w must land in [1, E] — this
    # fails both if the carry plumbing returns 0 and if the per-microbatch
    # sum is not normalized (which would give ≈ n_microbatches × aux).
    w = 0.05
    first_losses = {}
    for aw in (0.0, w):
        cfg_a = llama.tiny(ep_axis="ep", pp_axis="pp", n_microbatches=2,
                           dtype=jnp.float32, n_experts=4,
                           capacity_factor=4.0, aux_weight=aw)
        params_a = llama.init_params(cfg_a, jax.random.PRNGKey(0))
        opt_state_a = opt.init(params_a)
        specs_a = llama.param_specs(cfg_a)
        os_specs_a = spmd.infer_specs_like(opt_state_a, params_a, specs_a)
        astep = spmd.make_sharded_train_step(
            llama.make_train_step(cfg_a, opt), mesh, specs_a, os_specs_a,
            P(("dp", "ep"), None))
        params_a = spmd.shard_params(params_a, specs_a, mesh)
        _, _, loss_a = astep(params_a, opt_state_a, tokens, targets)
        first_losses[aw] = float(loss_a)
    ratio = (first_losses[w] - first_losses[0.0]) / w
    assert 1.0 - 1e-3 <= ratio <= 4.0 + 1e-3, ratio


def test_kv_cache_decode_matches_forward():
    """Cached greedy decode == argmax of the full-context forward at every
    generated position (teacher-forced equivalence: the KV cache is exact,
    not an approximation)."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=64, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(5))
    rng = np.random.RandomState(6)
    B, T0, N = 2, 7, 6
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T0)), jnp.int32)

    gen = jax.jit(lambda p, t: llama.generate(p, t, N, cfg))(params, prompt)
    assert gen.shape == (B, N)

    # Reference: recompute the FULL forward over (prompt + generated so
    # far) with no cache; its last-position argmax must reproduce each
    # generated token.
    seq = prompt
    for i in range(N):
        logits = llama.forward(params, seq, cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))
        np.testing.assert_array_equal(np.asarray(gen[:, i]), nxt,
                                      err_msg=f"token {i}")
        seq = jnp.concatenate(
            [seq, jnp.asarray(nxt, jnp.int32)[:, None]], axis=1)


def test_kv_cache_decode_moe():
    """Decode works through the MoE MLP too (routing per decoded token)."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=32, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False,
                     n_experts=4, capacity_factor=4.0)
    params = llama.init_params(cfg, jax.random.PRNGKey(7))
    prompt = jnp.asarray(
        np.random.RandomState(8).randint(0, cfg.vocab_size, (1, 5)),
        jnp.int32)
    gen = jax.jit(lambda p, t: llama.generate(p, t, 4, cfg))(params, prompt)
    assert gen.shape == (1, 4)
    logits = llama.forward(params, prompt, cfg)
    np.testing.assert_array_equal(
        np.asarray(gen[:, 0]),
        np.asarray(jnp.argmax(logits[:, -1, :], axis=-1)))


def test_entry_forward_single_device():
    """Single-chip jittable forward (the __graft_entry__ contract)."""
    cfg = llama.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None,
                     sp_axis=None)
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    tokens, _ = _data(cfg, batch=2, seq=8)
    logits = jax.jit(lambda p, t: llama.forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_tp_decode_matches_single_device():
    """tp=2 decode (heads split, psum at wo, cache sharded over its head
    axis) must produce the SAME logits as single-device decode at every
    step — prefill included (VERDICT r4 ask #4)."""
    from horovod_tpu.compat import shard_map

    cfg0 = llama.tiny(dtype=jnp.float32, max_seq=32, dp_axis=None,
                      tp_axis=None, sp_axis=None, use_flash=False)
    cfg_tp = llama.tiny(dtype=jnp.float32, max_seq=32, dp_axis=None,
                        tp_axis="tp", sp_axis=None, use_flash=False)
    params = llama.init_params(cfg0, jax.random.PRNGKey(21))
    rng = np.random.RandomState(22)
    B, T0, N = 2, 6, 5
    prompt = jnp.asarray(rng.randint(0, cfg0.vocab_size, (B, T0)),
                         jnp.int32)

    ref = jax.jit(lambda p, t: llama.generate(p, t, N, cfg0))(
        params, prompt)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("tp",))
    pspecs = llama.param_specs(cfg_tp)

    def run(p, t):
        return llama.generate(p, t, N, cfg_tp)

    gen = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt)
    np.testing.assert_array_equal(np.asarray(gen), np.asarray(ref))

    # decode_step level too: same logits, not just same argmax.
    cache0 = llama.init_cache(cfg0, B, 32)
    l0, _ = jax.jit(lambda p, c, t: llama.prefill(p, c, t, cfg0))(
        params, cache0, prompt)

    def pf(p, t):
        c = llama.init_cache(cfg_tp, B, 32)
        logits, _ = llama.prefill(p, c, t, cfg_tp)
        return logits

    ltp = jax.jit(shard_map(
        pf, mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt)
    np.testing.assert_allclose(np.asarray(ltp), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)



def test_sampling_modes():
    """temperature/top-k/top-p sampling: greedy default unchanged,
    temperature→0-ish concentrates on the argmax, top_p/top_k masks
    restrict support, rng is required and reproducible."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=32, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(31))
    prompt = jnp.asarray(
        np.random.RandomState(32).randint(0, cfg.vocab_size, (2, 5)),
        jnp.int32)

    greedy = llama.generate(params, prompt, 4, cfg)
    # Tiny temperature ≈ greedy (argmax dominates the categorical).
    near_greedy = llama.generate(params, prompt, 4, cfg, temperature=1e-4,
                                 rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(greedy),
                                  np.asarray(near_greedy))
    # Same rng → same sample; different rng → (almost surely) different.
    s1 = llama.generate(params, prompt, 8, cfg, temperature=5.0,
                        rng=jax.random.PRNGKey(2))
    s2 = llama.generate(params, prompt, 8, cfg, temperature=5.0,
                        rng=jax.random.PRNGKey(2))
    s3 = llama.generate(params, prompt, 8, cfg, temperature=5.0,
                        rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert not np.array_equal(np.asarray(s1), np.asarray(s3))
    with pytest.raises(ValueError, match="rng"):
        llama.generate(params, prompt, 2, cfg, temperature=1.0)

    # Unit level: top_k=1 ≡ greedy regardless of temperature; top_p→0
    # keeps only the argmax.
    logits = jnp.asarray(np.random.RandomState(33).randn(4, 16),
                         jnp.float32)
    am = np.asarray(jnp.argmax(logits, -1))
    k1 = llama.sample_logits(logits, jax.random.PRNGKey(4),
                             temperature=3.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(k1), am)
    p0 = llama.sample_logits(logits, jax.random.PRNGKey(5),
                             temperature=3.0, top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(p0), am)
    # top_k=3: every draw lands in the 3 largest logits.
    draws = [np.asarray(llama.sample_logits(
        logits, jax.random.PRNGKey(i), temperature=5.0, top_k=3))
        for i in range(20)]
    top3 = np.argsort(-np.asarray(logits), axis=-1)[:, :3]
    for d in draws:
        for b in range(4):
            assert d[b] in top3[b]


def test_decode_chunk_matches_step_loop():
    """decode_chunk over [B, Tq] == Tq sequential decode_steps (same
    logits, same cache) — the verify primitive of speculative decoding."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=32, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(41))
    rng = np.random.RandomState(42)
    B, T0, Tq = 2, 4, 5
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T0)), jnp.int32)
    chunk = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, Tq)), jnp.int32)

    _, c0 = llama.prefill(params, llama.init_cache(cfg, B, 32), prompt, cfg)
    cl, cc = llama.decode_chunk(params, c0, chunk, T0, cfg)

    cs = c0
    step_logits = []
    for i in range(Tq):
        li, cs = llama.decode_step(params, cs, chunk[:, i], T0 + i, cfg)
        step_logits.append(np.asarray(li))
    np.testing.assert_allclose(np.asarray(cl),
                               np.stack(step_logits, axis=1),
                               rtol=1e-5, atol=1e-5)
    for lc, ls in zip(cc, cs):
        np.testing.assert_allclose(np.asarray(lc["k"]), np.asarray(ls["k"]),
                                   rtol=1e-5, atol=1e-5)


def test_speculative_generate_matches_greedy():
    """Speculative decoding is EXACT greedy decoding: with a different
    (disagreeing) draft model, with self-speculation (full acceptance),
    and at n_draft=1, the output must equal plain generate()."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=128, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(43))
    draft = llama.init_params(cfg, jax.random.PRNGKey(44))
    prompt = jnp.asarray(
        np.random.RandomState(45).randint(0, cfg.vocab_size, (2, 5)),
        jnp.int32)
    N = 10
    ref = np.asarray(jax.jit(
        lambda p, t: llama.generate(p, t, N, cfg))(params, prompt))

    for dp, nd in ((draft, 3), (params, 4), (draft, 1)):
        spec = np.asarray(jax.jit(
            lambda p, d, t: llama.speculative_generate(
                p, d, t, N, cfg, n_draft=nd))(params, dp, prompt))
        np.testing.assert_array_equal(spec, ref, err_msg=f"n_draft={nd}")


def test_sliding_window_train_and_decode(monkeypatch):
    """Mistral-style sliding-window llama: flash path == jnp path for the
    loss, cached decode == full-context forward argmax, and sp rejects
    the window with a clear error."""
    kw = dict(dtype=jnp.float32, max_seq=64, dp_axis=None, tp_axis=None,
              sp_axis=None, sliding_window=6)
    cfg_jnp = llama.tiny(use_flash=False, **kw)
    cfg_flash = llama.tiny(use_flash=True, **kw)
    params = llama.init_params(cfg_jnp, jax.random.PRNGKey(51))
    tokens, targets = _data(cfg_jnp, batch=2, seq=24)

    l_jnp = float(llama.loss_fn(params, tokens, targets, cfg_jnp))
    l_flash = float(llama.loss_fn(params, tokens, targets, cfg_flash))
    np.testing.assert_allclose(l_flash, l_jnp, rtol=2e-5)
    # The window changes the math (vs full causal attention).
    cfg_full = llama.tiny(use_flash=False, dtype=jnp.float32, max_seq=64,
                          dp_axis=None, tp_axis=None, sp_axis=None)
    l_full = float(llama.loss_fn(params, tokens, targets, cfg_full))
    assert abs(l_full - l_jnp) > 1e-6

    # Cached decode under the window == windowed full-context forward.
    prompt = tokens[:, :7]
    gen = jax.jit(lambda p, t: llama.generate(p, t, 5, cfg_jnp))(
        params, prompt)
    seq = prompt
    for i in range(5):
        logits = llama.forward(params, seq, cfg_jnp)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))
        np.testing.assert_array_equal(np.asarray(gen[:, i]), nxt,
                                      err_msg=f"token {i}")
        seq = jnp.concatenate(
            [seq, jnp.asarray(nxt, jnp.int32)[:, None]], axis=1)

    # sp × window is rejected at trace time.
    cfg_sp = llama.tiny(dtype=jnp.float32, sliding_window=6)
    mesh = infer_mesh(8, sp=2)
    pspecs = llama.param_specs(cfg_sp)
    sp_params = llama.init_params(cfg_sp, jax.random.PRNGKey(52))
    from horovod_tpu.compat import shard_map
    sp_tokens, _ = _data(cfg_sp, batch=8, seq=16, seed=53)
    with pytest.raises(ValueError, match="sliding_window"):
        jax.jit(shard_map(
            lambda p, t: llama.forward(p, t, cfg_sp), mesh=mesh,
            in_specs=(pspecs, P(("dp", "ep", "pp"), "sp")),
            out_specs=P(("dp", "ep", "pp"), "sp"), check_vma=False))(
            sp_params, sp_tokens).block_until_ready()


def test_rolling_cache_matches_full_cache():
    """Rolling (ring-buffer) KV cache for windowed decode: O(W+slack)
    memory, positions wrap — must generate EXACTLY what the full-length
    masked cache generates, across multiple ring wraps, with prompts
    longer than the ring, through speculative decoding, and BEYOND
    max_seq (the unbounded-generation property)."""
    W, slack = 8, 4
    base = dict(dtype=jnp.float32, dp_axis=None, tp_axis=None,
                sp_axis=None, sliding_window=W, use_flash=False)
    cfg_full = llama.tiny(max_seq=64, **base)
    cfg_roll = llama.tiny(max_seq=64, rolling_cache=True,
                          rolling_slack=slack, **base)
    params = llama.init_params(cfg_full, jax.random.PRNGKey(61))
    rng = np.random.RandomState(62)
    prompt = jnp.asarray(rng.randint(0, cfg_full.vocab_size, (2, 10)),
                         jnp.int32)
    N = 20                                   # ring R=12 wraps twice
    ref = np.asarray(jax.jit(
        lambda p, t: llama.generate(p, t, N, cfg_full))(params, prompt))
    roll = np.asarray(jax.jit(
        lambda p, t: llama.generate(p, t, N, cfg_roll))(params, prompt))
    np.testing.assert_array_equal(roll, ref)
    # Ring memory really is O(W + slack).
    c = llama.init_cache(cfg_roll, 2)
    assert c[0]["k"].shape[1] == W + slack

    # Prompt longer than the ring.
    prompt2 = jnp.asarray(rng.randint(0, cfg_full.vocab_size, (1, 20)),
                          jnp.int32)
    ref2 = np.asarray(llama.generate(params, prompt2, 6, cfg_full))
    roll2 = np.asarray(llama.generate(params, prompt2, 6, cfg_roll))
    np.testing.assert_array_equal(roll2, ref2)

    # Prompt SHORTER than the window: never-written ring slots derive
    # negative positions and must be masked — qpos-W is negative too in
    # this regime, so the p_j >= 0 term is what excludes them (the
    # review-caught dilution bug).
    prompt3 = jnp.asarray(rng.randint(0, cfg_full.vocab_size, (2, 3)),
                          jnp.int32)
    ref3 = np.asarray(llama.generate(params, prompt3, 8, cfg_full))
    roll3 = np.asarray(llama.generate(params, prompt3, 8, cfg_roll))
    np.testing.assert_array_equal(roll3, ref3)

    # Speculative decoding on the rolling cache (chunk 3 <= slack).
    draft = llama.init_params(cfg_full, jax.random.PRNGKey(63))
    spec = np.asarray(llama.speculative_generate(
        params, draft, prompt, N, cfg_roll, n_draft=2))
    np.testing.assert_array_equal(spec, ref)

    # Chunks beyond the slack are rejected (their earlier rows would
    # attend freshly-overwritten slots).
    cache = llama.init_cache(cfg_roll, 1)
    big = jnp.zeros((1, slack + 1), jnp.int32)
    with pytest.raises(ValueError, match="rolling_slack"):
        llama.decode_chunk(params, cache, big, 0, cfg_roll)

    # Unbounded generation: past max_seq, where the full cache refuses.
    cfg_small = llama.tiny(max_seq=16, **base)
    cfg_small_roll = llama.tiny(max_seq=16, rolling_cache=True,
                                rolling_slack=slack, **base)
    with pytest.raises(ValueError, match="slots"):
        llama.generate(params, prompt, 30, cfg_small)
    long_out = llama.generate(params, prompt, 30, cfg_small_roll)
    assert long_out.shape == (2, 30)
    np.testing.assert_array_equal(
        np.asarray(long_out[:, :N]),
        np.asarray(llama.generate(params, prompt, N, cfg_full)))


def test_kv_cache_budget_enforced():
    """Decoding past the cache raises instead of silently clamping writes
    onto the last slot; n_tokens=0 returns an empty [B, 0]."""
    cfg = llama.tiny(dtype=jnp.float32, max_seq=8, dp_axis=None,
                     tp_axis=None, sp_axis=None, use_flash=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(9))
    prompt = jnp.asarray(
        np.random.RandomState(10).randint(0, cfg.vocab_size, (1, 6)),
        jnp.int32)
    with pytest.raises(ValueError, match="slots"):
        llama.generate(params, prompt, 6, cfg)      # positions 6..11 > 8
    assert llama.generate(params, prompt, 3, cfg).shape == (1, 3)
    assert llama.generate(params, prompt, 0, cfg).shape == (1, 0)
