"""Reusable N-slice simulation harness (promoted from the two-slice
worker preamble, ISSUE 17 satellite).

Two entry points for two process shapes:

- :func:`configure_slice_world` — subprocess workers (``tests/data/
  worker_*.py`` launched by ``torovodrun``): the pre-backend-init env
  dance — strip any inherited ``xla_force_host_platform_device_count``
  flag so stacked callers compose (the harness conftest injects one for
  the in-process 8-device mesh; a worker that wants 4 must not inherit
  8), declare the per-process device count through the compat shim, pin
  the CPU platform + gloo cross-process collectives, and optionally set
  ``HOROVOD_SLICE_MAP`` so the engine sees simulated slice boundaries
  (CPU devices carry no ``slice_index`` attribute).  Must run before
  anything initializes the JAX backend.

- :func:`simulated_slices` — in-process tests on the conftest's 8-device
  CPU mesh: arm an already-built engine's hierarchical mode with a
  simulated N×L slice split, clear the cached topology (the engine
  caches per process set — mutating the knobs without clearing would
  keep serving the old split), yield the derived topology, and restore
  every knob on exit.
"""

from __future__ import annotations

import contextlib
import os


def configure_slice_world(local_devices: int, *, slice_map: str = "",
                          gloo: bool = True):
    """Pre-init setup for one simulated-slice worker process.

    Returns the ``jax`` module so callers can keep configuring it.
    """
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if slice_map:
        os.environ["HOROVOD_SLICE_MAP"] = slice_map
    import jax

    from horovod_tpu.compat import set_host_device_count
    jax.config.update("jax_platforms", "cpu")
    set_host_device_count(int(local_devices))
    if gloo:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    return jax


@contextlib.contextmanager
def simulated_slices(engine, num_slices: int, local_size: int, *,
                     threshold: int = 0):
    """Arm ``engine`` for two-level dispatch over a simulated
    ``num_slices × local_size`` split of its (flat, usually 8-device CPU)
    world; yield the derived ``SliceTopology``; restore on exit.
    """
    saved = (engine.hierarchical_allreduce, engine._hier_local_size,
             engine.slice_map, engine.hier_threshold_bytes)
    engine.hierarchical_allreduce = True
    engine._hier_local_size = int(local_size)
    engine.slice_map = ",".join([str(int(local_size))] * int(num_slices))
    engine.hier_threshold_bytes = int(threshold)
    engine._slice_topos.clear()
    try:
        st = engine._slice_topology(0)
        assert st is not None and st.num_slices == num_slices \
            and st.local_size == local_size, st
        yield st
    finally:
        (engine.hierarchical_allreduce, engine._hier_local_size,
         engine.slice_map, engine.hier_threshold_bytes) = saved
        engine._slice_topos.clear()
