"""The analyzer gates this repo: lint horovod_tpu/ + examples/ in tier-1.

Any new deadlock-prone collective pattern introduced by a future PR fails
here with the finding's rule ID, location and fix hint.  Known, reviewed
findings go in the inline allowlist below — each entry must carry a reason.
"""

import os

from horovod_tpu.analysis import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (rule, path-suffix, line) -> reason.  Line numbers keep the allowlist
# honest: moving/duplicating an allowlisted pattern re-fails the gate.
ALLOWLIST = {
    # (example)
    # ("HVD101", "horovod_tpu/foo.py", 42): "rank-guard is matched by a "
    #     "process_set covering exactly those ranks",
}


def _key(finding):
    rel = os.path.relpath(finding.path, REPO)
    return (finding.rule, rel.replace(os.sep, "/"), finding.line)


def test_self_lint_errors_gate():
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    errors = [f for f in findings
              if f.is_error and _key(f) not in ALLOWLIST]
    assert not errors, (
        "new collective-correctness errors (fix them or allowlist with a "
        "reason):\n" + "\n".join(f.render() for f in errors))


def test_self_lint_warning_budget():
    """Warnings don't fail the gate, but silent growth does: a PR adding
    warning-severity findings must either fix them or consciously raise
    this budget in the same diff."""
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    warnings = [f for f in findings
                if not f.is_error and _key(f) not in ALLOWLIST]
    budget = 0   # current state: repo lints clean
    assert len(warnings) <= budget, (
        f"warning count {len(warnings)} exceeds budget {budget}:\n"
        + "\n".join(f.render() for f in warnings))


def test_self_lint_covers_monitor_package():
    """The monitor subsystem is linted explicitly (not only via the
    package walk, which a future exclude rule could silently narrow):
    its files must parse and carry zero findings of any severity."""
    mon_dir = os.path.join(REPO, "horovod_tpu", "monitor")
    files = [f for f in os.listdir(mon_dir) if f.endswith(".py")]
    assert len(files) >= 5, files       # registry/aggregator/agent/http/CLI
    findings = lint_paths([mon_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_trace_package():
    """Same explicit coverage for the tracing subsystem: core/writer/
    merge/analyze/CLI must parse and lint clean."""
    tr_dir = os.path.join(REPO, "horovod_tpu", "trace")
    files = [f for f in os.listdir(tr_dir) if f.endswith(".py")]
    assert len(files) >= 5, files       # core/writer/merge/analyze/CLI
    findings = lint_paths([tr_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_autoscale_stack():
    """Explicit coverage for the autoscaling subsystem (ISSUE 10): the
    policy engine and the driver/registration/worker layers it drives
    must parse and lint clean."""
    el_dir = os.path.join(REPO, "horovod_tpu", "elastic")
    files = {f for f in os.listdir(el_dir) if f.endswith(".py")}
    assert {"autoscale.py", "driver.py", "registration.py",
            "worker.py"} <= files, files
    findings = lint_paths([el_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_fault_harness():
    """Explicit coverage for the fault-injection harness AND the churn
    runner (ISSUE 12): both drive the control plane from the jax-free
    tier and the bench, and must parse and lint clean."""
    t_dir = os.path.join(REPO, "horovod_tpu", "testing")
    files = {f for f in os.listdir(t_dir) if f.endswith(".py")}
    assert {"faults.py", "churn.py"} <= files, files
    findings = lint_paths([t_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_allowlist_entries_still_fire():
    """Stale allowlist entries (fixed code, moved lines) must be pruned."""
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    live = {_key(f) for f in findings}
    stale = [k for k in ALLOWLIST if k not in live]
    assert not stale, f"allowlist entries no longer fire, remove them: {stale}"
