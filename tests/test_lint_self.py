"""The analyzer gates this repo: lint horovod_tpu/ + examples/ in tier-1.

Any new deadlock-prone collective pattern introduced by a future PR fails
here with the finding's rule ID, location and fix hint.  Known, reviewed
findings go in the inline allowlist below — each entry must carry a reason.
"""

import os

from horovod_tpu.analysis import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (rule, path-suffix, line) -> reason.  Line numbers keep the allowlist
# honest: moving/duplicating an allowlisted pattern re-fails the gate.
ALLOWLIST = {
    # (example)
    # ("HVD101", "horovod_tpu/foo.py", 42): "rank-guard is matched by a "
    #     "process_set covering exactly those ranks",
}


def _key(finding):
    rel = os.path.relpath(finding.path, REPO)
    return (finding.rule, rel.replace(os.sep, "/"), finding.line)


def test_self_lint_errors_gate():
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    errors = [f for f in findings
              if f.is_error and _key(f) not in ALLOWLIST]
    assert not errors, (
        "new collective-correctness errors (fix them or allowlist with a "
        "reason):\n" + "\n".join(f.render() for f in errors))


def test_self_lint_warning_budget():
    """Warnings don't fail the gate, but silent growth does: a PR adding
    warning-severity findings must either fix them or consciously raise
    this budget in the same diff."""
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    warnings = [f for f in findings
                if not f.is_error and _key(f) not in ALLOWLIST]
    budget = 0   # current state: repo lints clean
    assert len(warnings) <= budget, (
        f"warning count {len(warnings)} exceeds budget {budget}:\n"
        + "\n".join(f.render() for f in warnings))


def test_self_lint_covers_monitor_package():
    """The monitor subsystem is linted explicitly (not only via the
    package walk, which a future exclude rule could silently narrow):
    its files must parse and carry zero findings of any severity."""
    mon_dir = os.path.join(REPO, "horovod_tpu", "monitor")
    files = [f for f in os.listdir(mon_dir) if f.endswith(".py")]
    assert len(files) >= 5, files       # registry/aggregator/agent/http/CLI
    findings = lint_paths([mon_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_trace_package():
    """Same explicit coverage for the tracing subsystem: core/writer/
    merge/analyze/CLI must parse and lint clean."""
    tr_dir = os.path.join(REPO, "horovod_tpu", "trace")
    files = [f for f in os.listdir(tr_dir) if f.endswith(".py")]
    assert len(files) >= 5, files       # core/writer/merge/analyze/CLI
    findings = lint_paths([tr_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_autoscale_stack():
    """Explicit coverage for the autoscaling + resilient-state subsystem
    (ISSUES 10/14): the policy engine, the driver/registration/worker
    layers it drives, and the state plane must parse and lint clean."""
    el_dir = os.path.join(REPO, "horovod_tpu", "elastic")
    files = {f for f in os.listdir(el_dir) if f.endswith(".py")}
    assert {"autoscale.py", "driver.py", "registration.py",
            "worker.py", "stateplane.py"} <= files, files
    findings = lint_paths([el_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_slice_topology():
    """Explicit coverage for the two-level data plane's topology module
    (ISSUE 17): ``parallel/topology.py`` is jax-free and feeds the engine
    the (cross, local) mesh structure — it must parse and lint clean."""
    path = os.path.join(REPO, "horovod_tpu", "parallel", "topology.py")
    assert os.path.exists(path), path
    findings = lint_paths([path])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_fault_harness():
    """Explicit coverage for the fault-injection harness AND the churn
    runner (ISSUE 12): both drive the control plane from the jax-free
    tier and the bench, and must parse and lint clean."""
    t_dir = os.path.join(REPO, "horovod_tpu", "testing")
    files = {f for f in os.listdir(t_dir) if f.endswith(".py")}
    assert {"faults.py", "churn.py"} <= files, files
    findings = lint_paths([t_dir])
    assert not findings, "\n".join(f.render() for f in findings)


def test_self_lint_covers_serving_plane():
    """Explicit coverage for the serving plane (ISSUES 19/20): the
    batcher, replica loop, front door, and circuit breaker carry the
    fault-tolerance invariants and must parse and lint clean."""
    sv_dir = os.path.join(REPO, "horovod_tpu", "serve")
    files = {f for f in os.listdir(sv_dir) if f.endswith(".py")}
    assert {"batcher.py", "replica.py", "frontdoor.py",
            "resilience.py"} <= files, files
    findings = lint_paths([sv_dir])
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------------------------------- whole-package gate (13)
_GATE_RESULT = []      # memo: the full-repo analysis runs once per session


def _gate_result():
    if not _GATE_RESULT:
        from horovod_tpu.analysis.gate import run_gate
        _GATE_RESULT.append(run_gate(root=REPO, quiet=True))
    return _GATE_RESULT[0]


def test_whole_package_gate_green():
    """The interprocedural self-lint (tools/lint_gate.py semantics): the
    two-pass analyzer over horovod_tpu/ + examples/ + tools/ + bench.py
    must produce NO findings beyond the reviewed baseline."""
    new, _stale, _baselined = _gate_result()
    assert not new, (
        "new whole-package findings (fix them, pragma them with a reason, "
        "or — warnings only — re-baseline via "
        "`python tools/lint_gate.py --update-baseline`):\n"
        + "\n".join(f.render() for f in new))


def test_whole_package_baseline_not_stale():
    """Baseline honesty: entries whose finding no longer fires must be
    pruned in the same PR that fixes the code."""
    _new, stale, _baselined = _gate_result()
    assert not stale, f"stale baseline entries, prune them: {stale}"


def test_whole_package_baseline_carries_no_errors():
    """Only warning-severity findings may be baselined; error-severity
    ones must be fixed or carry an inline pragma with a reason."""
    from horovod_tpu.analysis.baseline import load_baseline
    from horovod_tpu.analysis.findings import RULES, Severity
    baseline = load_baseline(
        os.path.join(REPO, "tools", "lint_baseline.json"))
    errors = [k for k in baseline
              if RULES[k[0]].severity is Severity.ERROR]
    assert not errors, errors


def test_known_out_of_scope_files_now_lint_clean_via_pragmas():
    """ISSUE 13 satellite: bench.py's HVD103 and the deliberate divergence
    in tests/data/worker_join.py / worker_sanitizer.py are annotated with
    inline pragmas — the files lint error-free WITHOUT directory scoping,
    so the old ROADMAP carve-out is gone (bench.py is in the gate scope)."""
    findings = lint_paths([
        os.path.join(REPO, "bench.py"),
        os.path.join(REPO, "tests", "data", "worker_join.py"),
        os.path.join(REPO, "tests", "data", "worker_sanitizer.py"),
    ])
    errors = [f for f in findings if f.is_error]
    assert not errors, "\n".join(f.render() for f in errors)
    assert not any(f.rule == "HVD103" for f in findings)   # bench pragma


def test_allowlist_entries_still_fire():
    """Stale allowlist entries (fixed code, moved lines) must be pruned."""
    findings = lint_paths([os.path.join(REPO, "horovod_tpu"),
                           os.path.join(REPO, "examples")])
    live = {_key(f) for f in findings}
    stale = [k for k in ALLOWLIST if k not in live]
    assert not stale, f"allowlist entries no longer fire, remove them: {stale}"
