"""HF checkpoint conversion: our llama forward must reproduce
``transformers``' LlamaForCausalLM logits from the SAME weights — the
gold parity test for the rope-layout unpermute and every transpose —
plus a lossless round trip back to HF naming."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("transformers")

from horovod_tpu.models import convert, llama


def _cfgs(rms_eps=1e-5):
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    hf_cfg = HFLlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=rms_eps,
        rope_theta=10000.0, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    cfg = llama.LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=64, rope_theta=10000.0, dtype=jnp.float32,
        norm_eps=rms_eps,
        dp_axis=None, tp_axis=None, sp_axis=None, use_flash=False)
    return model, cfg


def test_hf_conversion_matches_transformers():
    import torch
    model, cfg = _cfgs()
    params = convert.from_hf_state_dict(model.state_dict(), cfg)

    tokens = np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 10))
    ours = np.asarray(llama.forward(params, jnp.asarray(tokens, jnp.int32),
                                    cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)

    # Cached decode from converted weights: greedy continuation equals
    # HF's argmax continuation (the serving path, end to end).
    gen = np.asarray(llama.generate(params,
                                    jnp.asarray(tokens, jnp.int32), 3, cfg))
    seq = torch.tensor(tokens)
    for i in range(3):
        with torch.no_grad():
            nxt = model(seq).logits[:, -1, :].argmax(-1)
        np.testing.assert_array_equal(gen[:, i], nxt.numpy(),
                                      err_msg=f"token {i}")
        seq = torch.cat([seq, nxt[:, None]], dim=1)


def test_hf_round_trip_lossless():
    model, cfg = _cfgs()
    sd = {k: v for k, v in model.state_dict().items()}
    params = convert.from_hf_state_dict(sd, cfg)
    sd2 = convert.to_hf_state_dict(params, cfg)
    assert set(sd2) == set(sd)
    for k in sd:
        np.testing.assert_allclose(sd2[k], sd[k].numpy(), atol=1e-6,
                                   err_msg=k)


def test_hf_mixtral_conversion_matches_transformers():
    """The whole MoE stack (normalized top-2 routing, SwiGLU experts,
    einsum dispatch) against transformers' MixtralForCausalLM from the
    SAME weights — capacity_factor = n_experts so no token drops and the
    static-capacity formulation must match Mixtral's dense-gather math
    exactly."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, num_local_experts=4,
        num_experts_per_tok=2)
    torch.manual_seed(0)
    model = MixtralForCausalLM(hf_cfg).eval()
    cfg = llama.LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=64, rope_theta=10000.0, dtype=jnp.float32,
        n_experts=4, router_top_k=2, moe_gated=True, ep_axis=None,
        capacity_factor=4.0, dp_axis=None, tp_axis=None, sp_axis=None,
        use_flash=False)
    params = convert.from_hf_state_dict(model.state_dict(), cfg)

    tokens = np.random.RandomState(5).randint(0, cfg.vocab_size, (2, 10))
    ours = np.asarray(llama.forward(params,
                                    jnp.asarray(tokens, jnp.int32), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-4)


def test_hf_missing_key_is_clear():
    _, cfg = _cfgs()
    with pytest.raises(KeyError, match="state dict is missing"):
        convert.from_hf_state_dict({}, cfg)


def test_tied_embeddings_fallback_and_round_trip():
    model, cfg = _cfgs()
    sd = {k: v for k, v in model.state_dict().items()
          if k != "lm_head.weight"}
    params = convert.from_hf_state_dict(sd, cfg)
    np.testing.assert_allclose(np.asarray(params["lm_head"]),
                               np.asarray(params["embed"]).T)
    # Lossless round trip in the TIED shape too: no extra lm_head key.
    sd2 = convert.to_hf_state_dict(params, cfg, tied_embeddings=True)
    assert set(sd2) == set(sd)


def test_norm_eps_matters_and_propagates():
    """A non-default-eps checkpoint (1e-4 here; 1e-6 families behave the
    same way) converts exactly when cfg.norm_eps matches — and measurably
    diverges when it does not (the silent-drift guard)."""
    import torch
    model, cfg = _cfgs(rms_eps=1e-4)
    params = convert.from_hf_state_dict(model.state_dict(), cfg)
    tokens = np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 8))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(llama.forward(params,
                                    jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)
    import dataclasses
    cfg_wrong = dataclasses.replace(cfg, norm_eps=1e-5)
    wrong = np.asarray(llama.forward(params,
                                     jnp.asarray(tokens, jnp.int32),
                                     cfg_wrong))
    assert np.abs(wrong - theirs).max() > np.abs(ours - theirs).max()


def test_mismatched_checkpoint_rejected():
    """Too-few-layers configs and MoE configs must refuse loudly."""
    model, cfg = _cfgs()
    import dataclasses
    with pytest.raises(ValueError, match="not consumed"):
        convert.from_hf_state_dict(model.state_dict(),
                                   dataclasses.replace(cfg, n_layers=1))
    with pytest.raises(ValueError, match="MoE|n_experts|dense"):
        convert.from_hf_state_dict(
            model.state_dict(),
            dataclasses.replace(cfg, n_experts=4))


# ----------------------------------------------------------------- GPT-2
def test_gpt2_hf_conversion_matches_transformers():
    """Converted HF GPT2LMHeadModel weights reproduce transformers'
    logits — the parity pin for the Conv1D no-transpose convention, the
    fused-qkv split, tanh-GELU, and the tied head."""
    import torch
    from transformers import GPT2Config as HFGPT2Config
    from transformers import GPT2LMHeadModel
    from horovod_tpu.models import gpt2

    hf_cfg = HFGPT2Config(vocab_size=256, n_positions=64, n_embd=64,
                          n_layer=2, n_head=4,
                          resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.from_hf_state_dict(hf.state_dict(), cfg)

    tokens = np.random.RandomState(0).randint(0, 256, (2, 40))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(gpt2.forward(params, jnp.asarray(tokens), cfg))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


def test_gpt2_missing_key_is_clear():
    from horovod_tpu.models import gpt2

    with pytest.raises(KeyError):
        gpt2.from_hf_state_dict({"transformer.wte.weight":
                                 np.zeros((256, 64))},
                                gpt2.tiny())


def test_gpt2_round_trip_lossless():
    """from_hf -> to_hf reproduces every tensor bit-exactly (fp32)."""
    import torch
    from transformers import GPT2Config as HFGPT2Config
    from transformers import GPT2LMHeadModel
    from horovod_tpu.models import gpt2

    hf_cfg = HFGPT2Config(vocab_size=256, n_positions=64, n_embd=64,
                          n_layer=2, n_head=4)
    torch.manual_seed(1)
    hf = GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.from_hf_state_dict(hf.state_dict(), cfg)
    back = gpt2.to_hf_state_dict(params, cfg)
    sd = hf.state_dict()
    for name, arr in back.items():
        ref = sd[name].detach().float().numpy()
        np.testing.assert_array_equal(arr, ref, err_msg=name)
