"""Eager collective tests: every op × several dtypes, vs locally-computed
expectations — the reference's assertion pattern from
``test/parallel/test_torch.py`` (SURVEY.md §4: "allreduce result == sum over
size() of deterministic per-rank tensors").
"""

import numpy as np
import pytest


def _per_rank(world, shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return [rng.randint(0, 10, size=shape).astype(dtype) for _ in range(world)]
    return [rng.randn(*shape).astype(dtype) for _ in range(world)]


@pytest.mark.parametrize("dtype", [np.float32, np.int32, np.float16])
def test_allreduce_sum(hvd, world_size, dtype):
    vals = _per_rank(world_size, (4, 3), dtype)
    x = hvd.stack_per_rank(vals)
    out = hvd.allreduce(x, op=hvd.Sum)
    expected = np.sum(np.stack(vals), axis=0)
    np.testing.assert_allclose(np.asarray(out), expected,
                               rtol=2e-3 if dtype == np.float16 else 1e-6)


def test_allreduce_average(hvd, world_size):
    vals = _per_rank(world_size, (5,), np.float32, seed=1)
    out = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out),
                               np.mean(np.stack(vals), axis=0), rtol=1e-6)


def test_allreduce_min_max(hvd, world_size):
    vals = _per_rank(world_size, (7,), np.float32, seed=2)
    out_min = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Min)
    out_max = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Max)
    np.testing.assert_allclose(np.asarray(out_min), np.min(np.stack(vals), 0))
    np.testing.assert_allclose(np.asarray(out_max), np.max(np.stack(vals), 0))


def test_allreduce_product(hvd, world_size):
    vals = [np.full((3,), 1.0 + 0.1 * r, np.float32) for r in range(world_size)]
    out = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Product)
    np.testing.assert_allclose(np.asarray(out), np.prod(np.stack(vals), 0),
                               rtol=1e-5)


def test_allreduce_prescale_postscale(hvd, world_size):
    vals = _per_rank(world_size, (4,), np.float32, seed=3)
    out = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Sum,
                        prescale_factor=0.5, postscale_factor=2.0)
    np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0),
                               rtol=1e-5)


def test_allreduce_async_poll(hvd, world_size):
    vals = _per_rank(world_size, (2, 2), np.float32, seed=4)
    h = hvd.allreduce_async(hvd.stack_per_rank(vals), op=hvd.Sum)
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0),
                               rtol=1e-6)


def test_grouped_allreduce(hvd, world_size):
    a = _per_rank(world_size, (3,), np.float32, seed=5)
    b = _per_rank(world_size, (2, 2), np.float32, seed=6)
    outs = hvd.grouped_allreduce([hvd.stack_per_rank(a), hvd.stack_per_rank(b)],
                                 op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(outs[0]), np.sum(np.stack(a), 0),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs[1]), np.sum(np.stack(b), 0),
                               rtol=1e-6)


def test_allgather(hvd, world_size):
    vals = [np.full((2, 3), r, np.float32) for r in range(world_size)]
    out = np.asarray(hvd.allgather(hvd.stack_per_rank(vals)))
    assert out.shape == (2 * world_size, 3)
    for r in range(world_size):
        np.testing.assert_array_equal(out[2 * r:2 * r + 2], vals[r])


@pytest.mark.parametrize("root", [0, 3])
def test_broadcast(hvd, world_size, root):
    vals = [np.full((4,), r, np.float32) for r in range(world_size)]
    out = np.asarray(hvd.broadcast(hvd.stack_per_rank(vals), root_rank=root))
    np.testing.assert_array_equal(out, vals[root])


def test_broadcast_object(hvd):
    obj = {"epoch": 3, "lr": 0.1, "name": "resnet"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_alltoall(hvd, world_size):
    # rank r sends chunk [r*world + c] to rank c; classic transpose check.
    vals = [np.arange(world_size, dtype=np.float32) + r * world_size
            for r in range(world_size)]
    out = np.asarray(hvd.alltoall(hvd.stack_per_rank(vals)))
    assert out.shape == (world_size, world_size)
    expected = np.stack(vals).T  # receiver c gets element c from every rank
    np.testing.assert_array_equal(out, expected)


def test_reducescatter(hvd, world_size):
    vals = _per_rank(world_size, (world_size * 2, 3), np.float32, seed=7)
    out = np.asarray(hvd.reducescatter(hvd.stack_per_rank(vals), op=hvd.Sum))
    total = np.sum(np.stack(vals), axis=0)
    assert out.shape == (world_size, 2, 3)
    for r in range(world_size):
        np.testing.assert_allclose(out[r], total[2 * r:2 * r + 2], rtol=1e-5)


def test_process_set_collective(hvd, world_size):
    ps = hvd.add_process_set([0, 2, 4])
    try:
        vals = [np.full((3,), float(r + 1), np.float32) for r in range(3)]
        out = hvd.allreduce(hvd.stack_per_rank(vals, ps), op=hvd.Sum,
                            process_set=ps)
        np.testing.assert_allclose(np.asarray(out), np.full((3,), 6.0))
    finally:
        hvd.remove_process_set(ps)


def test_barrier_and_join(hvd, world_size):
    hvd.barrier()
    assert hvd.join() == world_size - 1


def test_duplicate_name_rejected(hvd, world_size):
    from horovod_tpu.ops.engine import TensorTableEntry, CollectiveType
    import horovod_tpu.ops.eager as eager
    eng = eager._engine()
    vals = _per_rank(world_size, (2,), np.float32)
    x = hvd.stack_per_rank(vals)
    # Exercise the queue-level collision directly (deterministic, no timing).
    e1 = TensorTableEntry(handle=10**9, name="dup_direct",
                          ctype=CollectiveType.ALLREDUCE, tensor=x)
    eng.queue.push(e1)
    e2 = TensorTableEntry(handle=10**9 + 1, name="dup_direct",
                          ctype=CollectiveType.ALLREDUCE, tensor=x)
    with pytest.raises(ValueError):
        eng.queue.push(e2)
    eng.queue.drain()
    eng.queue.mark_done(e1)
    # After completion the name is free again through the public API:
    h = hvd.allreduce_async(x, name="dup_direct")
    hvd.synchronize(h)


def test_replicated_helper(hvd, world_size):
    out = hvd.allreduce(hvd.replicated(np.ones((3,), np.float32)), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), np.full((3,), world_size))


def test_cache_hits(hvd, world_size):
    import horovod_tpu.ops.eager as eager
    eng = eager._engine()
    vals = _per_rank(world_size, (6,), np.float32, seed=8)
    x = hvd.stack_per_rank(vals)
    hvd.allreduce(x, op=hvd.Sum)
    misses_before = eng.cache.misses
    hits_before = eng.cache.hits
    for _ in range(3):
        hvd.allreduce(x, op=hvd.Sum)
    assert eng.cache.misses == misses_before
    assert eng.cache.hits >= hits_before + 3


def test_device_resident_no_host_transfer(hvd, world_size):
    """A device array with the right sharding flows through the engine with
    ZERO host transfers (VERDICT r1 item 2; reference N7's raison d'etre)."""
    import jax
    vals = _per_rank(world_size, (16,), np.float32, seed=11)
    x = hvd.stack_per_rank(vals)          # device array, world-sharded
    assert isinstance(x, jax.Array)
    # Warm the fused-program cache so no compile-time constants transfer.
    hvd.allreduce(x, op=hvd.Sum, name="warm_noxfer")
    # The engine runs on a background thread, so use the process-wide guard
    # (the `with jax.transfer_guard(...)` form is thread-local and would
    # not observe the engine's dispatch).
    jax.config.update("jax_transfer_guard", "disallow")
    try:
        h = hvd.allreduce_async(x, op=hvd.Sum, name="noxfer")
        out = hvd.synchronize(h)
        assert isinstance(out, jax.Array)
    finally:
        jax.config.update("jax_transfer_guard", "allow")
    np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0),
                               rtol=1e-6)


def test_caller_array_never_donated(hvd, world_size):
    """The caller's correctly-sharded array must survive the collective
    (donation only applies to engine-owned temporaries)."""
    vals = _per_rank(world_size, (8,), np.float32, seed=12)
    x = hvd.stack_per_rank(vals)
    hvd.allreduce(x, op=hvd.Sum, name="donate_check_1")
    # Re-using the same input must still work — it was not invalidated.
    out = hvd.allreduce(x, op=hvd.Sum, name="donate_check_2")
    np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0),
                               rtol=1e-6)


def test_host_input_donated_path(hvd, world_size):
    """Host (numpy) inputs go through the owned/donated path and still
    produce correct results across all collective types."""
    vals = _per_rank(world_size, (4,), np.float32, seed=13)
    stacked = np.stack(vals)
    out = hvd.allreduce(stacked, op=hvd.Sum, name="donate_np_ar")
    np.testing.assert_allclose(np.asarray(out), stacked.sum(0), rtol=1e-6)
    out = hvd.allgather(stacked, name="donate_np_ag")
    np.testing.assert_allclose(np.asarray(out), np.concatenate(vals))


def test_alltoall_ragged(hvd, world_size):
    """Uneven splits (reference hvd.alltoall(tensor, splits)): rank r sends
    (r + j + 1) rows of value 100*r + j to rank j, embedding-style [n, dim]
    payload (DLRM exchange shape, SURVEY.md §2c config #5)."""
    w, dim = world_size, 3
    splits = np.array([[r + j + 1 for j in range(w)] for r in range(w)],
                      dtype=np.int64)
    tensors = []
    for r in range(w):
        rows = [np.full((r + j + 1, dim), 100.0 * r + j, np.float32)
                for j in range(w)]
        tensors.append(np.concatenate(rows, axis=0))
    outs, rsplits = hvd.alltoall(tensors, splits=splits)
    assert len(outs) == w
    np.testing.assert_array_equal(rsplits, splits.T)
    for j in range(w):
        expected = np.concatenate(
            [np.full((r + j + 1, dim), 100.0 * r + j, np.float32)
             for r in range(w)], axis=0)
        np.testing.assert_array_equal(outs[j], expected)


def test_alltoall_ragged_async(hvd, world_size):
    """Async ragged alltoall (VERDICT r2 missing #7): the handle resolves
    via poll→synchronize to the same result as the blocking form."""
    w, dim = world_size, 2
    splits = np.array([[r + j + 1 for j in range(w)] for r in range(w)],
                      dtype=np.int64)
    tensors = []
    for r in range(w):
        rows = [np.full((r + j + 1, dim), 10.0 * r + j, np.float32)
                for j in range(w)]
        tensors.append(np.concatenate(rows, axis=0))
    h = hvd.alltoall_async(tensors, splits=splits, name="a2av_async")
    import time
    deadline = time.time() + 30
    while not hvd.poll(h):
        assert time.time() < deadline, "async ragged alltoall never completed"
        time.sleep(0.01)
    outs, rsplits = hvd.synchronize(h)
    np.testing.assert_array_equal(rsplits, splits.T)
    for j in range(w):
        expected = np.concatenate(
            [np.full((r + j + 1, dim), 10.0 * r + j, np.float32)
             for r in range(w)], axis=0)
        np.testing.assert_array_equal(outs[j], expected)
    # A second synchronize returns the cached result unchanged.
    outs2, _ = hvd.synchronize(h)
    np.testing.assert_array_equal(outs2[0], outs[0])


def test_blocking_op_completes_inline_without_background_thread(hvd):
    """Blocking eager ops run the cycle INLINE on the submit thread in
    single-controller mode (the small-tensor latency fast path, VERDICT r3
    weak #3): with the background thread stopped, hvd.allreduce must still
    complete — proof the result did not ride the cycle thread."""
    import horovod_tpu.common.basics as basics
    eng = basics._get_state().engine
    assert eng.controller is None  # single-controller mode only
    # Park the background thread (restored after): shutdown flag keeps the
    # loop from draining, so only the inline kick can execute the op.
    eng._shutdown.set()
    eng._wake.set()
    try:
        eng._thread.join(timeout=10)
        assert not eng._thread.is_alive()
        vals = _per_rank(8, (4,), np.float32, seed=77)
        out = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Sum,
                            name="inline_fastpath")
        np.testing.assert_allclose(np.asarray(out),
                                   np.sum(np.stack(vals), 0), rtol=1e-6)
        # Grouped blocking form rides the same inline cycle.
        outs = hvd.grouped_allreduce(
            [hvd.stack_per_rank(vals), hvd.stack_per_rank(vals)],
            op=hvd.Sum, name="inline_group")
        for o in outs:
            np.testing.assert_allclose(np.asarray(o),
                                       np.sum(np.stack(vals), 0), rtol=1e-6)
    finally:
        eng._shutdown.clear()
        eng.start()


def test_allgather_object(hvd, world_size):
    """Pickle-allgather of heterogeneous per-rank objects (reference:
    allgather_object) — sizes differ per rank, result identical lists."""
    objs = [{"rank": r, "blob": "x" * (10 * (r + 1))}
            for r in range(world_size)]
    out = hvd.allgather_object(objs)
    assert out == objs
    # Replicated single object form.
    out2 = hvd.allgather_object({"same": 1})
    assert out2 == [{"same": 1}] * world_size
    # per_rank=False replicates a list payload VERBATIM even when its
    # length happens to equal world (the legacy sniff would misread it
    # as per-rank objects).
    payload = list(range(world_size))
    out3 = hvd.allgather_object(payload, per_rank=False)
    assert out3 == [payload] * world_size
    # per_rank=True demands an exact per-rank list.
    out4 = hvd.allgather_object(objs, per_rank=True)
    assert out4 == objs
    with pytest.raises(ValueError, match="per_rank=True"):
        hvd.allgather_object({"not": "a list"}, per_rank=True)
