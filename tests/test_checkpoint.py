"""Sharded checkpointing tests (SURVEY.md §5 checkpoint/resume, promoted to
first-class)."""

import numpy as np
import pytest

from horovod_tpu import checkpoint as ckpt


def _tree():
    import jax.numpy as jnp
    return {"params": {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                       "b": jnp.ones(4, jnp.float32)},
            "step": jnp.asarray(7, jnp.int32)}


def test_save_restore_roundtrip(tmp_path, hvd):
    tree = _tree()
    ckpt.save(str(tmp_path), tree, step=5)
    assert ckpt.latest_step(str(tmp_path)) == 5
    out = ckpt.restore(str(tmp_path), template=tree)
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.asarray(tree["params"]["w"]))
    assert int(out["step"]) == 7


def test_restore_without_template(tmp_path, hvd):
    tree = _tree()
    ckpt.save(str(tmp_path), tree, step=0)
    out = ckpt.restore(str(tmp_path))
    np.testing.assert_allclose(np.asarray(out["params"]["b"]), 1.0)


def test_restore_sharded_onto_mesh(tmp_path, hvd):
    """Save a replicated tree, restore it SHARDED over the 8-device mesh —
    the elastic-resume reshard path."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd_mod
    mesh = hvd_mod.mesh()
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    ckpt.save(str(tmp_path), {"x": x}, step=1)

    sharded = NamedSharding(mesh, P("hvd"))
    template = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                          sharding=sharded)}
    out = ckpt.restore(str(tmp_path), template=template)
    assert out["x"].sharding == sharded
    np.testing.assert_allclose(np.asarray(out["x"]), np.asarray(x))


def test_missing_checkpoint(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.restore(str(tmp_path / "nope"))
    assert ckpt.latest_step(str(tmp_path / "nope")) is None


def test_manager_policy_and_gc(tmp_path, hvd):
    mgr = ckpt.CheckpointManager(str(tmp_path), max_to_keep=2,
                                 save_interval_steps=10)
    tree = _tree()
    assert not mgr.save(5, tree)          # off-interval
    assert mgr.save(10, tree)
    assert mgr.save(20, tree)
    assert mgr.save(30, tree)
    assert mgr.all_steps() == [20, 30]    # GC keeps last 2
    assert mgr.latest_step() == 30
    out = mgr.restore(template=tree)
    assert int(out["step"]) == 7
    assert mgr.save(31, tree, force=True)


def test_elastic_state_durable_commit(tmp_path, hvd):
    from horovod_tpu.elastic import JaxState
    import jax.numpy as jnp

    state = JaxState(params={"w": jnp.ones(4)}, epoch=3)
    ckpt.save_state(state, str(tmp_path), step=3)

    fresh = JaxState(params={"w": jnp.zeros(4)}, epoch=0)
    ckpt.restore_state(fresh, str(tmp_path))
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 1.0)
    assert int(fresh.epoch) == 3
    # The restore also rewrote the committed backup.
    fresh.params = {"w": jnp.full(4, 9.0)}
    fresh.restore()
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 1.0)
