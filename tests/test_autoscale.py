"""Closed-loop autoscaling: policy engine, trend gauges, drain plumbing
(tier-1, no jax, no process spawns).

Covers the jax-free halves of the autoscaling subsystem (ISSUE 10):
``elastic/autoscale.ScalePolicy`` decision semantics (scripted summaries +
scripted clock — hysteresis, cooldown, attribution), the monitor
aggregator's windowed EWMA trend gauges and clean-leave accounting, the
registry's clean-exit-vs-blacklist classification, the driver's
discovery-flap debounce (assignments must not churn on a one-poll host
disappearance) and the DRAIN notification verb.  The end-to-end
simulated-load scenario lives in ``tests/test_multiprocess.py``.
"""

import socket
import time

import pytest

from horovod_tpu.common.exceptions import DrainRequested
from horovod_tpu.elastic.autoscale import (
    EVICT, HOLD, SCALE_IN, SCALE_OUT, ScaleDecision, ScalePolicy,
)
from horovod_tpu.elastic.discovery import DiscoveredHost, FixedHostDiscovery
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.registration import LEFT, WorkerStateRegistry
from horovod_tpu.monitor.aggregator import EwmaTrend, RankAggregator


# ---------------------------------------------------------------- EwmaTrend
def test_ewma_trend_null_until_window_fills_then_signed():
    t = EwmaTrend(min_samples=3)
    t.update(10.0)
    t.update(10.0)
    assert t.trend is None            # window not filled: policy holds
    t.update(10.0)
    assert t.trend == pytest.approx(0.0, abs=0.5)
    for v in (14.0, 18.0, 24.0):
        t.update(v)
    assert t.trend > 0                # rising series: positive trend
    for v in (6.0, 2.0, 1.0, 0.0, 0.0, 0.0):
        t.update(v)
    assert t.trend < 0                # falling series: negative trend
    t.reset()
    assert t.trend is None


# -------------------------------------------------------------- aggregator
def _snap(rank, cycle_us=100.0, queue=0, cycle=10, stalled=()):
    return {"rank": rank, "cycle_us_avg": cycle_us, "cycle": cycle,
            "last_cycle_age_s": 0.1, "stalled": list(stalled),
            "metrics": {"hvd_queue_pending": queue}}


def test_aggregator_summary_exposes_trend_gauges_and_load():
    agg = RankAggregator(world=2)
    s = agg.summary()
    assert s["cycle_us_spread_trend"] is None       # nulls until filled
    assert s["queue_depth_trend"] is None
    assert s["queue_depth"] is None
    for i in range(8):
        agg.update(0, _snap(0, cycle_us=100, queue=2 + i, cycle=i))
        agg.update(1, _snap(1, cycle_us=100 + 10 * i, queue=2 + i, cycle=i))
    s = agg.summary()
    assert s["queue_depth"] == 2 * (2 + 7)
    assert s["cycle_us_spread_trend"] > 0           # spread widening
    assert s["queue_depth_trend"] > 0               # backlog rising
    assert s["slowest_rank"] == 1
    assert s["ranks_reporting"] == 2
    # Join-epoch flush resets the trend windows with the table.
    agg.flush()
    s = agg.summary()
    assert s["cycle_us_spread_trend"] is None
    assert s["queue_depth_trend"] is None


def test_aggregator_mark_left_keeps_health_ok():
    """A clean departure (protocol v6) is NOT a degradation: /health stays
    ok, the rank reports as left, and skew/liveness skip it."""
    agg = RankAggregator(world=2)
    agg.update(0, _snap(0))
    agg.update(1, _snap(1))
    agg.mark_left(1)
    h = agg.health(interval_s=5.0)
    assert h["status"] == "ok", h
    assert h["ranks"]["1"]["left"] is True
    assert h["left_ranks"] == [1]
    assert agg.summary()["left_ranks"] == [1]
    # skew needs two LIVE ranks; the leaver no longer counts.
    assert agg.skew()["slowest_rank"] is None
    # ...and mark_left persists across a join-epoch flush (the departed
    # rank is still gone in the resumed world).
    agg.flush()
    assert agg.left_ranks() == [1]


# ------------------------------------------------------------- ScalePolicy
def _summary(spread=None, slowest=None, per_rank=None, q=0, q_trend=None,
             progress_total=None, commit_age=None):
    return {"cycle_us_spread": spread, "slowest_rank": slowest,
            "per_rank_cycle_us": per_rank or {}, "queue_depth": q,
            "queue_depth_trend": q_trend, "progress_total": progress_total,
            "last_commit_age_s": commit_age}


def _decisions(d):
    """The driver's DECISION events — the paced-commit ack records
    (``commit_request``, ISSUE 14) are bookkeeping, not decisions."""
    return [e for e in d.events if e["action"] != "commit_request"]


def test_policy_scale_out_needs_persistent_trend_then_cools_down():
    p = ScalePolicy(min_np=2, max_np=8, queue_trend_up=4.0, persistence=3,
                    cooldown_s=30.0)
    t = 1000.0
    # Two hot observations: below persistence — hold.
    for i in range(2):
        d = p.observe(_summary(q=50, q_trend=10.0, progress_total=i), 2,
                      now=t + i)
        assert d.is_hold, d
    d = p.observe(_summary(q=50, q_trend=10.0, progress_total=3), 2, now=t + 2)
    assert d.action == SCALE_OUT and d.target_size == 3, d
    # Cooldown: even a screaming-hot summary holds.
    d = p.observe(_summary(q=500, q_trend=99.0, progress_total=4), 3,
                  now=t + 10)
    assert d.is_hold and d.reason == "cooldown"
    # After the cooldown the counter restarts from zero (hysteresis).
    d = p.observe(_summary(q=50, q_trend=10.0, progress_total=5), 3,
                  now=t + 40)
    assert d.is_hold


def test_policy_null_trends_never_scale():
    """Unfilled windows (nulls) must hold — a fresh world is not a signal."""
    p = ScalePolicy(min_np=1, max_np=8, persistence=1, cooldown_s=0.0)
    for i in range(5):
        d = p.observe(_summary(q=0, q_trend=None, progress_total=None), 2,
                      now=100.0 + i)
        assert d.is_hold, d


def test_policy_evicts_persistent_straggler_with_attribution():
    p = ScalePolicy(min_np=1, straggler_factor=3.0, persistence=3,
                    cooldown_s=30.0)
    per_rank = {0: 100.0, 1: 100.0, 2: 900.0}
    t = 1000.0
    for i in range(2):
        d = p.observe(_summary(spread=800, slowest=2, per_rank=per_rank,
                               progress_total=i), 3, now=t + i)
        assert d.is_hold, d
    d = p.observe(_summary(spread=800, slowest=2, per_rank=per_rank,
                           progress_total=3), 3, now=t + 2)
    assert d.action == EVICT and d.evict_rank == 2, d
    # The reason IS the monitor attribution the drain log quotes.
    assert "rank 2" in d.reason and "900" in d.reason \
        and "monitor attribution" in d.reason, d.reason


def test_policy_straggler_identity_must_be_stable():
    """A different rank being slowest each observation is noise, not a
    straggler — the persistence counter tracks ONE rank."""
    p = ScalePolicy(min_np=1, straggler_factor=2.0, persistence=2,
                    cooldown_s=0.0)
    for i, slow in enumerate((0, 1, 2, 0, 1, 2)):
        per_rank = {r: (500.0 if r == slow else 100.0) for r in range(3)}
        d = p.observe(_summary(spread=400, slowest=slow, per_rank=per_rank,
                               progress_total=i), 3, now=100.0 + i)
        assert d.is_hold, (i, d)


def test_policy_scale_in_when_idle_and_respects_min_np():
    p = ScalePolicy(min_np=2, persistence=1, cooldown_s=0.0, idle_s=10.0)
    t = 1000.0
    # Busy (cycle counter advancing): no scale-in however long.
    for i in range(5):
        d = p.observe(_summary(q=0, progress_total=i), 3, now=t + 5 * i)
        assert d.is_hold, d
    # Idle (no queue, frozen cycle counter): scale in after idle_s.
    d = p.observe(_summary(q=0, progress_total=4), 3, now=t + 30)
    assert d.is_hold
    d = p.observe(_summary(q=0, progress_total=4), 3, now=t + 45)
    assert d.action == SCALE_IN and d.target_size == 2, d
    # At min_np: idle forever, never shrink below.
    p2 = ScalePolicy(min_np=2, cooldown_s=0.0, idle_s=1.0)
    p2.observe(_summary(q=0, progress_total=1), 2, now=t)
    d = p2.observe(_summary(q=0, progress_total=1), 2, now=t + 100)
    assert d.is_hold


# ------------------------------------------- clean-exit classification
def test_registry_record_left_neither_blacklists_nor_succeeds():
    reg = WorkerStateRegistry()
    reg.record_left("hostA:0")
    assert reg.state_of("hostA:0") == LEFT
    assert not reg.is_blacklisted("hostA")
    assert reg.success_count() == 0
    # Control: a failure on the same host DOES blacklist.
    reg.record_failure("hostA:1")
    assert reg.is_blacklisted("hostA")


class _FakeProc:
    def __init__(self, rc):
        self._rc = rc
        self.pid = 0

    def poll(self):
        return self._rc

    def terminate(self):
        pass


def _driver(**kw):
    kw.setdefault("min_np", 1)
    return ElasticDriver(FixedHostDiscovery([]), ["true"], **kw)


def test_driver_reap_classifies_drained_exit_as_left_not_success():
    d = _driver()
    d._assigned = {"hostA:0": {"rank": 0}}
    d._procs["hostA:0"] = _FakeProc(0)
    d._draining.add("hostA:0")
    changed = d._reap_exits()
    assert changed is True                       # world must re-form
    assert not d._success.is_set()               # NOT the job-success signal
    assert d.registry.state_of("hostA:0") == LEFT
    assert not d.registry.is_blacklisted("hostA")
    # Control A: the same exit WITHOUT the drain mark is job success.
    d2 = _driver()
    d2._assigned = {"hostA:0": {"rank": 0}}
    d2._procs["hostA:0"] = _FakeProc(0)
    assert d2._reap_exits() is False
    assert d2._success.is_set()
    # Control B: a crash blacklists.
    d3 = _driver()
    d3._procs["hostA:0"] = _FakeProc(7)
    assert d3._reap_exits() is True
    assert d3.registry.is_blacklisted("hostA")
    assert d3._first_failure_rc == 7


# ----------------------------------------------------- discovery flapping
def test_discovery_flap_does_not_churn_assignments():
    """A host missing for ONE poll then returning must not change the
    effective host list (so the driver's change detection never re-forms
    the world) — and the rank assignment computed over the flapped list
    is identical."""
    d = _driver(min_np=2, discovery_interval_s=1.0)   # grace = 2s default
    full = [DiscoveredHost("hostA", 1), DiscoveredHost("hostB", 1)]
    eff0 = d._effective_hosts(full, now=100.0)
    d._hosts = eff0
    base = [(h.hostname, h.slots) for h in eff0]
    ranks0 = {i: a["rank"]
              for i, a in d.compute_assignments(eff0).items()}

    # hostB vanishes for one poll — inside the grace window.
    flap = d._effective_hosts([full[0]], now=101.0)
    assert [(h.hostname, h.slots) for h in flap] == base, flap
    ranks1 = {i: a["rank"]
              for i, a in d.compute_assignments(flap).items()}
    assert ranks1 == ranks0                      # zero assignment churn

    # ...and returns: still identical.
    back = d._effective_hosts(full, now=102.0)
    assert [(h.hostname, h.slots) for h in back] == base

    # Gone PAST the grace window: now it really drops.
    gone = d._effective_hosts([full[0]], now=110.0)
    assert [(h.hostname, h.slots) for h in gone] == [("hostA", 1)]

    # A NEW host joins immediately — growth is never debounced.
    grown = d._effective_hosts(full + [DiscoveredHost("hostC", 1)],
                               now=111.0)
    assert ("hostC", 1) in [(h.hostname, h.slots) for h in grown]


def test_cordoned_host_excluded_like_blacklist_but_clean():
    d = _driver(min_np=1)
    hosts = [DiscoveredHost("hostA", 1), DiscoveredHost("hostB", 1)]
    d.cordon("hostB")
    active = d.active_hosts(hosts)
    assert [h.hostname for h in active] == ["hostA"]
    assert not d.registry.is_blacklisted("hostB")


# ------------------------------------------------------------- DRAIN verb
def test_drain_verb_raises_drain_requested_at_commit_point():
    """Driver → worker drain plumbing: the DRAIN ping lands in the
    notification manager and surfaces as DrainRequested from the next
    raise_if_updated() (the state.commit() check point), outranking a
    pending host update."""
    from horovod_tpu.elastic.worker import WorkerNotificationManager

    mgr = WorkerNotificationManager()          # no rendezvous env: local
    try:
        port = mgr._service.port
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            s.sendall(b"DRAIN\n")
        deadline = time.monotonic() + 5
        fired = False
        while time.monotonic() < deadline:
            try:
                mgr.raise_if_updated()
            except DrainRequested:
                fired = True
                break
            time.sleep(0.02)
        assert fired, "DRAIN ping never surfaced as DrainRequested"
        # One-shot: the next check is clean.
        mgr.raise_if_updated()
    finally:
        mgr._service.stop()


def test_autoscale_step_executes_evict_through_drain_and_cordon():
    """Driver-side decision execution: an EVICT decision cordons the
    straggler's host and (with no live proc/notification port) falls back
    to released-termination — never a blacklist — and the event log
    records the attribution."""
    decisions = iter([
        ScaleDecision(EVICT, reason="persistent straggler; monitor "
                      "attribution: rank 1 slowest", evict_rank=1),
        ScaleDecision(HOLD),
    ])

    class _Policy:
        min_np = 1

        def observe(self, summary, size, now=None):
            return next(decisions)

    d = _driver(autoscale_policy=_Policy(),
                autoscale_source=lambda: {"any": "summary"})
    d._assigned = {
        "hostA:0": {"rank": 0, "hostname": "hostA"},
        "hostB:0": {"rank": 1, "hostname": "hostB"},
    }
    d._autoscale_step()
    assert d._cordoned == {"hostB"}
    # The paced COMMIT fan-out records its ack event first (ISSUE 14),
    # then the decision event.
    decisions_logged = _decisions(d)
    assert len(decisions_logged) == 1
    ev = decisions_logged[0]
    assert ev["action"] == EVICT and ev["identity"] == "hostB:0"
    assert "monitor attribution" in ev["reason"]
    assert not d.registry.is_blacklisted("hostB")
    # Second step: hold → no new decision event (and no commit ping).
    before = len(d.events)
    d._autoscale_step()
    assert len(d.events) == before


# --------------------------------------------------- review-pass regressions
def test_policy_unobserved_load_never_reads_as_idle():
    """A summary with NO load telemetry at all (queue_depth and
    progress_total both None — exporter up, aggregation table empty) is
    UNKNOWN, not idle: the idle timer must not accrue toward draining a
    fleet whose load was never observed."""
    p = ScalePolicy(min_np=1, persistence=1, cooldown_s=0.0, idle_s=5.0)
    for i in range(20):
        d = p.observe(_summary(q=None, progress_total=None), 3,
                      now=100.0 + 10.0 * i)
        assert d.is_hold, (i, d)
    # Control: the same cadence WITH observed idleness does scale in
    # (first observation primes the progress baseline, second starts the
    # idle timer, third crosses idle_s).
    p2 = ScalePolicy(min_np=1, persistence=1, cooldown_s=0.0, idle_s=5.0)
    p2.observe(_summary(q=0, progress_total=7), 3, now=100.0)
    p2.observe(_summary(q=0, progress_total=7), 3, now=110.0)
    d = p2.observe(_summary(q=0, progress_total=7), 3, now=120.0)
    assert d.action == SCALE_IN, d


def test_host_granular_min_np_guard_blocks_scale_in_and_evict():
    """The policy approves scale decisions from RANK counts, but retiring
    a host removes ALL its slots: with 2x2-slot hosts and min_np=3, both
    scale_in and evict must be skipped or the next regeneration would
    abort the whole job below min_np."""
    for action_decision in (
            ScaleDecision(SCALE_IN, reason="idle", target_size=3),
            ScaleDecision(EVICT, reason="monitor attribution: rank 2",
                          evict_rank=2)):
        decisions = iter([action_decision])

        class _Policy:
            def observe(self, summary, size, now=None):
                return next(decisions)

        d = _driver(min_np=3, autoscale_policy=_Policy(),
                    autoscale_source=lambda: {"any": "summary"})
        d._assigned = {
            "hostA:0": {"rank": 0, "hostname": "hostA"},
            "hostA:1": {"rank": 1, "hostname": "hostA"},
            "hostB:0": {"rank": 2, "hostname": "hostB"},
            "hostB:1": {"rank": 3, "hostname": "hostB"},
        }
        d._autoscale_step()
        assert _decisions(d) == [], (action_decision.action, d.events)
        assert d._cordoned == set(), (action_decision.action, d._cordoned)


def test_evict_fallback_terminates_as_draining_and_regenerates():
    """An unreachable drain target (no notification port) falls back to
    termination marked DRAINING — so the reap classifies it LEFT and
    TRIGGERS the regeneration (a 'released' exit is silently skipped,
    which would leave survivors waiting on a generation that never
    forms)."""
    decisions = iter([ScaleDecision(
        EVICT, reason="monitor attribution: rank 1", evict_rank=1)])

    class _Policy:
        def observe(self, summary, size, now=None):
            return next(decisions)

    class _LiveProc(_FakeProc):
        def __init__(self):
            super().__init__(None)
            self.terminated = False

        def terminate(self):
            self.terminated = True
            self._rc = -15

    d = _driver(min_np=1, autoscale_policy=_Policy(),
                autoscale_source=lambda: {"any": "summary"})
    d._assigned = {
        "hostA:0": {"rank": 0, "hostname": "hostA"},
        "hostB:0": {"rank": 1, "hostname": "hostB"},
    }
    proc = _LiveProc()
    d._procs["hostB:0"] = proc
    d._autoscale_step()
    assert proc.terminated
    assert "hostB:0" in d._draining and "hostB:0" not in d._released
    # The reap must classify it as a departure AND demand regeneration.
    assert d._reap_exits() is True
    assert d.registry.state_of("hostB:0") == LEFT
    assert not d.registry.is_blacklisted("hostB")


# ------------------------------------------- preemption drains (ISSUE 12)
def test_policy_preempt_outranks_signals_and_cooldown():
    """The preempt decision source: a discovery preemption notice
    outranks the straggler/queue signals AND the cooldown window (the
    platform reclaims hardware on its own schedule), while still OPENING
    a cooldown so the shrink is not immediately second-guessed."""
    from horovod_tpu.elastic.autoscale import PREEMPT

    p = ScalePolicy(min_np=1, max_np=8, queue_high=1.0, persistence=1,
                    straggler_factor=2.0, cooldown_s=30.0)
    # A summary that would EVICT (persistent straggler) — the notice wins.
    evicty = _summary(slowest=1, per_rank={0: 100.0, 1: 1000.0, 2: 100.0},
                      q=50, progress_total=1)
    d = p.observe(evicty, 3, now=100.0, preempt_hosts=("hostB",))
    assert d.action == PREEMPT and d.hosts == ("hostB",), d
    assert "preemption notice" in d.reason and "hostB" in d.reason, d

    # The decision opened a cooldown: scale-out-worthy load holds.
    d2 = p.observe(_summary(q=50, progress_total=2), 3, now=101.0)
    assert d2.is_hold and d2.reason == "cooldown", d2

    # ...but a SECOND notice inside that same cooldown still fires.
    d3 = p.observe(_summary(q=50, progress_total=3), 3, now=102.0,
                   preempt_hosts=("hostC",))
    assert d3.action == PREEMPT and d3.hosts == ("hostC",), d3

    # Control: no notices -> the normal decision table resumes after
    # cooldown (the evicty summary evicts with attribution).
    p2 = ScalePolicy(min_np=1, persistence=1, straggler_factor=2.0,
                     cooldown_s=0.0)
    d4 = p2.observe(evicty, 3, now=200.0)
    assert d4.action == EVICT, d4


class _NoticeDiscovery(FixedHostDiscovery):
    def __init__(self, hosts, notices=()):
        super().__init__(hosts)
        self.notices = set(notices)

    def preemption_notices(self):
        return set(self.notices)


class _LiveProc2:
    def __init__(self):
        self._rc = None
        self.pid = 0
        self.terminated = False

    def poll(self):
        return self._rc

    def terminate(self):
        self.terminated = True
        self._rc = -15

    def exit(self, rc=0):
        self._rc = rc


def test_driver_preempt_drain_commits_cordons_and_classifies_left():
    """The tentpole's preemption path, driver side: a notice for an
    assigned host → COMMIT ping (checkpoint pacing) + DRAIN ping to its
    worker + cordon + grace deadline armed; the worker's clean exit is
    classified LEFT (never blacklisted, never a success signal) and
    triggers regeneration.  The notice is handled once while it stands,
    and re-arms after it clears."""
    from horovod_tpu.elastic.worker import WorkerNotificationManager

    disc = _NoticeDiscovery([DiscoveredHost("127.0.0.1", 1),
                             DiscoveredHost("hostB", 1)],
                            notices=["hostB"])
    d = ElasticDriver(disc, ["true"], min_np=1, preempt_grace_s=60.0)
    mgr = WorkerNotificationManager()     # plays hostB's worker
    try:
        d._assigned = {
            "127.0.0.1:0": {"rank": 0, "hostname": "127.0.0.1"},
            "hostB:0": {"rank": 1, "hostname": "hostB"},
        }
        proc = _LiveProc2()
        d._procs["hostB:0"] = proc
        # hostB resolves non-locally in drain pings; register the port
        # under the LOCAL identity trick: use 127.0.0.1-side identity so
        # the ping lands on the test's manager.
        d._assigned["hostB:0"]["hostname"] = "hostB"
        d.rendezvous._notify_ports["hostB:0"] = mgr._service.port
        # Make the drain ping route locally (the manager listens here).
        import horovod_tpu.elastic.driver as drv
        orig = drv.is_local_host
        drv.is_local_host = lambda h: True
        try:
            d._check_preemption()
        finally:
            drv.is_local_host = orig

        assert [e["action"] for e in _decisions(d)] == ["preempt_drain"]
        assert _decisions(d)[0]["host"] == "hostB"
        assert "preemption notice" in _decisions(d)[0]["reason"]
        # ISSUE 14 bugfix: the paced-commit fan-out recorded per-worker
        # acks in the event log BEFORE the cordon, and the listening
        # worker's ack landed within the grace-bounded wait.
        ack_ev = next(e for e in d.events
                      if e["action"] == "commit_request")
        assert ack_ev["acks"].get("hostB:0") is True, ack_ev
        assert "hostB" in d._cordoned
        assert "hostB:0" in d._draining
        assert "hostB:0" in d._drain_deadlines
        assert not d.registry.is_blacklisted("hostB")

        # The worker received BOTH pings: the commit request (checkpoint
        # pacing) and the drain.
        deadline = time.monotonic() + 5
        committed = drained = False
        while time.monotonic() < deadline and not (committed and drained):
            committed = committed or mgr.consume_commit_request()
            if not drained:
                try:
                    mgr.raise_if_updated()
                except DrainRequested:
                    drained = True
            time.sleep(0.02)
        assert committed, "COMMIT ping never arrived"
        assert drained, "DRAIN ping never arrived"

        # Handled once while the notice stands.
        d._check_preemption()
        assert len(_decisions(d)) == 1

        # Clean exit 0 → LEFT, regeneration, never blacklisted.
        proc.exit(0)
        assert d._reap_exits() is True
        assert d.registry.state_of("hostB:0") == LEFT
        assert not d.registry.is_blacklisted("hostB")
        assert not d._success.is_set()

        # Notice clears → the PREEMPTION cordon is released automatically
        # (recreated preemptible hardware under the same address rejoins
        # the world) → a later notice drains again.
        disc.notices.clear()
        d._check_preemption()
        assert "hostB" not in d._cordoned, d._cordoned
        disc.notices.add("hostB")
        d._procs["hostB:0"] = _LiveProc2()
        d._check_preemption()
        assert len(_decisions(d)) == 2, d.events
        assert "hostB" in d._cordoned

        # A notice for a host OUTSIDE the assignment cordons it (a
        # scale-out must never land on doomed hardware) without a drain
        # event, and releases when the notice clears.
        disc.notices.add("hostZ")
        d._check_preemption()
        assert "hostZ" in d._cordoned
        assert all(e.get("host") != "hostZ" for e in d.events), d.events
        disc.notices.discard("hostZ")
        d._check_preemption()
        assert "hostZ" not in d._cordoned

        # ...while an EVICT cordon is never released by notice churn.
        d.cordon("hostE")
        disc.notices.add("hostE")
        d._check_preemption()
        disc.notices.discard("hostE")
        d._check_preemption()
        assert "hostE" in d._cordoned
    finally:
        mgr._service.stop()
        d.rendezvous.stop()


def test_driver_preempt_grace_expiry_falls_back_to_termination():
    """The deadline fallback: a drained worker still alive past
    preempt_grace_s is terminated (the legacy sever), but stays
    classified as a departure — DRAINING → LEFT — and regenerates."""
    disc = _NoticeDiscovery([DiscoveredHost("hostA", 1),
                             DiscoveredHost("hostB", 1)],
                            notices=["hostB"])
    d = ElasticDriver(disc, ["true"], min_np=1, preempt_grace_s=0.0)
    try:
        d._assigned = {
            "hostA:0": {"rank": 0, "hostname": "hostA"},
            "hostB:0": {"rank": 1, "hostname": "hostB"},
        }
        proc = _LiveProc2()
        d._procs["hostB:0"] = proc
        # No notification port registered: drain_worker fails → the
        # unreachable fallback terminates immediately, marked DRAINING.
        d._check_preemption()
        assert proc.terminated
        assert "hostB:0" in d._draining and "hostB:0" not in d._released
        assert d._reap_exits() is True
        assert d.registry.state_of("hostB:0") == LEFT
        assert not d.registry.is_blacklisted("hostB")

        # The reachable-but-wedged case: drained with a 0s grace, the
        # deadline enforcement terminates it.
        d2 = ElasticDriver(
            _NoticeDiscovery([DiscoveredHost("hostC", 1)],
                             notices=[]),
            ["true"], min_np=1, preempt_grace_s=0.0)
        try:
            proc2 = _LiveProc2()
            d2._procs["hostC:0"] = proc2
            d2._draining.add("hostC:0")
            d2._drain_deadlines["hostC:0"] = time.monotonic() - 1.0
            d2._enforce_drain_deadlines()
            assert proc2.terminated
            assert "hostC:0" not in d2._drain_deadlines
        finally:
            d2.rendezvous.stop()
    finally:
        d.rendezvous.stop()


def test_compute_assignments_allocates_stable_agent_ports():
    """Hierarchical × elastic (ISSUE 12): with the hierarchical knob in
    the worker env, every assignment carries its host's agent port — ONE
    per host, STABLE across generations (the generation-surviving agent
    holds the listen socket), newcomers getting fresh ports."""
    d = _driver(min_np=1, env={"HOROVOD_HIERARCHICAL_CONTROLLER": "1"})
    try:
        hosts = [DiscoveredHost("127.0.0.1", 2), DiscoveredHost("hostB", 1)]
        gen1 = d.compute_assignments(hosts)
        ports1 = {i: a["agent_port"] for i, a in gen1.items()}
        assert ports1["127.0.0.1:0"] == ports1["127.0.0.1:1"]
        assert ports1["127.0.0.1:0"] != ports1["hostB:0"]
        # Generation 2 (a host joined): existing hosts keep their ports.
        gen2 = d.compute_assignments(hosts + [DiscoveredHost("hostC", 1)])
        assert gen2["127.0.0.1:0"]["agent_port"] == ports1["127.0.0.1:0"]
        assert gen2["hostB:0"]["agent_port"] == ports1["hostB:0"]
        assert gen2["hostC:0"]["agent_port"] not in (
            ports1["127.0.0.1:0"], ports1["hostB:0"])
    finally:
        d.rendezvous.stop()

    # Control: flat worlds carry no agent ports.
    d2 = _driver(min_np=1)
    try:
        flat = d2.compute_assignments([DiscoveredHost("127.0.0.1", 1)])
        assert "agent_port" not in flat["127.0.0.1:0"]
    finally:
        d2.rendezvous.stop()


def test_commit_verb_reaches_manager_and_state():
    """Checkpoint pacing plumbing: a COMMIT ping on the notification
    channel surfaces exactly once through consume_commit_request (the
    ``state.should_commit()`` backend), without disturbing the DRAIN or
    host-update verbs."""
    from horovod_tpu.elastic.worker import WorkerNotificationManager

    mgr = WorkerNotificationManager()
    try:
        with socket.create_connection(("127.0.0.1", mgr._service.port),
                                      timeout=5) as s:
            s.sendall(b"COMMIT\n")
        deadline = time.monotonic() + 5
        got = False
        while time.monotonic() < deadline and not got:
            got = mgr.consume_commit_request()
            time.sleep(0.02)
        assert got, "COMMIT ping never surfaced"
        assert mgr.consume_commit_request() is False   # one-shot
        mgr.raise_if_updated()                         # no spurious verbs
    finally:
        mgr._service.stop()


def test_effective_hosts_preserves_discovery_order_for_new_hosts():
    """The first generation (and any batch of newcomers) must keep the
    DISCOVERY order — the documented hostfile-order rank/coordinator
    placement — not an alphabetical resort."""
    d = _driver(min_np=1, discovery_interval_s=1.0)
    disc = [DiscoveredHost("node-b", 4), DiscoveredHost("node-a", 4)]
    eff = d._effective_hosts(disc, now=100.0)
    assert [h.hostname for h in eff] == ["node-b", "node-a"]
    d._hosts = eff
    # Newcomers land AFTER the established order, in discovery order.
    disc2 = [DiscoveredHost("node-z", 1), DiscoveredHost("node-b", 4),
             DiscoveredHost("node-a", 4), DiscoveredHost("node-c", 1)]
    eff2 = d._effective_hosts(disc2, now=101.0)
    assert [h.hostname for h in eff2] == ["node-b", "node-a", "node-z",
                                          "node-c"]


# ------------------------------------------- stale-state guard (ISSUE 14)
def test_policy_stale_commit_age_refuses_evict_and_scale_in():
    """HOROVOD_COMMIT_MAX_AGE_S: a would-fire evict (and a would-fire
    scale_in) is REFUSED while the fleet's last state-plane commit is
    older than the bound — shrinking a world whose restore point is
    stale converts an orderly drain into lost work.  The hold carries
    the attribution, opens NO cooldown, and the decision fires the
    moment the fleet commits again."""
    per_rank = {0: 100.0, 1: 100.0, 2: 900.0}
    p = ScalePolicy(min_np=1, straggler_factor=3.0, persistence=2,
                    cooldown_s=0.0, commit_max_age_s=10.0)
    t = 1000.0
    for i in range(4):
        d = p.observe(_summary(spread=800, slowest=2, per_rank=per_rank,
                               progress_total=i, commit_age=60.0),
                      3, now=t + i)
        assert d.is_hold, (i, d)
        if i >= 1:      # persistence satisfied: the GUARD is what holds
            assert "stale-state guard" in d.reason, d.reason
    assert p.stale_holds >= 2
    # Fresh commit → the evict fires immediately (no cooldown was opened).
    d = p.observe(_summary(spread=800, slowest=2, per_rank=per_rank,
                           progress_total=9, commit_age=1.0),
                  3, now=t + 10)
    assert d.action == EVICT and d.evict_rank == 2, d

    # scale_in: same guard.
    p2 = ScalePolicy(min_np=1, persistence=1, cooldown_s=0.0, idle_s=5.0,
                     commit_max_age_s=10.0)
    p2.observe(_summary(q=0, progress_total=7, commit_age=60.0), 3,
               now=t)
    p2.observe(_summary(q=0, progress_total=7, commit_age=60.0), 3,
               now=t + 10)
    d = p2.observe(_summary(q=0, progress_total=7, commit_age=60.0), 3,
                   now=t + 20)
    assert d.is_hold and "stale-state guard" in d.reason, d
    d = p2.observe(_summary(q=0, progress_total=7, commit_age=2.0), 3,
                   now=t + 30)
    assert d.action == SCALE_IN, d


def test_policy_stale_guard_off_and_unknown_age_keep_old_behavior():
    """Guard off (0, the default) or no checkpoint telemetry (age None):
    evict/scale_in behave exactly as before ISSUE 14."""
    per_rank = {0: 100.0, 1: 100.0, 2: 900.0}
    for kwargs, age in (({}, 1e9), ({"commit_max_age_s": 10.0}, None)):
        p = ScalePolicy(min_np=1, straggler_factor=3.0, persistence=1,
                        cooldown_s=0.0, **kwargs)
        d = p.observe(_summary(spread=800, slowest=2, per_rank=per_rank,
                               progress_total=1, commit_age=age),
                      3, now=1000.0)
        assert d.action == EVICT, (kwargs, age, d)


def test_policy_preempt_exempt_from_stale_guard():
    """Preemption outranks the stale-state guard too: the hardware is
    going away on the platform's schedule — holding would just convert
    the orderly drain into a crash."""
    p = ScalePolicy(min_np=1, commit_max_age_s=1.0)
    d = p.observe(_summary(commit_age=1e9), 3, now=100.0,
                  preempt_hosts=("hostB",))
    assert d.action == "preempt", d


# ------------------------------------- commit-ack plumbing (ISSUE 14 fix)
def test_commit_ping_acked_by_worker_and_recorded_in_events():
    """The notification service replies ACK to a COMMIT ping; the driver
    records per-worker acks in the event log and returns them — the
    preempt drain's grace-bounded wait keys on exactly this."""
    from horovod_tpu.elastic.worker import WorkerNotificationManager

    mgr = WorkerNotificationManager()
    d = _driver(min_np=1)
    try:
        d._assigned = {"127.0.0.1:0": {"rank": 0,
                                       "hostname": "127.0.0.1"}}
        d._procs["127.0.0.1:0"] = _FakeProc(None)
        d.rendezvous._notify_ports["127.0.0.1:0"] = mgr._service.port
        acks = d._request_commit_all(wait_s=3.0)
        assert acks == {"127.0.0.1:0": True}, acks
        assert mgr.consume_commit_request() is True
        ev = next(e for e in d.events if e["action"] == "commit_request")
        assert ev["acks"]["127.0.0.1:0"] is True
        assert ev["acked"] == ["127.0.0.1:0"]
        # An unreachable worker records False — visible, not silent.
        d._procs["127.0.0.1:9"] = _FakeProc(None)
        d.rendezvous._notify_ports["127.0.0.1:9"] = 1     # dead port
        acks = d._request_commit_all(wait_s=1.0)
        assert acks["127.0.0.1:9"] is False, acks
    finally:
        mgr._service.stop()
        d.rendezvous.stop()


def test_no_op_regeneration_skipped_when_layout_unchanged():
    """ISSUE 14 (review/drive fix): a regeneration whose active
    membership + rank layout exactly matches the live generation — e.g.
    an already-cordoned host aging past the discovery-grace window right
    after its drain re-formed the world — must NOT re-publish: fresh
    ports would force every healthy worker through a pointless
    teardown/re-init, and sub-second back-to-back generations strand
    joiners on superseded init barriers.  Exited identities still
    respawn into the live generation."""
    d = _driver(min_np=1)
    try:
        hosts = [DiscoveredHost("127.0.0.1", 1)]
        assert d._new_generation(hosts) is True
        v1 = d.rendezvous.version
        a1 = dict(d._assigned)
        # Same membership again: no new version, same assignment table.
        assert d._new_generation(hosts) is True
        assert d.rendezvous.version == v1
        assert d._assigned == a1
        # A membership change DOES regenerate.
        assert d._new_generation(
            hosts + [DiscoveredHost("127.0.0.2", 1)]) is True
        assert d.rendezvous.version == v1 + 1
        # ...and an exited identity respawns into the unchanged layout
        # without a republish.
        v2 = d.rendezvous.version
        dead = _FakeProc(1)
        for i in d._assigned:
            d._procs[i] = dead
        d._new_generation(hosts + [DiscoveredHost("127.0.0.2", 1)])
        assert d.rendezvous.version == v2
        assert all(p is not dead for p in d._procs.values())
    finally:
        d._shutdown_workers()
        d.rendezvous.stop()
