"""Distributed collective tracing (tier-1, no jax in the core).

Covers the jax-free trace package (span ring, phase accounting, per-rank
writer, cross-rank merge with cycle flows, critical-path analyzer, CLI),
the disarmed-is-None contract, the MON1 digest riding the monitor
side-channel through the real native server with the steady-state frame
guard intact, HVD302 phase enrichment, per-rank filename unification, and
the purity guard extension lives in tests/test_monitor.py.
"""

import json
import logging
import os
import socket
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common.controller import TCPController
from horovod_tpu.monitor import MetricRegistry, MonitorAgent
from horovod_tpu.trace import (
    DIGEST_MAX_CYCLES, DIGEST_MAX_OPEN, PHASES, TraceRecorder, TraceWriter,
    maybe_install,
)
from horovod_tpu.trace.analyze import critical_path, phase_summary
from horovod_tpu.trace.merge import (
    RankTrace, expand_inputs, load_trace_file, merge_snapshot, merge_traces,
)
from horovod_tpu.utils.timeline import per_rank_filename

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stamp(span, t0, q=0.001, n=0.002, c=0.003, r=0.004, d=0.005):
    """Complete a claimed span with phase durations (seconds)."""
    span.t_ready = t0 + q + n
    span.t_launch = t0 + q + n + c
    span.t_result = t0 + q + n + c + r
    span.t_done = t0 + q + n + c + r + d
    return span


def _make_span(rec, name, cycle, t0, **durs):
    span = rec.begin(name, t0, t0 + durs.get("q", 0.001))
    assert span is not None
    span.cycle = cycle
    _stamp(span, t0, **durs)
    rec.commit(span)
    return span


# ------------------------------------------------------------------- core
def test_span_stamping_and_phase_partition():
    rec = TraceRecorder(capacity=64)
    t0 = 100.0
    span = rec.begin("grad.0", t0, t0 + 0.001)
    assert span.phase_name() == "negotiation"     # drained, not ready yet
    span.cycle = 7
    _stamp(span, t0, q=0.001, n=0.002, c=0.003, r=0.004, d=0.005)
    rec.commit(span)
    phases = span.phases_us()
    assert phases == {"queue": pytest.approx(1000, rel=1e-6),
                      "negotiation": pytest.approx(2000, rel=1e-6),
                      "copy_in": pytest.approx(3000, rel=1e-6),
                      "reduce": pytest.approx(4000, rel=1e-6),
                      "drain": pytest.approx(5000, rel=1e-6)}
    # The five phases partition the lifecycle: sums re-add exactly.
    assert sum(phases.values()) == pytest.approx(span.lifecycle_us(),
                                                 rel=1e-9)
    summary = rec.phase_summary()
    assert summary["spans"] == 1
    assert summary["phase_sum_us"] == pytest.approx(summary["cycle_us"],
                                                    abs=0.05)


def test_commit_is_idempotent_and_partial_spans_tolerated():
    rec = TraceRecorder(capacity=64)
    span = rec.begin("t", 1.0, 1.001)
    span.error = True
    rec.commit(span)
    rec.commit(span)                       # double settle must not double
    assert rec.spans_committed == 1
    # Only queue elapsed; later phases report 0, nothing negative.
    phases = span.phases_us()
    assert phases["queue"] > 0
    assert all(phases[p] == 0.0 for p in PHASES[1:])


def test_ring_reuses_slots_and_bounds_memory():
    rec = TraceRecorder(capacity=16)       # floor capacity
    seen = set()
    for i in range(100):
        span = rec.begin(f"g.{i}", float(i), float(i) + 0.1)
        seen.add(id(span))
        span.cycle = i
        _stamp(span, float(i))
        rec.commit(span)
    # Zero allocation on the hot path: span objects are recycled in place.
    assert len(seen) <= 16
    assert rec.spans_committed == 100
    assert rec.dropped == 0


def test_ring_full_of_open_spans_drops_claims_not_blocks():
    rec = TraceRecorder(capacity=16)
    held = [rec.begin(f"h.{i}", 0.0, 0.1) for i in range(16)]
    assert all(s is not None for s in held)
    assert rec.begin("overflow", 0.0, 0.1) is None
    assert rec.dropped == 1
    rec.commit(_stamp(held[0], 0.0))
    assert rec.begin("retry", 0.0, 0.1) is not None


def test_disarmed_recorder_is_none():
    from horovod_tpu.common.config import Config
    assert maybe_install(Config()) is None
    cfg = Config()
    cfg.trace = True
    rec = maybe_install(cfg, rank=3)
    assert isinstance(rec, TraceRecorder) and rec.rank == 3


def test_trace_env_parsing(monkeypatch):
    from horovod_tpu.common.config import Config
    monkeypatch.delenv("HOROVOD_TRACE", raising=False)
    monkeypatch.delenv("HVD_TPU_TRACE", raising=False)
    assert Config.from_env().trace is False
    monkeypatch.setenv("HOROVOD_TRACE", "1")
    cfg = Config.from_env()
    assert cfg.trace is True and cfg.trace_filename == ""
    monkeypatch.setenv("HOROVOD_TRACE", "/tmp/tr.json")
    cfg = Config.from_env()
    assert cfg.trace is True and cfg.trace_filename == "/tmp/tr.json"
    monkeypatch.setenv("HOROVOD_TRACE", "0")
    assert Config.from_env().trace is False
    monkeypatch.setenv("HOROVOD_TRACE_RING", "128")
    monkeypatch.setenv("HOROVOD_TRACE", "1")
    assert Config.from_env().trace_ring == 128


def test_digest_is_size_capped():
    rec = TraceRecorder(capacity=64)
    for cyc in range(200):                 # far over DIGEST_MAX_CYCLES
        rec.cycle(cyc, 0.0, 0.001, 0.002, 0.003, 8, 42.0)
        _make_span(rec, f"g.{cyc % 8}", cyc, float(cyc))
    for i in range(40):                    # open spans over DIGEST_MAX_OPEN
        rec.begin(f"open.{i}", 0.0, 0.1)
    d = rec.digest()
    assert len(d["cycles"]) == DIGEST_MAX_CYCLES
    assert len(d["open"]) <= DIGEST_MAX_OPEN
    assert set(d["phases"]) == set(PHASES)
    blob = json.dumps(d, separators=(",", ":")).encode()
    assert len(blob) <= 8192, len(blob)    # far inside the 48KB blob guard


def test_phase_histograms_feed_registry():
    rec = TraceRecorder(capacity=64)
    _make_span(rec, "g", 1, 10.0)
    hists = rec.phase_histograms()
    assert set(hists) == set(PHASES)
    counts, sum_us, count = hists["reduce"]
    assert count == 1 and sum_us == pytest.approx(4000, rel=1e-6)
    reg = MetricRegistry()
    h = reg.histogram("hvd_trace_reduce_us", buckets=rec.buckets)
    h.set_cumulative(counts, sum_us, count)
    snap = h.snapshot_value()
    assert snap["count"] == 1 and snap["sum"] == pytest.approx(4000, abs=0.1)
    # set_cumulative never regresses (mirrored totals, like set_total).
    h.set_cumulative([0] * len(counts), 0, 0)
    assert h.snapshot_value()["count"] == 1
    with pytest.raises(ValueError):
        h.set_cumulative([1], 1, 1)


def test_reduce_legs_partition_hier_spans_only():
    """ISSUE 17: spans carrying a modeled cross_frac split their reduce
    phase into ICI/DCN legs that re-add EXACTLY; flat spans (frac 0)
    never touch the leg accumulators, so leg totals attribute only the
    time the two-level path actually ran."""
    from horovod_tpu.trace import REDUCE_LEGS

    rec = TraceRecorder(capacity=64)
    _make_span(rec, "flat", 1, 10.0)                   # frac 0.0
    span = rec.begin("hier", 20.0, 20.001)
    span.cycle = 2
    span.cross_frac = 0.25
    _stamp(span, 20.0)                                 # reduce = 4000us
    rec.commit(span)

    assert rec.leg_spans == 1
    hists = rec.phase_histograms()
    assert set(REDUCE_LEGS) <= set(hists)
    _, intra_us, n_i = hists[REDUCE_LEGS[0]]
    _, cross_us, n_c = hists[REDUCE_LEGS[1]]
    assert n_i == 1 and n_c == 1
    assert intra_us == pytest.approx(3000, rel=1e-6)
    assert cross_us == pytest.approx(1000, rel=1e-6)
    # the split re-adds to the hier span's reduce share exactly
    assert intra_us + cross_us == pytest.approx(4000, rel=1e-6)

    summary = rec.phase_summary()
    assert summary["leg_spans"] == 1
    assert summary["legs_us"][REDUCE_LEGS[1]] == pytest.approx(1000,
                                                               rel=1e-3)
    digest = rec.digest()
    assert "legs" in digest and REDUCE_LEGS[1] in digest["legs"]

    # monitor mirroring: leg keys ride the generic histogram loop
    reg = MetricRegistry()
    counts, sum_us, count = hists[REDUCE_LEGS[1]]
    h = reg.histogram("hvd_trace_reduce_cross_us", buckets=rec.buckets)
    h.set_cumulative(counts, sum_us, count)
    assert h.snapshot_value()["sum"] == pytest.approx(1000, abs=0.1)


def test_reduce_legs_absent_on_flat_runs():
    """A recorder that never saw a two-level span exposes NO leg keys —
    flat traces and /metrics stay byte-identical to the pre-ISSUE-17
    shape (the disarmed-costs-nothing contract, leg edition)."""
    from horovod_tpu.trace import REDUCE_LEGS

    rec = TraceRecorder(capacity=64)
    _make_span(rec, "flat", 1, 10.0)
    assert rec.leg_spans == 0
    assert set(rec.phase_histograms()) == set(PHASES)
    assert "leg_spans" not in rec.phase_summary()
    assert "legs" not in rec.digest()
    for leg in REDUCE_LEGS:
        assert leg not in rec.phase_histograms()


def test_analyzer_splits_reduce_by_cf_key(tmp_path):
    """Offline agreement: span lines carrying ``cf`` split the reduce
    phase in phase_summary()['legs'] with the same carry-forward rule the
    live recorder applies, and the report renders the ICI/DCN block."""
    from horovod_tpu.trace.analyze import render_report

    path = str(tmp_path / per_rank_filename("tr", 0))
    writer = TraceWriter(path, rank=0)
    rec = TraceRecorder(capacity=64, writer=writer, rank=0)
    rec.anchor_wall, rec.anchor_mono = 1000.0, 0.0
    writer.header(rank=0, anchor_wall=1000.0, anchor_mono=0.0)
    rec.cycle(1, 1.0, 1.001, 1.002, 1.003, 2, 50.0)
    _make_span(rec, "flat", 1, 1.0)
    span = rec.begin("hier", 2.0, 2.001)
    span.cycle = 1
    span.cross_frac = 0.5
    _stamp(span, 2.0)
    rec.commit(span)
    rec.close()

    rt = load_trace_file(path)
    flat_line = next(s for s in rt.spans if s["n"] == "flat")
    hier_line = next(s for s in rt.spans if s["n"] == "hier")
    assert "cf" not in flat_line                 # flat lines pay 0 bytes
    assert hier_line["cf"] == pytest.approx(0.5, abs=1e-4)

    summary = phase_summary([rt])
    legs = summary["legs"]
    assert legs["reduce_intra"]["spans"] == 1
    assert legs["reduce_cross"]["total_us"] == pytest.approx(2000,
                                                             rel=1e-3)
    report = render_report([rt])
    assert "two-level reduce legs" in report
    assert "DCN" in report and "ICI" in report


# ------------------------------------------------------------ writer/merge
def _write_rank_file(tmp_path, rank, cycles, anchor_wall=1000.0,
                     phase_scale=1.0):
    """A per-rank trace file with `cycles` cycles of 2 tensors each."""
    path = str(tmp_path / per_rank_filename("tr", rank))
    writer = TraceWriter(path, rank=rank)
    rec = TraceRecorder(capacity=64, writer=writer, rank=rank)
    rec.anchor_wall, rec.anchor_mono = anchor_wall, 0.0
    writer.header(rank=rank, anchor_wall=anchor_wall, anchor_mono=0.0)
    for cyc in range(1, cycles + 1):
        t0 = cyc * 1.0
        rec.cycle(cyc, t0, t0 + 0.001, t0 + 0.002, t0 + 0.003, 2, 50.0)
        for j in range(2):
            _make_span(rec, f"g.{j}", cyc, t0,
                       n=0.002 * phase_scale, r=0.004 * phase_scale)
    rec.close()
    return path


def test_writer_roundtrip_and_merge_has_lanes_and_flows(tmp_path):
    p0 = _write_rank_file(tmp_path, 0, cycles=3)
    p1 = _write_rank_file(tmp_path, 1, cycles=3, phase_scale=3.0)
    rt0, rt1 = load_trace_file(p0), load_trace_file(p1)
    assert rt0.rank == 0 and rt1.rank == 1
    assert len(rt0.spans) == 6 and len(rt0.cycles) == 3
    merged = merge_traces([rt0, rt1])
    ev = merged["traceEvents"]
    pids = {e["pid"] for e in ev if e.get("ph") == "X"}
    assert pids == {0, 1}, "one lane per rank"
    names = {e["args"]["name"] for e in ev if e["ph"] == "M"
             and e["name"] == "process_name"}
    assert names == {"rank 0", "rank 1"}
    # Phase slices present for every phase.
    slice_names = {e["name"] for e in ev if e.get("ph") == "X"}
    for p in PHASES:
        assert p.upper() in slice_names
    # Cycle-correlated flows: every common cycle id has a flow start on
    # one rank and a flow finish on the other.
    starts = {e["id"]: e["pid"] for e in ev if e.get("ph") == "s"}
    ends = {e["id"]: e["pid"] for e in ev if e.get("ph") == "f"}
    assert set(starts) == set(ends) == {1, 2, 3}
    assert all(starts[c] != ends[c] for c in starts)


def test_expand_inputs_globs_rank_suffixes(tmp_path):
    p0 = _write_rank_file(tmp_path, 0, cycles=1)
    p1 = _write_rank_file(tmp_path, 1, cycles=1)
    assert expand_inputs([str(tmp_path / "tr")]) == [p0, p1]
    assert expand_inputs([p1]) == [p1]
    with pytest.raises(FileNotFoundError):
        expand_inputs([str(tmp_path / "nope")])


def test_cli_merges_and_reports(tmp_path, capsys):
    from horovod_tpu.trace.__main__ import main
    _write_rank_file(tmp_path, 0, cycles=3)
    _write_rank_file(tmp_path, 1, cycles=3, phase_scale=2.0)
    out = str(tmp_path / "merged.json")
    rc = main([str(tmp_path / "tr"), "-o", out, "--report"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "critical-path attribution" in text
    assert "wrote" in text
    with open(out) as fh:
        merged = json.load(fh)
    assert {e["pid"] for e in merged["traceEvents"]} >= {0, 1}


def test_cli_rejects_bad_usage(tmp_path, capsys):
    from horovod_tpu.trace.__main__ import main
    with pytest.raises(SystemExit):
        main([])
    rc = main([str(tmp_path / "missing")])
    assert rc == 1


# ---------------------------------------------------------------- analyzer
def test_analyzer_agrees_with_recorder_on_partial_spans():
    """One attribution rule: a span missing a mid-stamp (batch failed
    before the launch stamp) carries the elapsed time into the phase that
    contains it, identically in the live recorder and the offline
    analyzer — the --report can never disagree with the MON1 digest."""
    from horovod_tpu.trace.analyze import _span_phases_us
    line = {"e": 1.0, "d": 1.001, "r": 1.003, "l": 0.0, "x": 1.009,
            "f": 1.010}
    offline = _span_phases_us(line)
    span = TraceRecorder(capacity=16).begin("t", 1.0, 1.001)
    span.t_ready, span.t_launch = 1.003, 0.0
    span.t_result, span.t_done = 1.009, 1.010
    live = span.phases_us()
    assert offline == live
    assert offline["copy_in"] == 0.0
    assert offline["reduce"] == pytest.approx(6000, rel=1e-6)
    # Nothing vanishes: the full lifecycle is attributed.
    assert sum(offline.values()) == pytest.approx(10000, rel=1e-6)

def test_critical_path_names_slowest_rank_and_attributes_phases(tmp_path):
    p0 = _write_rank_file(tmp_path, 0, cycles=4)
    p1 = _write_rank_file(tmp_path, 1, cycles=4, phase_scale=5.0)
    ranks = [load_trace_file(p0), load_trace_file(p1)]
    cp = critical_path(ranks)
    assert len(cp["cycles"]) == 4
    # Rank 1's phases are 5x: it gates every lock-step cycle.
    assert all(row["slowest_rank"] == 1 for row in cp["cycles"])
    assert cp["slowest_counts"] == {1: 4}
    att = cp["attributed_us"]
    # reduce (scaled 0.020s/span) dominates over drain (0.005s/span).
    assert att["reduce"] > att["drain"] > 0
    summary = phase_summary(ranks)
    assert summary["fleet"]["queue"]["spans"] == 16


def test_merge_snapshot_builds_digest_lanes():
    dump = {"table": {
        "0": {"trace": {"cycles": [[5, 2, 10, 20, 30, 40, 5]]}},
        "1": {"trace": {"cycles": [[5, 2, 12, 25, 33, 44, 6]]}},
    }}
    merged = merge_snapshot(dump)
    ev = merged["traceEvents"]
    assert {e["pid"] for e in ev if e.get("ph") == "X"} == {0, 1}
    assert {e["id"] for e in ev if e.get("ph") == "s"} == {5}
    assert {e["id"] for e in ev if e.get("ph") == "f"} == {5}


# ------------------------------------------- side-channel + frame guard
def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class E:
    def __init__(self, name, shape=(4,)):
        self.name = name
        self.tensor = np.zeros((2,) + tuple(shape), np.float32)


class FakeEngine:
    """Duck-typed engine surface the MonitorAgent collectors read."""

    def __init__(self, tracer=None):
        self.cycle_count = 10
        self.cycle_us_total = 1000.0
        self.last_cycle_ts = time.time()
        self._cycle_index = 10
        self.negotiation_us_total = 0.0
        self.negotiation_cycles = 0
        self.pipeline_chunks_total = 0
        self.pipeline_dispatches = 0
        self.monitor = None
        self.tracer = tracer


def _pair(fn, cache_capacity=2048):
    port = _free_port()
    results, errors = {}, {}
    peer_done = threading.Event()

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0,
                            cache_capacity=cache_capacity)
        try:
            results[rank] = fn(ctl, rank)
        except Exception as exc:  # noqa: BLE001 - surfaced by the assert
            errors[rank] = exc
        finally:
            if rank == 1:
                peer_done.set()
                ctl.shutdown()
            else:
                peer_done.wait(timeout=20)
                ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(timeout=20)
    assert not errors, errors
    assert set(results) == {0, 1}, results
    return results


def _steps(ctl, make_entries, n_steps, max_rounds=20):
    for _ in range(n_steps):
        entries = list(make_entries())
        got = []
        for _round in range(max_rounds):
            if not entries:
                break
            ready, errs = ctl.negotiate(entries)
            assert not errs, errs
            got += [e.name for e in ready]
            entries = [e for e in entries if e.name not in set(got)]
        assert not entries, f"never ready: {[e.name for e in entries]}"


def test_frame_guard_holds_with_tracing_digests_riding_mon1():
    """CI satellite: with tracing armed AND a MonitorAgent attached, the
    trace digest rides the MON1 side-channel (peers decode it from the
    aggregation table), the digest blob stays inside the size cap, and
    steady-state warm-path frames stay byte-stable — zero per-tensor
    metadata, the same fixed handful of negotiation-critical bytes."""
    names = [f"grad.{i}" for i in range(8)]

    def fn(ctl, rank):
        tracer = TraceRecorder(capacity=256, rank=rank)
        for cyc in range(1, 6):
            tracer.cycle(cyc, cyc * 1.0, cyc + 0.001, cyc + 0.002,
                         cyc + 0.003, 8, 40.0)
            _make_span(tracer, f"grad.{cyc % 8}", cyc, cyc * 1.0)
        agent = MonitorAgent(engine=FakeEngine(tracer=tracer),
                             controller=ctl, rank=rank, world=2,
                             interval_s=0.05)
        blob = agent.encode_frame()
        assert blob is not None and len(blob) <= 48 * 1024
        assert json.loads(blob.decode())["trace"]["cycles"], \
            "digest must ride the snapshot"
        mk = lambda: [E(n) for n in names]            # noqa: E731
        _steps(ctl, mk, 2)                            # warm-up: learn slots
        time.sleep(0.06)                              # arm the interval
        st = ctl.cache_stats
        full_before = st.full_announces
        bytes_before = ctl.bytes_sent
        mon_before = ctl.monitor_bytes_sent
        _steps(ctl, mk, 5)
        assert st.full_announces == full_before, (
            "tracing pushed steady-state cycles off the bitvector path")
        mon_bytes = ctl.monitor_bytes_sent - mon_before
        per_cycle = (ctl.bytes_sent - bytes_before - mon_bytes) / 5
        assert per_cycle <= 16, per_cycle
        deadline = time.monotonic() + 10
        while (len(agent.aggregator.ranks()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.06)
            _steps(ctl, mk, 1)
        peer = 1 - rank
        snap = agent.aggregator.snapshot_of(peer)
        assert snap is not None, agent.aggregator.table()
        assert snap.get("trace", {}).get("cycles"), (
            f"rank {rank}: peer digest missing: {snap.get('trace')}")
        return True

    _pair(fn)


def test_hvd302_report_quotes_laggard_phase_and_cycle_breakdown():
    """Satellite: the peer attribution block names the phase the laggard
    is stuck in and its last completed cycle's phase breakdown, alongside
    the ledger tail."""
    agent = MonitorAgent(engine=FakeEngine(), rank=0, world=2)
    agent.aggregator.update(1, {
        "ledger": ["#12 grad.7 [allreduce|float32|(8,)] at train.py:50"],
        "trace": {"v": 1, "open": {"grad.9": "negotiation"},
                  "cycles": [[41, 8, 100, 50, 200, 300, 10],
                             [42, 8, 110, 60, 210, 310, 12]]},
    })
    report = agent.peer_ledger_report()
    assert "rank 1 last submissions" in report
    assert "rank 1 currently in phase negotiation: grad.9" in report
    assert "rank 1 last cycle 42 (8 tensors)" in report
    assert "copy_in=210us" in report and "reduce=310us" in report
    # Phase-only peers (tracing without sanitizer ledger) still report.
    agent2 = MonitorAgent(engine=FakeEngine(), rank=0, world=2)
    agent2.aggregator.update(1, {
        "trace": {"open": {"g": "reduce"}, "cycles": []}})
    assert "currently in phase reduce" in agent2.peer_ledger_report()
    # The canonical skew stall: the laggard hasn't ENQUEUED yet, so its
    # digest has no open spans — the last-cycle breakdown must still
    # make it into the report.
    agent3 = MonitorAgent(engine=FakeEngine(), rank=0, world=2)
    agent3.aggregator.update(1, {
        "trace": {"cycles": [[9, 3, 10, 20, 30, 40, 5]]}})
    assert "rank 1 last cycle 9 (3 tensors)" in agent3.peer_ledger_report()


def test_dropped_claim_latches_entry_untraceable():
    """A tensor whose drain-time span claim was dropped (ring full) must
    never be re-claimed on a later drain — that would fold its elapsed
    negotiation time into the queue phase and re-count `dropped`."""
    from horovod_tpu.ops.engine import _SPAN_DROPPED, _live_span

    class Entry:
        span = None

    rec = TraceRecorder(capacity=16)
    held = [rec.begin(f"h.{i}", 0.0, 0.1) for i in range(16)]  # exhaust
    e = Entry()
    # The engine's drain-loop idiom: claim-or-latch, exactly once.
    if e.span is None:
        e.span = rec.begin("x", 0.0, 0.1) or _SPAN_DROPPED
    assert e.span is _SPAN_DROPPED and rec.dropped == 1
    # Requeued + re-drained: the sentinel blocks the re-claim even after
    # slots free up, and every stamp site sees "no span".
    rec.commit(_stamp(held[0], 0.0))
    if e.span is None:          # must NOT fire
        e.span = rec.begin("x", 0.5, 0.6) or _SPAN_DROPPED
    assert e.span is _SPAN_DROPPED
    assert _live_span(e) is None
    assert rec.dropped == 1


def test_stall_inspector_names_current_phase():
    """Engine-side half of the HVD302 phase satellite: the stall warning
    names the phase the stuck entry is in when tracing is armed."""
    from horovod_tpu.ops.scheduler import StallInspector
    from horovod_tpu.utils.logging import get_logger

    rec = TraceRecorder(capacity=64)
    insp = StallInspector(warn_after_s=0.0, shutdown_after_s=0.0)

    class Entry:
        name = "stuck.t"
        enqueue_time = time.monotonic() - 5.0
        span = rec.begin("stuck.t", time.monotonic() - 5.0,
                         time.monotonic() - 4.9)

    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    logger = get_logger()
    logger.addHandler(handler)
    try:
        insp.check([Entry()])
    finally:
        logger.removeHandler(handler)
    assert records and "stuck in phase negotiation" in records[0], records


# ----------------------------------------------- per-rank filename scheme
def test_per_rank_filename_unifies_all_launch_paths(monkeypatch, tmp_path):
    """Satellite: run.py, tpu_vm.py and the elastic bootstrap all produce
    the same ``<base>.<rank>`` names through one helper."""
    assert per_rank_filename("/tmp/tl", 3) == "/tmp/tl.3"

    # torovodrun static path: rank suffix on timeline AND trace.
    from horovod_tpu.runner.run import parse_args, placement, worker_envs
    args = parse_args(["-np", "2", "--timeline-filename", "/tmp/tl",
                       "--trace-filename", "/tmp/tr", "python", "t.py"])
    envs = worker_envs(args, placement(args), ("127.0.0.1", 5555, 5556))
    assert [e["HOROVOD_TIMELINE"] for e in envs] == ["/tmp/tl.0",
                                                     "/tmp/tl.1"]
    assert [e["HOROVOD_TRACE"] for e in envs] == ["/tmp/tr.0", "/tmp/tr.1"]

    # TPU-VM pod path: worker_id IS the process rank; same scheme.
    from horovod_tpu.runner import tpu_vm

    class EP:
        internal_ip = "10.0.0.1"
        external_ip = "1.2.3.4"
    env = tpu_vm.tpu_vm_worker_env(args, [EP(), EP()], worker_id=1,
                                   ports=(5555, 5556))
    assert env["HOROVOD_TIMELINE"] == "/tmp/tl.1"
    assert env["HOROVOD_TRACE"] == "/tmp/tr.1"

    # Elastic path: the env carries the BASE; the bootstrap suffixes with
    # the rendezvous-assigned rank (the driver can't know ranks earlier).
    from horovod_tpu.elastic import worker as ew
    monkeypatch.setattr(ew, "_current_version", None)
    monkeypatch.setattr(
        ew.rdv, "fetch_assignment",
        lambda *a, **k: {"version": 0, "rank": 1, "size": 2,
                         "local_rank": 0, "local_size": 1, "cross_rank": 1,
                         "cross_size": 2, "controller_addr": "127.0.0.1",
                         "controller_port": 1234, "controller_port2": 1235})
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_PORT", "9999")
    monkeypatch.setenv("HOROVOD_TIMELINE", "/tmp/tl")
    monkeypatch.setenv("HOROVOD_TRACE", "/tmp/tr")
    # elastic_bootstrap projects its assignment into os.environ directly
    # (by design — workers re-read it); scrub those keys afterwards or a
    # later hvd.init() in this process would take the multi-process path.
    assign_keys = [f"HOROVOD_{k}" for k in (
        "RANK", "SIZE", "LOCAL_RANK", "LOCAL_SIZE", "CROSS_RANK",
        "CROSS_SIZE", "CONTROLLER_ADDR", "CONTROLLER_PORT",
        "CONTROLLER_PORT2")]
    saved = {k: os.environ.get(k) for k in assign_keys}
    try:
        cfg = ew.elastic_bootstrap()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert cfg.timeline_filename == "/tmp/tl.1"
    assert cfg.trace_filename == "/tmp/tr.1"
    # The env keeps the BASE so the next generation re-suffixes cleanly.
    assert os.environ["HOROVOD_TIMELINE"] == "/tmp/tl"
    assert os.environ["HOROVOD_TRACE"] == "/tmp/tr"
