"""Basics API tests: init/rank/size/process sets.

Mirrors the reference's rank/size assertions scattered through
``test/parallel/test_torch.py`` (SURVEY.md §4).
"""

import pytest


def test_init_idempotent(hvd):
    assert hvd.is_initialized()
    hvd.init()
    assert hvd.is_initialized()


def test_world(hvd, world_size):
    assert hvd.size() == world_size == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()


def test_capabilities(hvd):
    assert hvd.xla_built()
    assert not hvd.nccl_built()
    assert not hvd.mpi_enabled()
    assert not hvd.cuda_built()


def test_mesh(hvd, world_size):
    m = hvd.mesh()
    assert m.devices.size == world_size
    assert m.axis_names == ("hvd",)


def test_process_set_add_remove(hvd):
    ps = hvd.add_process_set([0, 1, 2])
    try:
        assert ps.size() == 3
        assert ps.included(0) and not ps.included(3)
        assert ps.rank_in_set(2) == 2
        with pytest.raises(ValueError):
            hvd.add_process_set([0, 1, 2])  # duplicate
    finally:
        hvd.remove_process_set(ps)


def test_global_process_set(hvd, world_size):
    from horovod_tpu import global_process_set
    assert global_process_set.process_set_id == 0
    assert global_process_set.size() == world_size


def test_profile_trace_writes_xplane(hvd, tmp_path):
    """start_profile/stop_profile produce an XProf trace directory
    (the device-level complement to the coordinator timeline)."""
    import os
    import numpy as np

    logdir = str(tmp_path / "prof")
    with hvd.profile_step(logdir):
        hvd.allreduce(hvd.stack_per_rank(
            [np.ones((4,), np.float32)] * hvd.size()), op=hvd.Sum,
            name="profiled_ar")
    hits = [f for _, _, files in os.walk(logdir) for f in files
            if f.endswith(".xplane.pb")]
    assert hits, f"no xplane trace written under {logdir}"
