"""Callbacks (reference P5) and data helpers (reference P13) tests."""

import numpy as np
import pytest

import horovod_tpu as hvd_mod
from horovod_tpu import callbacks as cb
from horovod_tpu.data import (
    AsyncDataLoaderMixin, ShardedBatchIterator, prefetch_to_device,
    shard_indices)


class _State:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


# ---------------------------------------------------------------- callbacks
def test_broadcast_global_variables_callback(hvd):
    params = {"w": np.ones((3, 2), np.float32), "b": np.zeros(2, np.float32)}
    state = _State(params=params, opt_state=None)
    cb.BroadcastGlobalVariablesCallback(0).on_train_begin(state)
    np.testing.assert_allclose(state.params["w"], params["w"])
    np.testing.assert_allclose(state.params["b"], params["b"])


def test_broadcast_pytree_nested(hvd):
    tree = {"layer": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
            "step": np.asarray(3, np.int32)}
    out = cb.broadcast_pytree(tree)
    np.testing.assert_allclose(out["layer"]["w"], tree["layer"]["w"])
    assert out["step"] == 3
    assert out["step"].dtype == np.int32


def test_metric_average_callback(hvd):
    metrics = {"loss": 2.0, "acc": 0.5, "name": "skip-me"}
    cb.MetricAverageCallback().on_epoch_end(0, metrics=metrics)
    # Identical contributions -> averages unchanged; strings untouched.
    assert metrics["loss"] == pytest.approx(2.0)
    assert metrics["acc"] == pytest.approx(0.5)
    assert metrics["name"] == "skip-me"


def test_lr_warmup_callback(hvd):
    state = _State(lr=0.0)
    warm = cb.LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=4)
    size = hvd_mod.size()
    warm.on_epoch_begin(0, state)
    first = state.lr
    assert first == pytest.approx(0.1 * (1 + (size - 1) * 1 / 4))
    warm.on_epoch_begin(3, state)  # last warmup epoch lands on size()
    assert state.lr == pytest.approx(0.1 * size)
    # After warmup the callback must NOT touch lr (composability with decay
    # schedules — reference uses end_epoch=warmup_epochs).
    state.lr = 123.0
    warm.on_epoch_begin(10, state)
    assert state.lr == 123.0


def test_lr_schedule_callback(hvd):
    state = _State(lr=1.0)
    sched = cb.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e, start_epoch=1,
        end_epoch=3)
    sched.on_epoch_begin(0, state)
    assert state.lr == 1.0  # before start_epoch
    sched.on_epoch_begin(1, state)
    assert state.lr == pytest.approx(0.1)
    sched.on_epoch_begin(3, state)
    assert state.lr == pytest.approx(0.1)  # after end_epoch: unchanged


def test_warmup_scaled_schedule(hvd):
    sched = cb.warmup_scaled_schedule(0.1, steps_per_epoch=10,
                                      warmup_epochs=2)
    size = hvd_mod.size()
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(20)) == pytest.approx(0.1 * size)
    assert float(sched(10)) == pytest.approx(0.1 * (1 + size) / 2)


# -------------------------------------------------------------------- data
class _ListLoader:
    def __init__(self, items):
        self.items = items

    def __iter__(self):
        yield from self.items


class _AsyncListLoader(AsyncDataLoaderMixin, _ListLoader):
    pass


def test_async_data_loader_mixin():
    loader = _AsyncListLoader(list(range(100)), async_loader_queue_size=8)
    assert list(loader) == list(range(100))
    assert list(loader) == list(range(100))  # re-iterable
    loader.close_async_loader()


def test_async_data_loader_disabled():
    loader = _AsyncListLoader([1, 2, 3], async_loader_queue_size=0)
    assert list(loader) == [1, 2, 3]


def test_async_data_loader_propagates_errors():
    class Bad:
        def __iter__(self):
            yield 1
            raise ValueError("boom")

    class AsyncBad(AsyncDataLoaderMixin, Bad):
        pass

    with pytest.raises(ValueError, match="boom"):
        list(AsyncBad(async_loader_queue_size=4))


def test_shard_indices_partition():
    parts = [shard_indices(103, rank=r, size=4, shuffle=True, seed=1,
                           drop_remainder=True) for r in range(4)]
    flat = np.concatenate(parts)
    assert len(flat) == 25 * 4
    assert len(set(flat.tolist())) == 100  # disjoint
    # Without drop_remainder: equal per-rank lengths (pad by wrapping, the
    # DistributedSampler contract) and full coverage.
    parts = [shard_indices(103, rank=r, size=4, shuffle=False,
                           drop_remainder=False) for r in range(4)]
    assert all(len(p) == 26 for p in parts)
    assert set(np.concatenate(parts).tolist()) == set(range(103))


def test_sharded_batch_iterator_single_controller(hvd):
    x = np.arange(64, dtype=np.float32)
    y = x * 2
    it = ShardedBatchIterator([x, y], batch_size=2, shuffle=False)
    batches = list(it)
    # Single-controller: global batches of batch_size * size().
    assert len(batches) == len(it) == 64 // (2 * hvd_mod.size())
    bx, by = batches[0]
    assert bx.shape == (2 * hvd_mod.size(),)
    np.testing.assert_allclose(by, bx * 2)
    # Epoch changes reshuffle deterministically.
    it2 = ShardedBatchIterator([x, y], batch_size=2, shuffle=True, seed=3)
    it2.set_epoch(0)
    a = [b[0] for b in it2]
    it2.set_epoch(1)
    b = [b[0] for b in it2]
    assert not all(np.array_equal(p, q) for p, q in zip(a, b))


def test_prefetch_to_device(hvd):
    import jax
    batches = [(np.full((2,), i, np.float32),) for i in range(10)]
    out = list(prefetch_to_device(iter(batches), size=3))
    assert len(out) == 10
    assert all(isinstance(b[0], jax.Array) for b in out)
    np.testing.assert_allclose(np.asarray(out[7][0]), 7.0)


def test_sharded_batch_iterator_len_matches_iter_tail(hvd):
    x = np.arange(10, dtype=np.float32)
    # drop_remainder=False: short final batch, len() counts it.
    it = ShardedBatchIterator([x], batch_size=3, shuffle=False,
                              drop_remainder=False)
    batches = list(it)
    assert len(batches) == len(it)
    assert sum(len(b[0]) for b in batches) == 10
