"""Control-plane fault tolerance (tier-1, no subprocess spawns).

Covers: the typed exception taxonomy, the retry/backoff helper, the
fault-injection harness (``horovod_tpu/testing/faults.py``), the protocol
v4 liveness machinery through REAL native server + client threads
(dead-peer abort, round deadline, client recv timeout, connect retries),
the engine's clean-shutdown invariants (``InflightRing.abort``), and the
monitor agent's HVD303 enrichment + ``/health`` ``peer_dead`` reporting.
The cross-process acceptance lives in ``tests/test_multiprocess.py``
(``worker_faults.py``).
"""

import socket
import struct
import threading
import time

import pytest

from horovod_tpu.common.controller import TCPController
from horovod_tpu.common.exceptions import (
    ControlPlaneError, HorovodInternalError, JoinTimeoutError,
    PeerFailureError, RoundTimeoutError,
)
from horovod_tpu.common.net import retry_with_backoff
from horovod_tpu.testing import faults


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends unarmed — an armed leak would make the
    controller cache the fire hook in unrelated tests."""
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------- exceptions
def test_exception_taxonomy():
    """PeerFailureError / RoundTimeoutError are ControlPlaneErrors are
    HorovodInternalErrors — the elastic wrapper's catch covers all of
    them; JoinTimeoutError is a TimeoutError (pre-existing handlers keep
    working)."""
    assert issubclass(PeerFailureError, ControlPlaneError)
    assert issubclass(RoundTimeoutError, ControlPlaneError)
    assert issubclass(ControlPlaneError, HorovodInternalError)
    assert issubclass(HorovodInternalError, RuntimeError)
    assert issubclass(JoinTimeoutError, TimeoutError)
    exc = PeerFailureError("boom", dead_ranks=[3, 1], reason="died")
    assert exc.dead_ranks == [1, 3] and exc.reason == "died"
    # The legacy import path still resolves (re-export contract).
    from horovod_tpu.elastic.state import (
        HorovodInternalError as legacy, PeerFailureError as legacy_pf)
    assert legacy is HorovodInternalError and legacy_pf is PeerFailureError


# ---------------------------------------------------------- retry/backoff
def test_retry_with_backoff_succeeds_after_failures():
    calls = []
    delays = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = retry_with_backoff(fn, retries=4, base_ms=1.0, max_ms=4.0,
                             jitter=0.0,
                             on_retry=lambda a, e, d: delays.append(d))
    assert out == "ok" and len(calls) == 3
    # Exponential schedule: 1ms then 2ms (jitter disabled).
    assert delays == [0.001, 0.002]


def test_retry_with_backoff_exhausts_and_reraises():
    calls = []

    def fn():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_with_backoff(fn, retries=2, base_ms=1.0, jitter=0.0)
    assert len(calls) == 3      # 1 initial + 2 retries


def test_retry_with_backoff_caps_delay():
    delays = []

    def fn():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_with_backoff(fn, retries=5, base_ms=1.0, max_ms=2.0,
                           jitter=0.0,
                           on_retry=lambda a, e, d: delays.append(d))
    assert max(delays) <= 0.002 + 1e-9


# ------------------------------------------------------ fault-spec parsing
def test_fault_spec_parse_forms():
    s = faults.FaultSpec.parse("mid_round_exit:1:crash")
    assert (s.point, s.rank, s.action, s.nth) == ("mid_round_exit", 1,
                                                  "crash", 1)
    s = faults.FaultSpec.parse("round_send:0:delay_ms=250:7")
    assert s.action == "delay_ms" and s.arg == 250.0 and s.nth == 7
    s = faults.FaultSpec.parse("connect:2:hang")
    assert s.point == "connect" and s.action == "hang"


@pytest.mark.parametrize("bad", [
    "nope",                       # too few fields
    "badpoint:1:crash",           # unknown point
    "round_send:1:explode",       # unknown action
    "round_send:1:crash:-1",      # nth < 0 (0 = persistent, ISSUE 14)
    "round_send:1:crash:2:extra", # too many fields
])
def test_fault_spec_parse_rejects(bad):
    with pytest.raises(ValueError):
        faults.FaultSpec.parse(bad)


def test_fault_spec_nth_zero_is_persistent():
    """nth=0 (ISSUE 14): the fault fires on EVERY arrival — how a
    persistently failing disk is modeled — and still reports fired()."""
    s = faults.FaultSpec.parse("round_send:1:delay_ms=0:0")
    assert s.nth == 0
    faults.arm(s)
    try:
        for _ in range(3):
            faults.fire("round_send", 1)
        assert faults.fired()
        # Each arrival executes: the counter keeps advancing and a later
        # arrival still runs the action (probed via io_error raising).
        faults.arm("round_send:1:io_error:0")
        for _ in range(3):
            with pytest.raises(OSError):
                faults.fire("round_send", 1)
    finally:
        faults.disarm()


def test_fault_spec_serving_verbs_expand_to_serve_forward():
    """ISSUE 20: the serving chaos sugar — replica_crash / forward_fault /
    slow_replica — normalizes onto the serve_forward point with the right
    action, nth gate and persistence."""
    s = faults.FaultSpec.parse("replica_crash:1@3")
    assert (s.point, s.rank, s.action, s.nth) == ("serve_forward", 1,
                                                  "crash", 3)
    # ':' works as the separator too, and nth defaults to 1.
    assert faults.FaultSpec.parse("replica_crash:0:2").nth == 2
    assert faults.FaultSpec.parse("replica_crash:0").nth == 1
    s = faults.FaultSpec.parse("forward_fault:0:2")
    assert (s.point, s.action, s.nth) == ("serve_forward", "io_error", 2)
    # slow_replica is PERSISTENT (every batch) — the hedging target.
    s = faults.FaultSpec.parse("slow_replica:1:250")
    assert (s.point, s.action, s.arg, s.nth) == ("serve_forward",
                                                 "delay_ms", 250.0, 0)


@pytest.mark.parametrize("bad", [
    "replica_crash",              # no rank
    "replica_crash:x@1",          # non-integer rank
    "replica_crash:-1@1",         # negative rank
    "replica_crash:1@-2",         # negative nth
    "forward_fault:1:2:3",        # too many fields
    "slow_replica:1",             # missing delay
    "slow_replica:1:abc",         # non-numeric delay
    "slow_replica:1:-5",          # negative delay
])
def test_fault_spec_serving_verbs_reject(bad):
    with pytest.raises(ValueError):
        faults.FaultSpec.parse(bad)


def test_serving_fault_fires_like_base_grammar():
    """The sugar arms the same machinery: forward_fault raises the
    injected OSError into the serve_forward arrival, nth-gated."""
    faults.arm("forward_fault:0:2")
    try:
        faults.fire("serve_forward", 0)           # arrival 1: pass
        assert not faults.fired()
        with pytest.raises(OSError, match="injected I/O fault"):
            faults.fire("serve_forward", 0)       # arrival 2: fires
        assert faults.fired()
        faults.fire("serve_forward", 0)           # one-shot: pass again
    finally:
        faults.disarm()


def test_fire_is_noop_when_unarmed_and_rank_gated():
    assert not faults.armed()
    faults.fire("round_send", 0)          # no spec: must be a no-op
    faults.arm("round_send:1:delay_ms=1")
    faults.fire("round_send", 0)          # wrong rank
    faults.fire("pre_announce", 1)        # wrong point
    assert not faults.fired()
    faults.fire("round_send", 1)
    assert faults.fired()


def test_fire_nth_semantics_one_shot():
    fired_at = []
    faults.arm("round_recv:0:delay_ms=1:3")
    for i in range(5):
        faults.fire("round_recv", 0)
        if faults.fired() and not fired_at:
            fired_at.append(i)
    assert fired_at == [2]                # 3rd arrival, zero-indexed 2


def test_fire_econnreset_calls_sever():
    severed = []
    faults.arm("round_send:0:econnreset")
    faults.fire("round_send", 0, sever=lambda: severed.append(1))
    assert severed == [1]
    # One-shot: a later arrival does not sever again.
    faults.fire("round_send", 0, sever=lambda: severed.append(2))
    assert severed == [1]


# ------------------------------------- v4 liveness through the real server
def test_dead_peer_socket_gets_typed_abort():
    """Rank 1's connection dies mid-run (econnreset fault); rank 0 raises
    HVD303 PeerFailureError naming rank 1 — instead of the pre-v4 forever-
    blocked recv."""
    faults.arm("round_send:1:econnreset:3")
    port = _free_port()
    res = {}

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            try:
                for _ in range(10):
                    ctl.negotiate([])
                res[rank] = "no error"
            except PeerFailureError as exc:
                res[rank] = ("peer_failure", exc.dead_ranks,
                             "HVD303" in str(exc))
            except HorovodInternalError:
                res[rank] = ("internal",)   # the severed rank's own view
        finally:
            if rank == 0:
                deadline = time.time() + 20
                while len(res) < 2 and time.time() < deadline:
                    time.sleep(0.01)
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(20)
    assert res[0] == ("peer_failure", [1], True), res
    assert res[1][0] in ("internal", "peer_failure"), res


def test_dead_peer_in_round_one_still_gets_typed_abort():
    """Failure-at-startup attribution: rank 1 dies before sending its very
    FIRST frame — the server hasn't processed anyone's FLT1 capability ad
    yet (ads ride the round-1 frames, processed only after a full gather),
    so it must latch the ads from the already-gathered frames before
    broadcasting, or every survivor would get the untyped legacy rc=-1
    instead of HVD303 with the dead-rank list.  Rank 0's first frame is
    deliberately DELAYED past rank 1's death: the server's bounded grace
    drain must hold the abort until the survivor's ad is in hand (an
    immediate broadcast would find no FLT1 to deliver it to)."""
    faults.arm("round_send:1:econnreset:1")
    port = _free_port()
    res = {}

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            try:
                if rank == 0:
                    time.sleep(0.5)   # rank 1 is long dead by now
                for _ in range(10):
                    ctl.negotiate([])
                res[rank] = "no error"
            except PeerFailureError as exc:
                res[rank] = ("peer_failure", exc.dead_ranks,
                             "HVD303" in str(exc))
            except HorovodInternalError:
                res[rank] = ("internal",)   # the severed rank's own view
        finally:
            if rank == 0:
                deadline = time.time() + 20
                while len(res) < 2 and time.time() < deadline:
                    time.sleep(0.01)
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(20)
    assert res[0] == ("peer_failure", [1], True), res
    assert res[1][0] in ("internal", "peer_failure"), res


def test_round_deadline_declares_silent_rank_dead():
    """Rank 1 stops negotiating (socket open, process 'hung'): the server's
    per-round deadline — armed at rank 0's frame — declares it dead and
    rank 0 gets the abort within ~the deadline, not never."""
    port = _free_port()
    res = {}
    release = threading.Event()

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0, round_timeout_s=1.0)
        try:
            if rank == 1:
                ctl.negotiate([])
                ctl.negotiate([])
                release.wait(20)          # silent: no further rounds
                res[1] = "done"
            else:
                t0 = time.monotonic()
                try:
                    for _ in range(10):
                        ctl.negotiate([])
                    res[0] = "no error"
                except PeerFailureError as exc:
                    res[0] = ("deadline", exc.dead_ranks,
                              "deadline" in str(exc),
                              time.monotonic() - t0)
        finally:
            if rank == 0:
                deadline = time.time() + 25
                while 0 not in res and time.time() < deadline:
                    time.sleep(0.01)
            release.set()
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(25)
    kind, dead, named, dt = res[0]
    assert kind == "deadline" and dead == [1] and named, res
    assert dt < 6.0, f"abort took {dt}s against a 1s deadline"


def test_client_round_timeout_against_wedged_server():
    """The coordinator accepts frames but never answers: the client's own
    wall-clock deadline (2x HOROVOD_ROUND_TIMEOUT_S) fires as a typed
    RoundTimeoutError instead of blocking forever."""
    port = _free_port()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", port))
    lsock.listen(2)

    def mute_server():
        try:
            conn, _ = lsock.accept()
            conn.recv(4)                      # rank handshake
            while True:
                hdr = conn.recv(4)
                if not hdr:
                    return
                (n,) = struct.unpack("<I", hdr)
                got = b""
                while len(got) < n:
                    chunk = conn.recv(n - len(got))
                    if not chunk:
                        return
                    got += chunk
                # swallow the frame; never respond
        except OSError:
            pass

    t = threading.Thread(target=mute_server, daemon=True)
    t.start()
    ctl = TCPController("127.0.0.1", port, rank=1, world=2,
                        stall_warn_s=60.0, round_timeout_s=0.5)
    try:
        t0 = time.monotonic()
        with pytest.raises(RoundTimeoutError) as ei:
            ctl.negotiate([])
        dt = time.monotonic() - t0
        assert 0.8 < dt < 6.0, dt
        assert "HVD303" in str(ei.value)
        assert ei.value.timeout_s == pytest.approx(1.0)
    finally:
        ctl.shutdown()
        lsock.close()


def test_client_round_timeout_against_mid_frame_wedged_server():
    """The coordinator wedges MID-frame (length prefix written, payload
    never arrives): the client deadline must bound the whole frame read,
    not just its first byte — otherwise poll() sees POLLIN and the recv
    blocks forever, the exact pre-v4 wedge the timeout documents away."""
    port = _free_port()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", port))
    lsock.listen(2)
    hold = []

    def prefix_only_server():
        try:
            conn, _ = lsock.accept()
            hold.append(conn)                 # keep the socket open
            conn.recv(4)                      # rank handshake
            hdr = conn.recv(4)
            if not hdr:
                return
            (n,) = struct.unpack("<I", hdr)
            got = b""
            while len(got) < n:
                chunk = conn.recv(n - len(got))
                if not chunk:
                    return
                got += chunk
            conn.sendall(struct.pack("<I", 100))  # prefix, then silence
        except OSError:
            pass

    t = threading.Thread(target=prefix_only_server, daemon=True)
    t.start()
    ctl = TCPController("127.0.0.1", port, rank=1, world=2,
                        stall_warn_s=60.0, round_timeout_s=0.5)
    try:
        t0 = time.monotonic()
        with pytest.raises(RoundTimeoutError) as ei:
            ctl.negotiate([])
        dt = time.monotonic() - t0
        assert 0.8 < dt < 6.0, dt
        assert "HVD303" in str(ei.value)
    finally:
        ctl.shutdown()
        lsock.close()


def test_coordinator_death_mid_round_raises_typed_unattributed():
    """The COORDINATOR itself dies mid-round (socket closed, no abort
    verdict ever sent): the client must still raise a typed
    PeerFailureError — empty dead_ranks, since nothing attributed the
    death — so the engine runs its clean abort instead of wedging the
    InflightRing behind a plain HorovodInternalError."""
    port = _free_port()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", port))
    lsock.listen(2)

    def vanishing_server():
        try:
            conn, _ = lsock.accept()
            conn.recv(4)                      # rank handshake
            hdr = conn.recv(4)                # round-1 frame prefix
            if hdr:
                (n,) = struct.unpack("<I", hdr)
                got = b""
                while len(got) < n:
                    chunk = conn.recv(n - len(got))
                    if not chunk:
                        break
                    got += chunk
            conn.close()                      # die without answering
        except OSError:
            pass

    t = threading.Thread(target=vanishing_server, daemon=True)
    t.start()
    ctl = TCPController("127.0.0.1", port, rank=1, world=2,
                        stall_warn_s=60.0, round_timeout_s=2.0)
    try:
        with pytest.raises(PeerFailureError) as ei:
            ctl.negotiate([])
        assert ei.value.dead_ranks == []
        assert "HVD303" in str(ei.value)
    finally:
        ctl.shutdown()
        lsock.close()


def test_round_deadline_covers_mid_frame_wedge():
    """Rank 1 wedges mid-frame-write (length prefix sent, payload never
    comes): poll() reports it readable, so the gather's frame read itself
    must be deadline-bounded — rank 0 still gets the typed ABORT naming
    rank 1 instead of the whole control plane blocking in read_frame."""
    port = _free_port()
    res = {}

    def wedged_rank1():
        # Raw client: handshake, then only the length prefix of its
        # round-1 frame.  The socket stays open ('hung', not crashed).
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=2)
                break
            except OSError:
                time.sleep(0.05)
        else:
            return
        try:
            s.sendall(struct.pack("<I", 1))       # rank id
            s.sendall(struct.pack("<I", 64))      # frame prefix, no payload
            while 0 not in res and time.time() < deadline:
                time.sleep(0.01)
        finally:
            s.close()

    t1 = threading.Thread(target=wedged_rank1, daemon=True)
    t1.start()
    ctl = TCPController("127.0.0.1", port, rank=0, world=2,
                        stall_warn_s=60.0, round_timeout_s=1.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(PeerFailureError) as ei:
            for _ in range(10):
                ctl.negotiate([])
        dt = time.monotonic() - t0
        res[0] = "aborted"
        assert ei.value.dead_ranks == [1]
        assert "deadline" in str(ei.value)
        assert dt < 6.0, f"abort took {dt}s against a 1s deadline"
    finally:
        res.setdefault(0, "failed")
        ctl.shutdown()
        t1.join(25)


def test_connect_retries_cover_late_server_start():
    """Workers may start before the coordinator: the bounded-retry connect
    keeps attempting (with backoff) until the server appears."""
    port = _free_port()
    res = {}

    def late_rank0():
        time.sleep(1.0)
        ctl = TCPController("127.0.0.1", port, rank=0, world=2,
                            stall_warn_s=60.0)
        try:
            ctl.negotiate([])
            res[0] = "ok"
            deadline = time.time() + 20
            while 1 not in res and time.time() < deadline:
                time.sleep(0.01)
        finally:
            ctl.shutdown()

    t0 = threading.Thread(target=late_rank0, daemon=True)
    t0.start()
    # Short per-attempt budget forces actual retries before rank 0's
    # server exists.
    ctl = TCPController("127.0.0.1", port, rank=1, world=2,
                        stall_warn_s=60.0, connect_timeout_ms=8000,
                        connect_retries=6, connect_backoff_ms=50.0)
    try:
        ctl.negotiate([])
        res[1] = "ok"
    finally:
        ctl.shutdown()
    t0.join(25)
    assert res == {0: "ok", 1: "ok"}


def test_connect_exhaustion_raises_runtime_error():
    port = _free_port()   # nothing listening, ever
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="failed to connect"):
        TCPController("127.0.0.1", port, rank=1, world=2,
                      stall_warn_s=60.0, connect_timeout_ms=2000,
                      connect_retries=1, connect_backoff_ms=10.0)
    assert time.monotonic() - t0 < 30


def test_hierarchical_silent_host_hits_round_deadline():
    """Protocol v5 fault path: a whole host goes silent (its ranks stop
    negotiating, sockets open) behind its agent — the root's per-round
    deadline, armed by the healthy host's uplink, declares the silent
    host's ranks dead and the survivors get the typed ABORT through their
    own agent.  Attribution is host-granular by design: the agent is the
    ranks' only path, so the verdict names all of them."""
    from test_host_agent import HostAgent, _free_port as _hier_port

    port = _hier_port()
    agents = [HostAgent(0, "127.0.0.1", port, [0], host_index=0,
                        connect_timeout_ms=20000).start(),
              HostAgent(0, "127.0.0.1", port, [1], host_index=1,
                        connect_timeout_ms=20000).start()]
    res = {}
    release = threading.Event()

    def worker(rank):
        ctl = TCPController("127.0.0.1", agents[rank].port, rank=rank,
                            world=2, stall_warn_s=60.0, round_timeout_s=1.0,
                            server_port=port if rank == 0 else None)
        try:
            if rank == 1:
                ctl.negotiate([])
                ctl.negotiate([])
                release.wait(20)          # silent: no further rounds
                res[1] = "done"
            else:
                t0 = time.monotonic()
                try:
                    for _ in range(10):
                        ctl.negotiate([])
                    res[0] = "no error"
                except PeerFailureError as exc:
                    res[0] = ("deadline", exc.dead_ranks,
                              "deadline" in str(exc),
                              time.monotonic() - t0)
        finally:
            if rank == 0:
                deadline = time.time() + 25
                while 0 not in res and time.time() < deadline:
                    time.sleep(0.01)
            release.set()
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(25)
    for a in agents:
        a.stop()
    kind, dead, named, dt = res[0]
    assert kind == "deadline" and dead == [1] and named, res
    assert dt < 8.0, f"abort took {dt}s against a 1s deadline"


# ------------------------------------------------------ join_wait contract
def test_join_wait_raises_typed_timeout():
    """join_wait either returns the last joining rank or raises
    JoinTimeoutError — never a sentinel (satellite contract)."""
    port = _free_port()
    res = {}
    release = threading.Event()

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            if rank == 0:
                ctl.request_join()
                ctl.negotiate([])             # join announced; peer has not
                with pytest.raises(JoinTimeoutError):
                    ctl.join_wait(timeout=0.2)
                res[0] = "typed"
                release.set()
            else:
                ctl.negotiate([])             # participates but never joins
                release.wait(20)
                res[1] = "done"
        finally:
            if rank == 0:
                deadline = time.time() + 20
                while 1 not in res and time.time() < deadline:
                    time.sleep(0.01)
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(20)
    assert res == {0: "typed", 1: "done"}


def test_fail_join_releases_blocked_join_waiter():
    """Part of the no-waiter-may-hang invariant: ``hvd.join()``'s default
    is ``timeout=None``, and the all-joined verdict can never arrive from
    a dead control plane — ``fail_join`` must release the blocked waiter
    with the typed fault, and stay sticky for every later ``join_wait``
    (this controller generation is dead)."""
    port = _free_port()
    res = {}
    release = threading.Event()
    fault = PeerFailureError("HVD303 join test", dead_ranks=[1])

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0)
        try:
            if rank == 0:
                ctl.request_join()
                ctl.negotiate([])        # join announced; peer never joins
                got = {}

                def waiter():
                    try:
                        ctl.join_wait(None)   # hvd.join() default: forever
                    except PeerFailureError as exc:
                        got["exc"] = exc

                t = threading.Thread(target=waiter, daemon=True)
                t.start()
                time.sleep(0.2)          # waiter is parked on _join_event
                ctl.fail_join(fault)
                t.join(10)
                assert not t.is_alive(), "join waiter still blocked"
                assert got.get("exc") is fault
                with pytest.raises(PeerFailureError):   # sticky
                    ctl.join_wait(timeout=1)
                res[0] = "typed"
                release.set()
            else:
                ctl.negotiate([])        # participates but never joins
                release.wait(20)
                res[1] = "done"
        finally:
            if rank == 0:
                deadline = time.time() + 20
                while 1 not in res and time.time() < deadline:
                    time.sleep(0.01)
            ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(20)
    assert res == {0: "typed", 1: "done"}


# -------------------------------------------- engine-side abort invariants
def test_inflight_ring_abort_settles_without_device_wait():
    """InflightRing.abort fails every queued batch with the fault WITHOUT
    calling the waiter — including the batch the watcher is currently
    blocked on.  On a real TPU a collective whose participant died can
    block ``jax.block_until_ready`` forever, so the abort must settle the
    whole window from the aborting thread; waiting for the wedged waiter
    to return (it may never) would hang every waiter on the head batch."""
    from horovod_tpu.ops.scheduler import InflightRing
    settled = []
    waited = []
    gate = threading.Event()

    def waiter(results):
        waited.append(results)
        gate.wait(10)     # simulates a device wait that never completes

    ring = InflightRing(waiter, lambda b, r, e: settled.append((b, e)),
                        depth=4)
    try:
        ring.submit(["b0"], "r0")
        time.sleep(0.1)                       # watcher picks up b0
        ring.submit(["b1"], "r1")
        ring.submit(["b2"], "r2")
        fault = PeerFailureError("dead", dead_ranks=[1])
        ring.abort(fault)
        # NOTE: the gate stays CLOSED — the watcher is still wedged in
        # b0's device wait, yet every batch (b0 included) must settle.
        deadline = time.time() + 5
        while len(settled) < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert len(settled) == 3, settled
        assert waited == ["r0"]               # b1/b2's waiter never ran
        errs = {b[0]: e for (b, e) in settled}
        assert errs["b0"] is fault
        assert errs["b1"] is fault and errs["b2"] is fault
        # A submit racing (or following) the abort settles immediately
        # with the fault instead of queueing into the dead window.
        ring.submit(["b3"], "r3")
        assert settled[-1] == (["b3"], fault)
    finally:
        gate.set()
        ring.stop()


def test_inflight_ring_abort_skips_already_settled_batch():
    """A batch the watcher already settled SUCCESSFULLY must not be
    re-settled with the fault by a racing ``abort()``: the per-batch
    settle claim makes exactly one thread run the settler, so a completed
    collective cannot retroactively report PeerFailureError (a spurious
    failure — and under elastic, an unnecessary rollback).  The window is
    [claimed, settler running, not yet popped]: the batch is still in
    ``_items`` when the abort snapshots the window."""
    from horovod_tpu.ops.scheduler import InflightRing
    settled = []
    in_settler = threading.Event()
    release = threading.Event()

    def settler(batch, results, error):
        settled.append((batch[0], error))
        if batch[0] == "b0" and error is None:
            in_settler.set()
            release.wait(10)   # hold b0 mid-settle, still in _items

    ring = InflightRing(lambda r: None, settler, depth=4)
    try:
        ring.submit(["b0"], "r0")
        assert in_settler.wait(5)    # watcher claimed b0, settling success
        ring.submit(["b1"], "r1")    # unclaimed: the abort must fail THIS
        fault = PeerFailureError("dead", dead_ranks=[1])
        ring.abort(fault)            # races b0's in-flight success settle
        release.set()
        deadline = time.time() + 5
        while len(settled) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert dict(settled) == {"b0": None, "b1": fault}, settled
        assert [b for b, _ in settled].count("b0") == 1   # exactly once
    finally:
        release.set()
        ring.stop()


def test_engine_abort_fails_join_waiters():
    """``_abort_engine`` extends the no-waiter-may-hang invariant to join
    waiters: it must hand the fault to ``controller.fail_join`` (the
    single-controller engine has no TCP controller — ``None`` — so a stub
    stands in for the multi-process wiring)."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    hvd.init()
    eng = basics._get_state().engine
    fault = PeerFailureError("HVD303 join wiring", dead_ranks=[1])
    failed = []

    class _StubCtl:
        def fail_join(self, exc):
            failed.append(exc)

    assert eng.controller is None     # single-controller mode
    eng.stop()
    eng.controller = _StubCtl()
    try:
        eng._abort_engine(fault)
        assert failed == [fault]
    finally:
        # Un-down the shared engine for the rest of the suite.
        eng.controller = None
        eng._fault = None
        eng._shutdown.clear()
        eng.start()


def test_engine_abort_settles_queue_and_rejects_new_work():
    """A ControlPlaneError from negotiation cleanly downs the engine:
    queued waiters settle with the error, later enqueues raise it
    immediately (no hang, no wedge)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.ops.engine import CollectiveType

    hvd.init()
    eng = basics._get_state().engine
    fault = PeerFailureError("HVD303 test fault", dead_ranks=[1])
    assert eng.fault is None
    # Park the cycle thread so the queued entry cannot complete before the
    # abort lands (single-controller cycles settle within microseconds).
    eng.stop()
    try:
        h = eng.enqueue("fault.test.pending", CollectiveType.ALLREDUCE,
                        hvd.stack_per_rank(
                            [np.ones(2, np.float32)] * hvd.size()))
        eng._abort_engine(fault)
        with pytest.raises(PeerFailureError):
            eng.synchronize(h, timeout=5)
        with pytest.raises(PeerFailureError):
            eng.enqueue("fault.test.after", CollectiveType.ALLREDUCE,
                        hvd.stack_per_rank(
                            [np.ones(2, np.float32)] * hvd.size()))
    finally:
        # Un-down the shared engine for the rest of the suite.
        eng._fault = None
        eng._shutdown.clear()
        eng.start()


def test_cycle_fault_sets_engine_fault_before_releasing_waiters():
    """Ordering invariant: when a cycle fails with a ControlPlaneError,
    ``engine.fault`` must be set BEFORE any of that cycle's waiters are
    released — a waiter that wakes first reads ``engine.fault`` in
    ``basics.shutdown()`` to pick the abrupt teardown, and a still-None
    fault would route a poisoned jax world through the graceful shutdown
    barrier it can never complete."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.ops.engine import CollectiveType

    hvd.init()
    eng = basics._get_state().engine
    fault = PeerFailureError("HVD303 ordering", dead_ranks=[1])
    eng.stop()
    h = eng.enqueue("fault.order.entry", CollectiveType.ALLREDUCE,
                    hvd.stack_per_rank(
                        [np.ones(2, np.float32)] * hvd.size()))
    with eng._handles_lock:
        e = eng._handles[h]
    seen = []
    orig_set = e.done.set

    def probing_set():
        seen.append(eng.fault)     # what a waking waiter would observe
        orig_set()

    e.done.set = probing_set

    def failing_compute(entries):
        raise fault

    orig_compute = eng._compute_response_list
    eng._compute_response_list = failing_compute
    try:
        eng.run_loop_once()
        assert seen and all(f is fault for f in seen), seen
        with pytest.raises(PeerFailureError):
            eng.synchronize(h, timeout=5)
    finally:
        eng._compute_response_list = orig_compute
        eng._fault = None
        eng._shutdown.clear()
        eng.start()


def test_enqueue_fault_race_settles_exactly_once():
    """The enqueue-vs-abort race path must settle via drain-as-claim: when
    the fault (and the abort's own queue sweep) lands between the guard
    and the push, the post-push re-check may only settle entries it drains
    back out itself — an entry the abort already swept must NOT be settled
    a second time (a double settle garbles the timeline's QUEUE pairing)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.ops.engine import CollectiveType

    hvd.init()
    eng = basics._get_state().engine
    fault = PeerFailureError("HVD303 race", dead_ranks=[1])
    eng.stop()
    settles = []
    orig_settle = eng._settle_queued

    def counting_settle(entries, exc):
        settles.append([e.name for e in entries])
        orig_settle(entries, exc)

    orig_push = eng.queue.push_many

    def racing_push(entries):
        orig_push(entries)
        # The abort lands NOW — fault set + abort's queue sweep both run
        # between this thread's push and its post-push re-check.
        eng._fault = fault
        counting_settle(eng.queue.drain(), fault)

    eng._settle_queued = counting_settle
    eng.queue.push_many = racing_push
    try:
        h = eng.enqueue("fault.race.once", CollectiveType.ALLREDUCE,
                        hvd.stack_per_rank(
                            [np.ones(2, np.float32)] * hvd.size()))
        with pytest.raises(PeerFailureError):
            eng.synchronize(h, timeout=5)
        flat = [n for batch in settles for n in batch]
        assert flat.count("fault.race.once") == 1, settles
    finally:
        eng.queue.push_many = orig_push
        eng._settle_queued = orig_settle
        eng._fault = None
        eng._shutdown.clear()
        eng.start()


# ----------------------------------------------- monitor HVD303 enrichment
def test_monitor_health_reports_peer_dead():
    from horovod_tpu.monitor import MonitorAgent
    agent = MonitorAgent(rank=0, world=2, interval_s=0.2)
    agent.aggregator.update(1, {"rank": 1, "ledger": ["allreduce 'g' @x:1"]})
    h = agent.health()
    assert h["status"] in ("ok", "degraded")
    agent.on_peer_failure([1], "rank(s) [1] lost connection")
    h = agent.health()
    assert h["status"] == "peer_dead"
    assert h["peer_dead"] == [1]
    assert "lost connection" in h["peer_dead_reason"]
    agent.close()


def test_monitor_peer_failure_context_quotes_dead_rank():
    from horovod_tpu.monitor import MonitorAgent
    agent = MonitorAgent(rank=0, world=3, interval_s=0.2)
    agent.aggregator.update(1, {"rank": 1,
                                "ledger": ["allreduce 'grad.7' @t.py:12"]})
    ctx = agent.peer_failure_context([1, 2])
    assert "rank 1: last snapshot" in ctx
    assert "grad.7" in ctx
    assert "rank 2: no snapshot ever received" in ctx
    # Unattributed (round timeout): every known rank's age is listed.
    ctx_all = agent.peer_failure_context(None)
    assert "rank 1" in ctx_all
    agent.close()


def test_controller_enricher_is_guarded():
    """A raising enricher must never mask the HVD303 failure itself."""
    ctl = TCPController.__new__(TCPController)
    ctl.fault_enricher = None
    assert ctl._enrich([1]) == ""

    def boom(ranks):
        raise RuntimeError("telemetry bug")

    ctl.fault_enricher = boom
    assert ctl._enrich([1]) == ""
    with pytest.raises(PeerFailureError) as ei:
        ctl._raise_peer_failure([2, 0], "it died")
    assert ei.value.dead_ranks == [0, 2]
    assert "it died" in str(ei.value)


# --------------------------------------------------------- abort frame fmt
def test_parse_abort_roundtrip_and_rejects_normal_frames():
    reason = "rank(s) [1] lost connection mid-negotiation"
    frame = struct.pack("<III", 0xFFFFFFFF, 0x34544241, 2)
    frame += struct.pack("<II", 1, 3)
    frame += struct.pack("<H", len(reason)) + reason.encode()
    got = TCPController._parse_abort(frame)
    assert got == ([1, 3], reason)
    # A normal response (n_ready=0...) must never parse as an abort.
    assert TCPController._parse_abort(struct.pack("<III", 0, 0, 0)) is None
    assert TCPController._parse_abort(b"") is None
