"""Latency fast lane + ByteScheduler partitioning, hermetic tier (ISSUE 8).

The lane fork and the tensor split must be bitwise-invisible: the same
input through fast-lane-on vs off (and partition-on vs off) produces
byte-identical results, with and without bf16 wire compression.  The
persistent-program pin must engage (and self-invalidate on any parameter
drift), partitioned sub-tensors must never re-fuse past the split, and
the trace phase attribution must show copy_in collapsing on the fast
lane.  Runs on the 8-virtual-device CPU mesh (single-controller mode —
the slot-keyed pin + frame guards are covered by
tests/data/worker_fastlane.py and test_response_cache.py)."""

import numpy as np
import pytest


def _engine(hvd):
    from horovod_tpu.common import basics
    return basics._get_state().engine


@pytest.fixture()
def lane_knobs(hvd):
    """Save/restore the latency-war knobs around a test."""
    eng = _engine(hvd)
    saved = (eng.fast_lane_threshold, eng.partition_threshold)
    yield eng
    eng.fast_lane_threshold, eng.partition_threshold = saved


def _stacked(world, shape, seed, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return np.stack([rng.randn(*shape).astype(dtype) * (r + 1)
                     for r in range(world)])


# ---------------------------------------------------------------- fast lane
def test_fast_lane_bitwise_matches_fused_path(hvd, world_size, lane_knobs):
    """Same inputs, both lanes, fp32 and bf16 wire compression: bitwise
    equal — the fast lane skips the fusion buffer, never the math."""
    eng = lane_knobs
    xs = [_stacked(world_size, (999,), 0), _stacked(world_size, (17, 5), 1)]
    for comp in (None, "bf16"):
        eng.fast_lane_threshold = 0
        base = [np.asarray(hvd.allreduce(
            x.copy(), name=f"fl_base_{comp}_{i}", op=hvd.Sum,
            compression=comp)) for i, x in enumerate(xs)]
        eng.fast_lane_threshold = 1 << 20
        out = [np.asarray(hvd.allreduce(
            x.copy(), name=f"fl_on_{comp}_{i}", op=hvd.Sum,
            compression=comp)) for i, x in enumerate(xs)]
        for b, o in zip(base, out):
            np.testing.assert_array_equal(b, o)
    assert eng.fast_lane_dispatches >= 4


def test_fast_lane_pin_engages_and_survives_resubmission(hvd, world_size,
                                                         lane_knobs):
    """First submission builds + pins; the steady-state resubmission under
    the same name is served by the pinned program (zero key construction,
    zero program-cache lookup)."""
    eng = lane_knobs
    eng.fast_lane_threshold = 1 << 20
    x = _stacked(world_size, (501,), 2)
    hvd.allreduce(x.copy(), name="fl_pin", op=hvd.Sum)
    hits0, misses0 = eng.fast_lane_hits, eng.cache.misses
    out = np.asarray(hvd.allreduce(x.copy(), name="fl_pin", op=hvd.Sum))
    assert eng.fast_lane_hits == hits0 + 1
    assert eng.cache.misses == misses0, "pin hit still touched the cache"
    np.testing.assert_array_equal(
        out, np.asarray(hvd.allreduce(x.copy(), name="fl_pin_ref",
                                      op=hvd.Sum)))


def test_fast_lane_pin_invalidates_on_shape_change(hvd, world_size,
                                                   lane_knobs):
    """Name reuse under a new shape must drop the stale pin and rebuild —
    never dispatch the old program."""
    eng = lane_knobs
    eng.fast_lane_threshold = 1 << 20
    hvd.allreduce(_stacked(world_size, (64,), 3), name="fl_reshape",
                  op=hvd.Sum)
    hvd.allreduce(_stacked(world_size, (64,), 3), name="fl_reshape",
                  op=hvd.Sum)                       # pin warm
    hits0 = eng.fast_lane_hits
    x = _stacked(world_size, (128,), 4)
    out = np.asarray(hvd.allreduce(x.copy(), name="fl_reshape", op=hvd.Sum))
    assert out.shape == (128,)
    assert eng.fast_lane_hits == hits0, "stale pin served a new shape"
    # ...and the new shape re-pins.
    hvd.allreduce(x.copy(), name="fl_reshape", op=hvd.Sum)
    assert eng.fast_lane_hits == hits0 + 1


def test_fast_lane_skips_groups_and_big_tensors(hvd, world_size, lane_knobs):
    """Grouped members stay fused (atomicity) and super-threshold tensors
    stay on the fusion path."""
    eng = lane_knobs
    eng.fast_lane_threshold = 256
    d0 = eng.fast_lane_dispatches
    hvd.grouped_allreduce([_stacked(world_size, (4,), 5),
                           _stacked(world_size, (5,), 6)],
                          name="fl_group", op=hvd.Sum)
    hvd.allreduce(_stacked(world_size, (10000,), 7), name="fl_big",
                  op=hvd.Sum)
    assert eng.fast_lane_dispatches == d0


def test_fast_lane_trace_copy_in_collapses(hvd, world_size, lane_knobs):
    """Phase attribution on the fast lane: the pinned program is fetched
    O(1) and t_launch stamps BEFORE the invoke, so copy_in (ready→launch)
    collapses and the device wait lands in reduce — the acceptance
    criterion's `copy_in+drain ≈ 0 on the fast lane`."""
    from horovod_tpu.trace import TraceRecorder

    eng = lane_knobs
    eng.fast_lane_threshold = 1 << 20
    x = _stacked(world_size, (2048,), 8)
    hvd.allreduce(x.copy(), name="fl_traced", op=hvd.Sum)   # build + pin
    saved_tracer = eng.tracer
    eng.tracer = TraceRecorder(capacity=256)
    try:
        for i in range(5):
            hvd.allreduce(x.copy() * (i + 1), name="fl_traced", op=hvd.Sum)
        summary = eng.tracer.phase_summary()
    finally:
        eng.tracer = saved_tracer
    ph = summary["phases_us"]
    assert summary["spans"] >= 5
    # The collective itself (reduce) dominates the program fetch (copy_in)
    # by construction on the pinned path; drain is the settle epilogue.
    assert ph["copy_in"] < ph["reduce"], ph


# --------------------------------------------------------------- partitioning
def test_partition_bitwise_matches_whole_tensor(hvd, world_size, lane_knobs):
    """Partition-on results are bitwise-identical to the unsplit path —
    fp32, bf16 wire compression, AVERAGE with scale factors."""
    eng = lane_knobs
    cases = [
        dict(op=hvd.Sum, compression=None),
        dict(op=hvd.Sum, compression="bf16"),
        dict(op=hvd.Average, prescale_factor=0.5, postscale_factor=3.0),
        dict(op=hvd.Min), dict(op=hvd.Max),
    ]
    x = _stacked(world_size, (100, 41), 9)   # 131KB global stacked
    for i, kw in enumerate(cases):
        eng.partition_threshold = 0
        base = np.asarray(hvd.allreduce(x.copy(), name=f"pt_base_{i}", **kw))
        eng.partition_threshold = 32768      # global bytes -> ~5 parts
        out = np.asarray(hvd.allreduce(x.copy(), name=f"pt_on_{i}", **kw))
        np.testing.assert_array_equal(base, out)
    assert eng.partition_splits >= len(cases)


def test_partition_count_in_fusion_key(hvd, world_size, lane_knobs):
    """The partition count rides the fusion key (like chunk counts): a
    sub-tensor's program can never cross-serve a same-shaped ordinary
    tensor, and parts of one parent never re-fuse into a whole-tensor
    batch."""
    from horovod_tpu.ops.engine import TensorTableEntry, CollectiveType, \
        _fusion_key

    class A:
        nbytes = 400
        shape = (2, 100)

    plain = TensorTableEntry(handle=1, name="t",
                             ctype=CollectiveType.ALLREDUCE, tensor=A())
    part = TensorTableEntry(handle=2, name="t::part0/4",
                            ctype=CollectiveType.ALLREDUCE, tensor=A())
    part.partition = ("t", 0, 4)
    sibling = TensorTableEntry(handle=3, name="t::part1/4",
                               ctype=CollectiveType.ALLREDUCE, tensor=A())
    sibling.partition = ("t", 1, 4)
    assert _fusion_key(plain) != _fusion_key(part)
    assert _fusion_key(part) == _fusion_key(sibling)   # one compiled program
    assert _fusion_key(part)[-1] == 4                  # the count, not bytes


def test_partition_threshold_counts_global_bytes(hvd, world_size,
                                                 lane_knobs):
    """The threshold counts GLOBAL stacked bytes (the fusion-threshold
    convention): a tensor whose global size exceeds it must split even
    when each rank's share alone would not — the eligibility gate and the
    plan may never disagree (a gate-pass that plans zero parts would make
    the knob silently inert for a whole size band)."""
    eng = lane_knobs
    x = _stacked(world_size, (1024,), 15)    # 4KB/rank, 32KB global
    eng.partition_threshold = 16384
    s0 = eng.partition_splits
    out = np.asarray(hvd.allreduce(x.copy(), name="pt_global", op=hvd.Sum))
    assert eng.partition_splits == s0 + 1, (
        "global-bytes-eligible tensor did not split")
    eng.partition_threshold = 0
    ref = np.asarray(hvd.allreduce(x.copy(), name="pt_global_ref",
                                   op=hvd.Sum))
    np.testing.assert_array_equal(ref, out)


def test_partition_poll_and_async_handles(hvd, world_size, lane_knobs):
    """Async submit of a partitioned tensor: poll converges, synchronize
    reassembles — callers cannot tell a split tensor from a whole one."""
    from horovod_tpu.ops import eager

    eng = lane_knobs
    eng.partition_threshold = 32768
    x = _stacked(world_size, (5000,), 10)    # 160KB global stacked
    h = eager.allreduce_async(x.copy(), name="pt_async", op=hvd.Sum)
    eng.kick()
    out = np.asarray(eager.synchronize(h))
    assert eager.poll(h)
    eng.partition_threshold = 0
    ref = np.asarray(hvd.allreduce(x.copy(), name="pt_async_ref",
                                   op=hvd.Sum))
    np.testing.assert_array_equal(ref, out)


def test_partition_skips_adasum_and_groups(hvd, world_size, lane_knobs):
    """ADASUM mixes dot products across the whole vector (splitting would
    change the math) and grouped members are atomic: neither splits."""
    eng = lane_knobs
    eng.partition_threshold = 256
    s0 = eng.partition_splits
    hvd.grouped_allreduce([_stacked(world_size, (500,), 11)],
                          name="pt_group", op=hvd.Sum)
    hvd.allreduce(_stacked(world_size, (500,), 12), name="pt_adasum",
                  op=hvd.Adasum)
    assert eng.partition_splits == s0


def test_partition_and_fast_lane_compose(hvd, world_size, lane_knobs):
    """Both knobs on: a huge tensor splits, a small one rides the fast
    lane, results all bitwise-correct in one submission burst."""
    from horovod_tpu.ops import eager

    eng = lane_knobs
    eng.partition_threshold = 0
    eng.fast_lane_threshold = 0
    big = _stacked(world_size, (4000,), 13)
    small = _stacked(world_size, (50,), 14)
    ref_big = np.asarray(hvd.allreduce(big.copy(), name="mix_rb",
                                       op=hvd.Sum))
    ref_small = np.asarray(hvd.allreduce(small.copy(), name="mix_rs",
                                         op=hvd.Sum))
    eng.partition_threshold = 16384
    eng.fast_lane_threshold = 4096
    h_big = eager.allreduce_async(big.copy(), name="mix_b", op=hvd.Sum)
    h_small = eager.allreduce_async(small.copy(), name="mix_s", op=hvd.Sum,
                                    priority=5)
    eng.kick()
    np.testing.assert_array_equal(ref_small,
                                  np.asarray(eager.synchronize(h_small)))
    np.testing.assert_array_equal(ref_big,
                                  np.asarray(eager.synchronize(h_big)))
