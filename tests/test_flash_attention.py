"""Pallas flash attention vs the jnp reference — values AND gradients, with
padding (T not a block multiple), causal and full (SURVEY.md §7 "pallas
kernels for the hot ops").  Runs in Pallas interpret mode on the CPU mesh;
the identical kernel compiles on TPU.
"""

import jax
import jax.export  # noqa: F401  (not auto-imported on jax<=0.4)
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel.ring_attention import local_flash_attention


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape,blocks", [
    ((2, 70, 3, 16), (32, 32)),   # padded: 70 % 32 != 0
    ((1, 64, 2, 32), (32, 32)),   # exact multiple
    ((2, 33, 1, 8), (16, 16)),    # tiny + padding
])
def test_flash_matches_reference(shape, blocks, causal):
    B, T, H, D = shape
    bq, bk = blocks
    rng = np.random.RandomState(hash((shape, causal)) % (2**31))
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    ref = local_flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=bq, block_k=bk) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(local_flash_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_repeated(causal):
    """Native GQA (kv heads shared via block index maps) == materialized
    jnp.repeat, for values and all three gradients (dk/dv accumulate over
    the q-head group)."""
    B, T, H, K, D = 2, 40, 4, 2, 16
    rep = H // K
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, rep, axis=2)
        vr = jnp.repeat(v, rep, axis=2)
        return jnp.sum(local_flash_attention(q, kr, vr, causal=causal) ** 2)

    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal,
                                   block_q=16, block_k=16)),
        np.asarray(local_flash_attention(
            q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
            causal=causal)),
        atol=3e-5, rtol=3e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_cross_attention_shapes():
    """Tq != Tk (cross attention / KV cache shapes)."""
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 17, 2, 16), jnp.float32)
    k = jnp.asarray(rng.randn(1, 50, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(1, 50, 2, 16), jnp.float32)
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=16)
    ref = local_flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("window,shape,blocks", [
    (8, (2, 70, 3, 16), (32, 32)),    # window smaller than a block
    (40, (1, 64, 2, 32), (16, 16)),   # window spans several blocks
    (4, (2, 33, 1, 8), (16, 16)),     # tiny + padding
])
def test_flash_sliding_window_matches_reference(window, shape, blocks):
    """Sliding-window (Mistral) flash == jnp reference with the same
    band mask — values and all three gradients, including the
    whole-block skip path (window < block)."""
    B, T, H, D = shape
    bq, bk = blocks
    rng = np.random.RandomState(hash((shape, window)) % (2**31))
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    out = flash_attention(q, k, v, causal=True, window=window,
                          block_q=bq, block_k=bk)
    ref = local_flash_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, window=window, block_q=bq, block_k=bk) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(local_flash_attention(
        q, k, v, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_sliding_window_gqa():
    """Windowed attention through the native-GQA kv index maps."""
    B, T, H, K, D = 2, 48, 4, 2, 16
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    out = flash_attention(q, k, v, causal=True, window=12,
                          block_q=16, block_k=16)
    ref = local_flash_attention(q, k, v, causal=True, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=12)


def test_flash_tpu_lowering():
    """Cross-platform lowering: the Mosaic/TPU pipeline runs client-side,
    so a CPU host can verify the kernels lower for TPU at real llama
    shapes — the guard that keeps the driver's on-TPU compile check safe."""
    def f(q, k, v):
        return jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, interpret=False).astype(jnp.float32)),
            argnums=(0, 1, 2))(q, k, v)

    spec_q = jax.ShapeDtypeStruct((1, 1024, 8, 128), jnp.bfloat16)
    spec_kv = jax.ShapeDtypeStruct((1, 1024, 4, 128), jnp.bfloat16)  # GQA
    exp = jax.export.export(jax.jit(f), platforms=["tpu"])(
        spec_q, spec_kv, spec_kv)
    assert len(exp.mlir_module_serialized) > 0


def test_prefill_tpu_lowering(monkeypatch):
    """The blockwise prefill lowers for TPU WITH the Pallas flash kernel
    in the module (≥1 tpu_custom_call per layer) — proof the serving
    prompt path rides the MXU kernel, not the jnp fallback, checked
    client-side without a chip."""
    from horovod_tpu.models import llama
    from horovod_tpu.ops import flash_attention as fa

    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    # Trace happens on a CPU host: force the kernel's compiled (Mosaic)
    # path rather than the interpret default so the export carries the
    # real tpu_custom_calls.
    monkeypatch.setattr(fa, "_interpret_default", lambda: False)
    cfg = llama.tiny(n_heads=8, n_kv_heads=4, d_model=256, d_ff=512,
                     vocab_size=512, max_seq=1024, n_layers=2,
                     dtype=jnp.bfloat16, dp_axis=None, tp_axis=None,
                     sp_axis=None, use_flash=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    cache = llama.init_cache(cfg, 1, 1024)
    toks = jax.ShapeDtypeStruct((1, 512), jnp.int32)

    def f(params, cache, toks):
        return llama.prefill(params, cache, toks, cfg)[0]

    exp = jax.export.export(jax.jit(f), platforms=["tpu"])(
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), cache),
        toks)
    mod = exp.mlir_module()
    assert mod.count("tpu_custom_call") >= cfg.n_layers, \
        mod.count("tpu_custom_call")


def test_ulysses_routes_through_flash(monkeypatch):
    """HVD_TPU_FLASH=1 makes Ulysses run the pallas kernel on its local
    heads INSIDE shard_map over the sp mesh — the real sp usage."""
    from horovod_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.parallel.ulysses import ulysses_attention

    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    # Spy: if routing regresses to the jnp fallback, fail loudly instead of
    # passing vacuously (flash and reference are numerically identical).
    # NB: horovod_tpu.parallel re-exports the ring_attention FUNCTION, which
    # shadows the submodule attribute — import the module explicitly.
    import importlib
    ra = importlib.import_module("horovod_tpu.parallel.ring_attention")

    def _boom(*a, **k):
        raise AssertionError("routing fell back to local_flash_attention "
                             "despite HVD_TPU_FLASH=1")
    monkeypatch.setattr(ra, "local_flash_attention", _boom)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 64, 8, 16), jnp.float32)
    k = jnp.asarray(rng.randn(1, 64, 8, 16), jnp.float32)
    v = jnp.asarray(rng.randn(1, 64, 8, 16), jnp.float32)
    ref = local_flash_attention(q, k, v, causal=True)

    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
    out = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                          causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=3e-5)


def test_bert_uses_flash_when_forced(monkeypatch):
    from horovod_tpu.models import bert

    cfg = bert.tiny(dtype=jnp.float32,
                    dp_axis=None, tp_axis=None, sp_axis=None)
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 24)),
                         jnp.int32)
    monkeypatch.setenv("HVD_TPU_FLASH", "0")
    ref = bert.forward(params, tokens, cfg)
    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    monkeypatch.setattr(
        bert, "local_flash_attention",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
            "bert fell back to local_flash_attention under "
            "HVD_TPU_FLASH=1")))
    out = bert.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_llama_uses_flash_when_forced(monkeypatch):
    """HVD_TPU_FLASH=1 routes llama attention through the pallas kernel;
    logits must match the jnp-reference path."""
    from horovod_tpu.models import llama

    cfg = llama.tiny(n_heads=4, n_kv_heads=2, d_model=64, d_ff=128,
                     vocab_size=128, dtype=jnp.float32,
                     dp_axis=None, tp_axis=None, sp_axis=None)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 40)),
                         jnp.int32)
    monkeypatch.setenv("HVD_TPU_FLASH", "0")
    ref = llama.forward(params, tokens, cfg)
    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    # Spy: the forced run must NOT touch the jnp fallback (otherwise this
    # test is vacuous — both paths produce identical numbers).
    monkeypatch.setattr(
        llama, "local_flash_attention",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
            "llama fell back to local_flash_attention under "
            "HVD_TPU_FLASH=1")))
    out = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_flash_auto_seq_threshold(monkeypatch):
    """Auto routing is sequence-aware (BENCH_SELF_r05: flash LOSES to
    XLA's fused attention below the crossover on real v5e — 330k vs 552k
    tok/s at T=512): on TPU, auto mode picks flash only at/above
    HVD_TPU_FLASH_MIN_SEQ; explicit forces ignore the threshold."""
    from horovod_tpu.ops import flash_attention as fa

    monkeypatch.delenv("HVD_TPU_FLASH", raising=False)
    monkeypatch.setenv("HVD_TPU_FLASH_MIN_SEQ", "1024")
    monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
    assert fa.flash_enabled(seq=512) is False
    assert fa.flash_enabled(seq=1024) is True
    assert fa.flash_enabled(seq=4096) is True
    assert fa.flash_enabled() is True          # unknown seq: legacy default
    assert fa.resolve_flash(None, seq=512) is False
    assert fa.resolve_flash(True, seq=512) is True    # config force wins
    assert fa.resolve_flash(False, seq=8192) is False

    # Causality-aware defaults (BENCH_SELF_r05 in-model A/B with the
    # raw-bf16 kernels): causal crossover 512, non-causal stays 1024.
    monkeypatch.delenv("HVD_TPU_FLASH_MIN_SEQ", raising=False)
    assert fa.flash_min_seq(causal=True) == 512
    assert fa.flash_min_seq(causal=False) == 1024
    assert fa.flash_enabled(seq=512, causal=True) is True
    assert fa.flash_enabled(seq=256, causal=True) is False
    assert fa.flash_enabled(seq=512, causal=False) is False
    assert fa.flash_enabled(seq=1024, causal=False) is True
    monkeypatch.setenv("HVD_TPU_FLASH_MIN_SEQ", "2048")  # overrides BOTH
    assert fa.flash_enabled(seq=1024, causal=True) is False
    assert fa.flash_enabled(seq=2048, causal=False) is True
    monkeypatch.setenv("HVD_TPU_FLASH_MIN_SEQ", "1024")

    monkeypatch.setenv("HVD_TPU_FLASH", "1")   # env force beats threshold
    assert fa.flash_enabled(seq=128) is True
    monkeypatch.setenv("HVD_TPU_FLASH", "0")
    assert fa.flash_enabled(seq=8192) is False

    # Off-TPU auto stays off at any length.
    monkeypatch.delenv("HVD_TPU_FLASH", raising=False)
    monkeypatch.setattr(fa.jax, "default_backend", lambda: "cpu")
    assert fa.flash_enabled(seq=8192) is False


def test_flash_block_env_defaults(monkeypatch):
    """HVD_TPU_FLASH_BLOCK_Q/K tune the kernel tiles without a code
    change (tools/flash_sweep.py feeds these); unset keeps the measured
    512x512 default (FLASH_SWEEP_r05: best or tied at every shape)."""
    from horovod_tpu.ops import flash_attention as fa
    monkeypatch.delenv("HVD_TPU_FLASH_BLOCK_Q", raising=False)
    monkeypatch.delenv("HVD_TPU_FLASH_BLOCK_K", raising=False)
    assert fa._block_defaults() == (512, 512)
    monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_K", "1024")
    assert fa._block_defaults() == (256, 1024)
    monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_Q", "junk")
    assert fa._block_defaults()[0] == 512


def test_flash_rejects_mixed_dtypes():
    """The kernels feed raw operands to the MXU, so mixed q/k/v dtypes
    must fail with the explicit entry-point error, not a cryptic
    dot_general trace error."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.bfloat16)
    with pytest.raises(ValueError, match="share one dtype"):
        flash_attention(q, k, v, causal=True, block_q=16, block_k=16)


def test_flash_bwd_casts_f32_cotangent():
    """An f32 cotangent over bf16 primals is legal in jax; the backward
    must cast it rather than die on the raw-dtype contract."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 32, 2, 8), jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 32, 2, 8), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 32, 2, 8), jnp.bfloat16)

    def loss(q, k, v):
        # .astype(f32) before the reduction makes the incoming cotangent
        # of the flash output an f32 array.
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=16,
                                       block_k=16).astype(jnp.float32) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert a.dtype == jnp.bfloat16
        assert np.all(np.isfinite(np.asarray(a, np.float32)))


def test_vit_uses_flash_when_forced(monkeypatch):
    """HVD_TPU_FLASH=1 routes ViT's (reused bert) attention through the
    pallas kernel; logits must match the jnp-reference path."""
    from horovod_tpu.models import vit, bert

    cfg = vit.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    images = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                         jnp.float32)
    monkeypatch.setenv("HVD_TPU_FLASH", "0")
    ref = vit.logits(params, images, cfg)
    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    monkeypatch.setattr(
        bert, "local_flash_attention",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
            "vit fell back to local_flash_attention under "
            "HVD_TPU_FLASH=1")))
    out = vit.logits(params, images, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_gpt2_uses_flash_when_forced(monkeypatch):
    """HVD_TPU_FLASH=1 routes GPT-2's causal attention through the
    pallas kernel; logits must match the jnp-reference path."""
    from horovod_tpu.models import gpt2

    cfg = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 24)),
                         jnp.int32)
    monkeypatch.setenv("HVD_TPU_FLASH", "0")
    ref = gpt2.forward(params, tokens, cfg)
    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    import importlib
    ra = importlib.import_module("horovod_tpu.parallel.ring_attention")
    monkeypatch.setattr(
        ra, "local_flash_attention",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
            "gpt2 fell back to local_flash_attention under "
            "HVD_TPU_FLASH=1")))
    out = gpt2.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
