"""Resilient state plane (ISSUE 14): sharded overlap-scheduled
checkpoints + peer-to-peer elastic restore.

Fast tier: shard math (zero.py parity), two-phase manifest atomicity
(torn manifests skipped, never loaded), corrupt-shard quarantine with
rank attribution, write-failure degradation to the previous durable
epoch (retry_with_backoff proof + persistent-failure proof), the
peer-vs-disk restore decision (zero disk reads on the peer path,
survivor death mid-restore re-fetching from the next survivor / falling
back to disk), and the checkpoint dispatch lane: gradient-lane pops are
provably unchanged by checkpoint items (the pure-function budget rule),
and a live CPU-mesh engine streams a durable write while collectives
flow.
"""

import heapq
import os
import time

import numpy as np
import pytest

from horovod_tpu.elastic import stateplane as spl
from horovod_tpu.ops.scheduler import (
    CKPT_LANE, FAST_LANE, FUSED_LANE, CheckpointChunk, pop_checkpoint_items,
    pop_gradient_batches,
)
from horovod_tpu.testing import faults


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _state(epoch=1, n=2048):
    return {"step": epoch, "note": f"e{epoch}",
            "params": np.arange(n, dtype=np.float32) * float(epoch)}


def _plane(directory, rank=0, world=1, serve=False, **kw):
    kw.setdefault("io_backoff_ms", 1.0)
    return spl.StatePlane(str(directory), rank=rank, world=world,
                          serve=serve, **kw)


# ----------------------------------------------------------- shard math
def test_shard_math_round_trips_and_matches_zero_convention():
    """Pad-to-multiple + even slice (parallel/zero.py's _shard_leaf
    convention on bytes): shards cover the blob exactly once, all equal
    length, reassembly is the identity."""
    blob = bytes(range(256)) * 7 + b"tail"
    for world in (1, 2, 3, 8, 16):
        per, pad = spl.shard_bounds(len(blob), world)
        assert per * world == len(blob) + pad
        assert 0 <= pad < world
        parts = [spl.shard_of(blob, i, world) for i in range(world)]
        assert all(len(p) == per for p in parts)
        assert b"".join(parts)[:len(blob)] == blob


def test_encode_decode_round_trip():
    st = _state(3)
    st["obj"] = {"nested": [1, 2, "x"]}
    out = spl.decode_state(spl.encode_state(st))
    assert out["step"] == 3 and out["obj"] == {"nested": [1, 2, "x"]}
    np.testing.assert_array_equal(out["params"], st["params"])


# ------------------------------------------------------------- manifests
def test_two_phase_manifest_and_completeness(tmp_path):
    """An epoch exists exactly when every rank's manifest does; newest
    complete epoch wins; no .tmp ever survives a clean commit."""
    world = 3
    planes = [_plane(tmp_path, rank=r, world=world) for r in range(world)]
    for p in planes:
        assert p.wait_durable(p.commit(state=_state(1), epoch=1), 10)
    assert spl.latest_complete_epoch(str(tmp_path)) == 1
    # Epoch 2: only 2 of 3 ranks commit -> incomplete, epoch 1 still wins.
    for p in planes[:2]:
        assert p.wait_durable(p.commit(state=_state(2), epoch=2), 10)
    assert spl.latest_complete_epoch(str(tmp_path)) == 1
    j = _plane(tmp_path)
    data, epoch, source = j.restore()
    assert (epoch, source) == (1, "disk")
    np.testing.assert_array_equal(data["params"], _state(1)["params"])
    assert not [f for f in os.listdir(tmp_path / "epoch_0000000001")
                if f.endswith(".tmp")]


def test_torn_manifest_is_skipped_not_loaded(tmp_path):
    """A crash between the shard rename and the manifest rename (the
    ckpt_torn point) leaves a torn epoch: restore must fall back to the
    previous complete epoch, never parse the torn one."""
    p = _plane(tmp_path)
    assert p.wait_durable(p.commit(state=_state(1)), 10)
    faults.arm("ckpt_torn:0:io_error")
    p._fire = faults.fire
    e1 = p.commit(state=_state(2))
    assert not p.wait_durable(e1, 10)
    assert faults.fired() and p.write_failures == 1
    faults.disarm()
    # The torn epoch's shard landed, its manifest did not.
    torn = tmp_path / f"epoch_{e1:010d}"
    assert (torn / "shard_0_of_1.bin").exists()
    assert not (torn / "shard_0_of_1.json").exists()
    data, epoch, source = _plane(tmp_path).restore()
    assert (epoch, source) == (0, "disk")
    np.testing.assert_array_equal(data["params"], _state(1)["params"])


def test_unparseable_manifest_marks_epoch_unusable(tmp_path):
    p = _plane(tmp_path)
    p.commit(state=_state(1), wait=True)
    p.commit(state=_state(2), wait=True)
    man = tmp_path / "epoch_0000000001" / "shard_0_of_1.json"
    man.write_text("{torn")
    assert spl.latest_complete_epoch(str(tmp_path)) == 0


def test_corrupt_shard_quarantined_with_attribution(tmp_path):
    """A flipped bit in rank 1's shard: the restore quarantines THAT file
    (attributed to the rank that wrote it) and falls back to the next
    older complete epoch."""
    world = 2
    planes = [_plane(tmp_path, rank=r, world=world) for r in range(world)]
    for e in (1, 2):
        for p in planes:
            assert p.wait_durable(p.commit(state=_state(e), epoch=e), 10)
    victim = tmp_path / "epoch_0000000002" / "shard_1_of_2.bin"
    raw = bytearray(victim.read_bytes())
    raw[7] ^= 0xFF
    victim.write_bytes(bytes(raw))
    j = _plane(tmp_path)
    data, epoch, source = j.restore()
    assert (epoch, source) == (1, "disk")
    np.testing.assert_array_equal(data["params"], _state(1)["params"])
    assert j.quarantined and "shard_1_of_2" in j.quarantined[0]
    assert victim.with_name(victim.name + ".quarantined").exists()


# ----------------------------------------------------------- write faults
def test_transient_write_failure_recovers_via_backoff(tmp_path):
    """One injected OSError on the first chunk-write attempt: the
    retry_with_backoff path lands the epoch anyway."""
    faults.arm("ckpt_write_fail:0:io_error")       # nth=1: one-shot
    p = _plane(tmp_path)
    e = p.commit(state=_state(1))
    assert p.wait_durable(e, 10)
    assert faults.fired() and p.write_failures == 0
    assert spl.latest_complete_epoch(str(tmp_path)) == e


def test_persistent_write_failure_degrades_to_previous_epoch(tmp_path):
    """nth=0 (persistent) write faults exhaust the bounded retries: the
    epoch is abandoned with attribution, the previous durable epoch
    remains the restore point, and nothing torn is observable."""
    p = _plane(tmp_path)
    e0 = p.commit(state=_state(1), wait=True)
    faults.arm("ckpt_write_fail:0:io_error:0")     # nth=0: every arrival
    p._fire = faults.fire
    e1 = p.commit(state=_state(2))
    assert not p.wait_durable(e1, 10)
    assert p.write_failures == 1 and p.durable_epoch == e0
    faults.disarm()
    data, epoch, source = _plane(tmp_path).restore()
    assert (epoch, source) == (e0, "disk")
    np.testing.assert_array_equal(data["params"], _state(1)["params"])
    # The failed epoch left no partial files behind.
    d = tmp_path / f"epoch_{e1:010d}"
    assert not d.exists() or not any(
        f.endswith((".bin", ".json")) for f in os.listdir(d))


def test_fault_nth_zero_grammar():
    s = faults.FaultSpec.parse("ckpt_write_fail:3:io_error:0")
    assert (s.point, s.rank, s.action, s.nth) == (
        "ckpt_write_fail", 3, "io_error", 0)
    with pytest.raises(ValueError):
        faults.FaultSpec.parse("ckpt_write_fail:0:io_error:-1")
    with pytest.raises(ValueError):
        faults.FaultSpec.parse("nope:0:io_error")


def test_supersede_cancels_stale_write_job(tmp_path):
    """Rapid commit cadence (autoscale oscillation): a newer commit
    cancels the unfinished previous job — newest epoch wins, no backlog
    of doomed epochs."""

    class _Park:
        """Engine stand-in that parks items until released."""

        def __init__(self):
            self.items = []

        def submit_checkpoint_io(self, items):
            self.items.extend(items)

    eng = _Park()
    p = _plane(tmp_path)
    p.engine = eng
    e1 = p.commit(state=_state(1))
    e2 = p.commit(state=_state(2))
    for it in eng.items:
        it.run()
    assert p.durable_epoch == e2
    assert spl.latest_complete_epoch(str(tmp_path)) == e2
    # e1's canceled chunks cleaned up after themselves.
    d1 = tmp_path / f"epoch_{e1:010d}"
    assert not d1.exists() or not any(
        f.endswith((".bin", ".json")) for f in os.listdir(d1))


# ------------------------------------------------------------ peer restore
def test_peer_restore_is_bitwise_with_zero_disk_reads(tmp_path):
    """Survivors holding epoch E hand a fresh joiner the committed state
    shard-by-shard: bitwise-identical, spread across the donors, zero
    checkpoint files opened."""
    world = 3
    donors = [_plane(tmp_path / f"d{r}", rank=r, world=world, serve=True)
              for r in range(world)]
    blob_ref = spl.encode_state(_state(9))
    for p in donors:
        p.commit(state=_state(9), epoch=9)
    try:
        j = _plane(tmp_path / "joiner", rank=0, world=world)
        peers = [("127.0.0.1", p.server.port) for p in donors]
        data, epoch, source = j.restore(peers=peers)
        assert (epoch, source) == (9, "peer")
        assert j.disk_reads == 0
        assert j.peer_shards_fetched == len(donors)
        np.testing.assert_array_equal(data["params"], _state(9)["params"])
        assert j.memory_state()[2] == spl.blob_digest(blob_ref)
        assert j.last_restore_source == "peer"
    finally:
        for p in donors:
            p.close()


def test_peer_restore_requires_newer_epoch(tmp_path):
    """The quorum rule: peers at (or below) my epoch are not a restore
    source — and a rank already holding the newest epoch keeps its OWN
    state (source 'memory', never a rollback)."""
    donor = _plane(tmp_path, rank=0, world=1, serve=True)
    donor.commit(state=_state(4), epoch=4, wait=True)
    try:
        j = _plane(tmp_path, rank=0, world=1)
        j.commit(state=_state(4), epoch=4, wait=True)   # already current
        data, epoch, source = j.restore(
            peers=[("127.0.0.1", donor.server.port)])
        assert source == "memory" and epoch == 4
        assert j.restore_fallbacks == 0      # never a peer ATTEMPT
        np.testing.assert_array_equal(data["params"],
                                      _state(4)["params"])
    finally:
        donor.close()


def test_peer_death_mid_restore_refetches_from_next_survivor(tmp_path):
    """restore_peer_exit (econnreset) on one donor: the joiner re-fetches
    that shard from another survivor — still a pure peer restore, zero
    disk reads."""
    donors = [_plane(tmp_path / f"d{r}", rank=r, world=2, serve=True)
              for r in range(2)]
    for p in donors:
        p.commit(state=_state(5), epoch=5)
    faults.arm("restore_peer_exit:0:econnreset")
    donors[0]._fire = faults.fire            # rank 0 donor dies mid-serve
    try:
        j = _plane(tmp_path / "j", rank=0, world=2)
        data, epoch, source = j.restore(
            peers=[("127.0.0.1", p.server.port) for p in donors])
        assert (epoch, source) == (5, "peer")
        assert faults.fired() and j.disk_reads == 0
        np.testing.assert_array_equal(data["params"], _state(5)["params"])
    finally:
        for p in donors:
            p.close()


def test_sole_peer_death_falls_back_to_disk(tmp_path):
    """The LAST newer-epoch survivor dying mid-restore: clean fallback to
    the newest complete epoch on disk — consistent, attributed, no
    wedge."""
    donor = _plane(tmp_path, rank=0, world=1, serve=True)
    donor.commit(state=_state(2), epoch=2, wait=True)
    faults.arm("restore_peer_exit:0:econnreset")
    donor._fire = faults.fire
    try:
        j = _plane(tmp_path, rank=0, world=1)
        data, epoch, source = j.restore(
            peers=[("127.0.0.1", donor.server.port)])
        assert (epoch, source) == (2, "disk")
        assert j.restore_fallbacks == 1
        np.testing.assert_array_equal(data["params"], _state(2)["params"])
    finally:
        donor.close()


def test_unreachable_peers_fall_through_to_disk(tmp_path):
    p = _plane(tmp_path)
    p.commit(state=_state(1), wait=True)
    j = _plane(tmp_path)
    _data, epoch, source = j.restore(peers=[("127.0.0.1", 1)])  # dead port
    assert (epoch, source) == (0, "disk")


# -------------------------------------------------------- dispatch lanes
def _heap_with(batches, ckpt_items):
    heap, seq = [], 0
    for lane, prio, payload in batches:
        heapq.heappush(heap, (lane, -prio, seq, payload))
        seq += 1
    for it in ckpt_items:
        heapq.heappush(heap, (CKPT_LANE, 0, seq, it))
        seq += 1
    return heap


def test_gradient_pops_unchanged_by_checkpoint_items():
    """THE dispatch-order guarantee: for every budget, the gradient-lane
    pop sequence with checkpoint items in the heap is identical to the
    sequence without them, and checkpoint items never consume the fused
    budget."""
    batches = [(FUSED_LANE, 0, "fuseA"), (FAST_LANE, 0, "fast1"),
               (FUSED_LANE, 5, "fuseHot"), (FAST_LANE, 2, "fast2"),
               (FUSED_LANE, 0, "fuseB")]
    ckpt = [CheckpointChunk(f"ck{i}", run=lambda: None) for i in range(4)]
    for budget in (1, 2, 3, 10):
        h_plain = _heap_with(batches, [])
        h_ckpt = _heap_with(batches, ckpt)
        got_plain = pop_gradient_batches(h_plain, budget)
        got_ckpt = pop_gradient_batches(h_ckpt, budget)
        assert got_plain == got_ckpt, (budget, got_plain, got_ckpt)
        # Leftover gradient batches (budget exhausted) still outrank the
        # checkpoint lane: nothing checkpoint-shaped pops while they wait.
        leftovers = [x for x in h_ckpt if x[0] != CKPT_LANE]
        if leftovers:
            assert pop_checkpoint_items(h_ckpt, 99) == []
        else:
            popped = pop_checkpoint_items(h_ckpt, 2)
            assert len(popped) == 2
            assert all(isinstance(i, CheckpointChunk) for i in popped)


def test_checkpoint_items_pop_in_arrival_order_after_gradients():
    items = [CheckpointChunk(f"ck{i}", run=lambda: None) for i in range(3)]
    heap = _heap_with([(FUSED_LANE, 0, "g")], items)
    assert pop_gradient_batches(heap, 1) == ["g"]
    assert [i.name for i in pop_checkpoint_items(heap, 10)] == [
        "ck0", "ck1", "ck2"]


def test_checkpoint_chunk_fail_hook():
    seen = []
    c = CheckpointChunk("x", run=lambda: None, fail=seen.append)
    exc = RuntimeError("boom")
    c.fail(exc)
    assert seen == [exc]


# ------------------------------------------------- live engine integration
def test_engine_streams_durable_write_while_collectives_flow(
        hvd, world_size, tmp_path):
    """The overlap end to end on the CPU mesh: a commit streamed through
    the live engine's checkpoint lane lands durable while allreduces
    flow, results bitwise-equal to a checkpoint-less run, and the lane
    counts the chunks."""
    from horovod_tpu.common import basics
    eng = basics._get_state().engine
    plane = _plane(tmp_path, world=1)
    plane.engine = eng
    before = eng.ckpt_chunks_dispatched
    x = np.stack([np.full((64,), r + 1.0, np.float32)
                  for r in range(world_size)])
    base = np.asarray(hvd.allreduce(x.copy(), name="ckpt_base",
                                    op=hvd.Sum))
    epoch = plane.commit(state=_state(1, n=1 << 16))
    out = np.asarray(hvd.allreduce(x.copy(), name="ckpt_overlap",
                                   op=hvd.Sum))
    assert plane.wait_durable(epoch, 15), "lane never drained the write"
    np.testing.assert_array_equal(base, out)
    assert eng.ckpt_chunks_dispatched > before
    assert spl.latest_complete_epoch(str(tmp_path)) == epoch


def test_engine_submit_after_fault_fails_items_cleanly(hvd, tmp_path):
    """A closed lane (engine fault latched) must fail checkpoint items
    immediately — the write job abandons its epoch instead of queueing
    into a dead engine."""
    from horovod_tpu.ops.engine import CollectiveEngine
    from horovod_tpu.common import basics
    eng = CollectiveEngine(basics._get_state())
    eng._fault = RuntimeError("dead control plane")
    failed = []
    eng.submit_checkpoint_io(
        [CheckpointChunk("c", run=lambda: None, fail=failed.append)])
    assert len(failed) == 1 and "dead control plane" in str(failed[0])


def test_write_job_abort_keeps_previous_epoch(tmp_path):
    """The engine-abort path (_abort_engine fails the lane): the job
    cleans up and the previous durable epoch remains."""

    class _Park:
        def __init__(self):
            self.items = []

        def submit_checkpoint_io(self, items):
            self.items.extend(items)

    p = _plane(tmp_path)
    e0 = p.commit(state=_state(1), wait=True)
    p.engine = _Park()
    e1 = p.commit(state=_state(2))
    for it in p.engine.items:
        it.fail(RuntimeError("HVD303"))
    assert p.write_failures == 1 and p.durable_epoch == e0
    assert not p.wait_durable(e1, 1)


# ------------------------------------------------------- monitor wiring
def test_aggregator_summary_carries_fleet_commit_age():
    """last_commit_age_s = the STALEST reporting rank (one stale rank
    makes a shrink unsafe); null without checkpoint telemetry."""
    from horovod_tpu.monitor.aggregator import RankAggregator
    agg = RankAggregator(world=2)
    agg.update(0, {"cycle_us_avg": 100.0,
                   "checkpoint": {"epoch": 5, "durable_epoch": 5,
                                  "last_commit_age_s": 2.0}})
    agg.update(1, {"cycle_us_avg": 110.0,
                   "checkpoint": {"epoch": 4, "durable_epoch": 4,
                                  "last_commit_age_s": 31.5}})
    s = agg.summary()
    assert s["last_commit_age_s"] == 31.5
    h = agg.health(interval_s=5.0)
    assert h["checkpoint"]["last_commit_age_s"] == 31.5
    assert h["checkpoint"]["min_durable_epoch"] == 4
    assert h["checkpoint"]["ranks"]["1"]["epoch"] == 4
    agg2 = RankAggregator(world=1)
    agg2.update(0, {"cycle_us_avg": 100.0})
    assert agg2.summary()["last_commit_age_s"] is None
    assert "checkpoint" not in agg2.health()


def test_monitor_exports_last_commit_age_gauge(tmp_path):
    """hvd_last_commit_age_s (plus epoch/failure series) on /metrics via
    the standard agent collector, off a duck-typed engine."""
    from horovod_tpu.monitor.agent import MonitorAgent

    class _Eng:
        cycle_count = 1
        cycle_us_total = 10.0
        _cycle_index = 1
        last_cycle_ts = time.time()
        monitor = None
        ckpt_chunks_dispatched = 7

    eng = _Eng()
    eng.stateplane = _plane(tmp_path)
    eng.stateplane.commit(state=_state(1), wait=True)
    agent = MonitorAgent(engine=eng, rank=0, world=1, interval_s=0.01)
    text = agent.render_prometheus()
    assert "hvd_last_commit_age_s" in text
    assert 'hvd_ckpt_epoch{rank="0"} 0' in text
    assert 'hvd_ckpt_chunks_total{rank="0"} 7' in text
    snap = agent.local_snapshot()
    assert snap["checkpoint"]["epoch"] == 0
    assert snap["checkpoint"]["last_commit_age_s"] is not None

    # Review fix: an armed-but-NEVER-committed plane exports the same
    # infinitely-stale sentinel the aggregator/stale-guard use — never
    # -1, which would read FRESHER than every committed rank and hide
    # exactly this rank from any age > threshold alert.
    from horovod_tpu.monitor.aggregator import NEVER_COMMITTED_AGE_S
    eng2 = _Eng()
    eng2.stateplane = _plane(tmp_path / "fresh")
    agent2 = MonitorAgent(engine=eng2, rank=0, world=1, interval_s=0.01)
    line = next(l for l in agent2.render_prometheus().splitlines()
                if l.startswith("hvd_last_commit_age_s{"))
    assert float(line.split()[-1]) == NEVER_COMMITTED_AGE_S, line


def test_obtain_reuses_plane_across_engine_generations(tmp_path):
    """One plane per checkpoint directory per process (like the
    generation-surviving host agent): re-init re-binds rank/world/engine
    but the in-memory epoch — what survivors serve to re-joiners —
    persists."""
    p1 = spl.obtain(str(tmp_path), rank=1, world=4, engine=None)
    p1.commit(state=_state(1), wait=True)
    try:
        p2 = spl.obtain(str(tmp_path), rank=0, world=3, engine="eng2")
        assert p2 is p1
        assert (p2.rank, p2.world, p2.engine) == (0, 3, "eng2")
        assert p2.epoch == 0                 # the committed epoch survived
        assert p2.server is not None
    finally:
        p1.close()
        spl._registry.pop(str(tmp_path), None)


def test_mid_fetch_commit_does_not_strand_peer_restore(tmp_path):
    """Review fix: a survivor committing DURING a joiner's fetch keeps
    serving the epoch the fetch started on (current + previous blobs
    retained) — the peer path must not silently degrade to disk under
    active training."""
    donor = _plane(tmp_path, rank=0, world=1, serve=True)
    donor.commit(state=_state(5), epoch=5)
    donor.commit(state=_state(6), epoch=6)       # epoch 5 still servable
    try:
        assert donor.blob_for(5) is not None
        assert donor.blob_for(6) is not None
        assert donor.blob_for(4) is None         # only current + previous
        piece = spl.fetch_shard("127.0.0.1", donor.server.port,
                                5, 0, 1)
        blob5 = spl.encode_state(_state(5))
        assert piece[:len(blob5)] == blob5
    finally:
        donor.close()


def test_aggregator_never_committed_rank_reads_infinitely_stale():
    """Review fix: an ARMED plane that has never committed must count as
    effectively-infinitely stale (the guard refuses the shrink), never
    invisible — via a FINITE sentinel so /health stays strict JSON."""
    import json as _json

    from horovod_tpu.monitor.aggregator import (
        NEVER_COMMITTED_AGE_S, RankAggregator,
    )
    agg = RankAggregator(world=2)
    agg.update(0, {"cycle_us_avg": 100.0,
                   "checkpoint": {"epoch": 3, "durable_epoch": 3,
                                  "last_commit_age_s": 1.0}})
    agg.update(1, {"cycle_us_avg": 100.0,
                   "checkpoint": {"epoch": -1, "durable_epoch": -1,
                                  "last_commit_age_s": None}})
    age = agg.summary()["last_commit_age_s"]
    assert age == NEVER_COMMITTED_AGE_S
    # Strict JSON round-trip (jq/JSON.parse compatibility): no Infinity.
    assert "Infinity" not in _json.dumps(agg.health())
    # ...and the policy holds on it.
    from horovod_tpu.elastic.autoscale import ScalePolicy
    p = ScalePolicy(min_np=1, persistence=1, cooldown_s=0.0, idle_s=1.0,
                    commit_max_age_s=30.0)
    p.observe({"queue_depth": 0, "progress_total": 7,
               "last_commit_age_s": age}, 3, now=100.0)
    p.observe({"queue_depth": 0, "progress_total": 7,
               "last_commit_age_s": age}, 3, now=110.0)
    d = p.observe({"queue_depth": 0, "progress_total": 7,
                   "last_commit_age_s": age}, 3, now=120.0)
    assert d.is_hold and "stale-state guard" in d.reason, d


def test_restore_never_rolls_a_rank_backwards(tmp_path):
    """Review fix: a restore whose recovered epoch is NOT newer than the
    rank's in-memory epoch (peer died mid-fetch, disk holds an older
    epoch) keeps the rank's own state — source 'memory' — instead of
    rolling it (and, via a re-ranked rank 0's sync, the fleet) back."""
    p = _plane(tmp_path)
    p.commit(state=_state(4), epoch=4, wait=True)
    p.commit(state=_state(5), epoch=5)         # epoch 5 in memory
    # Disk newest-complete is 4 (epoch 5's write may or may not have
    # landed; force the older-recovery shape with an unreachable peer).
    data, epoch, source = p.restore(peers=[("127.0.0.1", 1)])
    if p.durable_epoch >= 5:
        assert epoch == 5                      # disk caught up: fine
    else:
        assert (epoch, source) == (5, "memory"), (epoch, source)
    assert data["step"] == 5
    np.testing.assert_array_equal(data["params"], _state(5)["params"])


def test_malformed_peer_header_takes_the_failover_path(tmp_path):
    """Review fix: a garbled header — a reused port where another service
    answers, or a dying peer's truncated line — must raise OSError from
    the peer clients (the failover / disk-fallback path catches exactly
    that), never IndexError/ValueError crashing the restoring worker."""
    import socket
    import threading

    def _fake_server(replies):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)

        def _serve():
            for reply in replies:
                conn, _ = srv.accept()
                conn.makefile("rb").readline()
                conn.sendall(reply)
                conn.close()
            srv.close()

        threading.Thread(target=_serve, daemon=True).start()
        return srv.getsockname()[1]

    port = _fake_server([b"\n", b"OK notanint x\n",
                         b"EPOCH zero 0 -\n", b"HTTP/1.1 400 nope\n"])
    with pytest.raises(OSError):
        spl.fetch_shard("127.0.0.1", port, 1, 0, 1)      # empty header
    with pytest.raises(OSError):
        spl.fetch_shard("127.0.0.1", port, 1, 0, 1)      # non-int length
    with pytest.raises(OSError):
        spl.peer_epoch("127.0.0.1", port)                # non-int epoch
    with pytest.raises(OSError):
        spl.peer_epoch("127.0.0.1", port)                # alien service
    # ...and restore() treats such a peer like any dead one: disk wins.
    p = _plane(tmp_path)
    p.commit(state=_state(1), wait=True)
    j = _plane(tmp_path)
    bad_port = _fake_server([b"HTTP/1.1 400 nope\n"])
    _data, epoch, source = j.restore(peers=[("127.0.0.1", bad_port)])
    assert (epoch, source) == (0, "disk")


def test_write_job_manifest_survives_plane_rebind(tmp_path):
    """Review fix: a chunked write job snapshots rank/world/generation at
    creation — an elastic re-bind (obtain() renumbering the plane while
    chunks are still queued on the checkpoint lane) must not produce a
    manifest whose rank/world disagree with the shard filename, which
    epoch_manifests would reject forever."""
    p = _plane(tmp_path, rank=0, world=1)
    blob = spl.encode_state(_state(3))
    job = spl._WriteJob(p, 3, blob)
    items = job.chunk_items(1024)
    p.rank, p.world, p.generation = 5, 8, 9     # re-bind mid-job
    for it in items:
        it.run()
    manifests = spl.epoch_manifests(str(tmp_path), 3)
    assert manifests is not None, "re-bound manifest rejected"
    assert (manifests[0]["rank"], manifests[0]["world"]) == (0, 1)
    assert p.durable_epoch == 3


# ------------------------------------------- JaxState peer restore (ISSUE 15)
def _sharded_saveable(world: int, base: float = 7.0):
    """A rank-invariant sharded-optimizer saveable in exactly the form
    ``JaxState.save`` emits for a DistributedOptimizer(sharded=True)
    state: gathered flat moment arrays + a real shard plan."""
    import jax.numpy as jnp
    from horovod_tpu.jax.optimizer import _make_shard_plan
    n = 10                                    # non-divisible by world=4
    plan = _make_shard_plan([jnp.zeros((n,), jnp.float32)], world, 0, 0)
    pad = plan.pads[0]
    mu = np.concatenate([np.arange(n, dtype=np.float32) + base,
                         np.zeros(pad, np.float32)])
    return {"__hvd_sharded_opt__": 1, "world": world,
            "plan": plan._replace(rank=-1)._asdict(),
            "inner_states": [{"mu": mu, "count": np.int32(3)}]}, plan


def test_jaxstate_load_recovered_reslices_own_shard(tmp_path, monkeypatch):
    """The REAL jax path through the peer shard fetch: a committed state
    holding a sharded-optimizer saveable round-trips the plane, and the
    joining rank's JaxState.load_recovered puts tree leaves back on
    device AND re-slices exactly its own 1/N optimizer shard (never the
    gathered whole)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.common import basics
    from horovod_tpu.elastic.state import JaxState
    from horovod_tpu.jax.optimizer import ShardedOptimizerState

    world, my_rank = 4, 2
    saveable, plan = _sharded_saveable(world)
    committed = {"step": 9,
                 "params": {"w": np.arange(6, dtype=np.float32) * 3.0},
                 "opt": saveable}

    # Round-trip the real plane: donors commit, a fresh joiner restores.
    donors = [spl.StatePlane(str(tmp_path), rank=r, world=world, serve=True)
              for r in range(world)]
    try:
        for p in donors:
            p.commit(state=committed, epoch=2)
        joiner = spl.StatePlane(str(tmp_path) + ".j", rank=my_rank,
                                world=world, serve=False)
        data, epoch, source = joiner.restore(
            peers=[("127.0.0.1", p.server.port) for p in donors])
        assert (epoch, source) == (2, "peer")
    finally:
        for p in donors:
            p.close()

    monkeypatch.setattr(basics, "rank", lambda: my_rank)
    monkeypatch.setattr(basics, "size", lambda: world)
    state = JaxState(params={"w": jnp.zeros((6,), jnp.float32)},
                     opt=0, step=0)
    state.load_recovered(data)

    assert state.step == 9
    assert isinstance(state.params["w"], jax.Array)
    np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                  committed["params"]["w"])
    # The optimizer came back as THIS rank's 1/N shard, not the whole.
    assert isinstance(state.opt, ShardedOptimizerState)
    assert state.opt.plan.rank == my_rank
    per = plan.pers[0]
    got = np.asarray(state.opt.inner_states[0]["mu"])
    want = np.asarray(saveable["inner_states"][0]["mu"])
    np.testing.assert_array_equal(
        got, want[my_rank * per:(my_rank + 1) * per])
    assert got.size == per < want.size
    # Scalars stay replicated.
    assert int(state.opt.inner_states[0]["count"]) == 3
    # The recovered dict IS the new saved state (no re-gather, no
    # collective on the lone stale rank).
    assert state._saved_state["step"] == 9
    assert state._saved_state["opt"]["__hvd_sharded_opt__"] == 1


def test_jaxstate_load_recovered_world_mismatch_keeps_saveable(monkeypatch):
    """A committed world that no longer matches the fleet cannot be
    re-sliced silently: the raw saveable is kept (the caller re-inits),
    never a wrong-shaped shard."""
    import jax.numpy as jnp

    from horovod_tpu.common import basics
    from horovod_tpu.elastic.state import JaxState

    saveable, _plan = _sharded_saveable(4)
    monkeypatch.setattr(basics, "rank", lambda: 0)
    monkeypatch.setattr(basics, "size", lambda: 2)       # world changed
    state = JaxState(params={"w": jnp.zeros((6,), jnp.float32)},
                     opt=0, step=0)
    state.load_recovered({"opt": saveable, "step": 1,
                          "params": {"w": np.zeros(6, np.float32)}})
    assert isinstance(state.opt, dict)
    assert state.opt["__hvd_sharded_opt__"] == 1
