"""Smoke-run every user-facing example under ``torovodrun -np 2`` on CPU —
the reference CI's examples tier (its buildkite pipelines run
``examples/*/..._mnist.py`` on every backend; SURVEY.md §4).  Tiny sizes:
the goal is "a new user's copy-paste works", not convergence.
"""

import os
import subprocess
import sys

import pytest

# Integration tier: real subprocess launches (see pyproject markers);
# the fast hermetic tier excludes these with `-m 'not slow'`.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _example_env(**extra):
    env = dict(os.environ)
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    env.pop("HOROVOD_TIMELINE", None)
    env.update(extra)
    return env


def _run_example(script, extra_args=(), np_=2, timeout=300, launcher_args=()):
    env = _example_env()
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           *launcher_args]
    if np_ is not None:
        cmd += ["-np", str(np_)]
    cmd += [sys.executable, os.path.join(EXAMPLES, script), *extra_args]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def _assert_done(r):
    assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-4000:])
    assert "DONE" in r.stdout, r.stdout[-4000:]


def test_example_mnist_jax():
    r = _run_example("mnist_jax.py",
                     ["--epochs", "1", "--n-train", "256",
                      "--batch-size", "32"])
    _assert_done(r)
    assert "epoch 0" in r.stdout


def test_example_resnet_synthetic():
    r = _run_example("resnet_synthetic.py",
                     ["--depth", "18", "--image-size", "32",
                      "--num-classes", "10", "--batch-size", "4",
                      "--num-iters", "2", "--num-warmup", "1", "--fp32"])
    _assert_done(r)
    assert "img/s" in r.stdout


def test_example_torch_mnist():
    r = _run_example("torch_mnist.py",
                     ["--epochs", "1", "--n-train", "256",
                      "--batch-size", "32"])
    _assert_done(r)
    assert "epoch 0" in r.stdout


def test_example_tf_keras_mnist():
    r = _run_example("tf_keras_mnist.py",
                     ["--epochs", "1", "--n-train", "256",
                      "--batch-size", "32"])
    _assert_done(r)


def test_example_dlrm_alltoall():
    r = _run_example("dlrm_alltoall.py",
                     ["--steps", "2", "--batch-size", "16",
                      "--vocab", "64", "--dim", "4"])
    _assert_done(r)
    assert "exchanged" in r.stdout


def test_example_llama_spmd():
    """Single-process SPMD flagship: dp=2 x tp=2 x sp=2 over 8 virtual CPU
    devices (no torovodrun — one controller drives the mesh)."""
    env = _example_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "llama_spmd.py"),
         "--dp", "2", "--tp", "2", "--sp", "2", "--steps", "2", "--tiny"],
        env=env, capture_output=True, text=True, timeout=300)
    _assert_done(r)
    assert "tok/s" in r.stdout


def test_example_llama_spmd_pipeline():
    """Flagship with pipeline stages: dp=2 x pp=2 x tp=2, GPipe
    microbatches (VERDICT r3 weak #5a: pp composed into the llama step)."""
    env = _example_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "llama_spmd.py"),
         "--dp", "2", "--pp", "2", "--tp", "2", "--steps", "2", "--tiny",
         "--seq", "32"],
        env=env, capture_output=True, text=True, timeout=300)
    _assert_done(r)
    assert "pp=2" in r.stdout


def test_example_llama_generate():
    """Inference example: tp=2 sharded generate with sampling (blockwise
    prefill + KV-cache decode through shard_map)."""
    env = _example_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "llama_generate.py"),
         "--tiny", "--tp", "2", "--n-tokens", "6",
         "--temperature", "0.8", "--top-p", "0.9"],
        env=env, capture_output=True, text=True, timeout=300)
    _assert_done(r)
    assert "tp=2" in r.stdout and "sampled" in r.stdout


def test_example_moe_expert_parallel():
    """MoE with experts sharded over ep=4 (alltoall dispatch/return)."""
    env = _example_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "moe_expert_parallel.py"),
         "--ep", "4", "--steps", "2"],
        env=env, capture_output=True, text=True, timeout=300)
    _assert_done(r)
    assert "ep=4" in r.stdout


def test_example_adasum_train():
    r = _run_example("adasum_train.py",
                     ["--epochs", "1", "--n-train", "128",
                      "--batch-size", "32"])
    _assert_done(r)
    assert "adasum" in r.stdout


def test_example_elastic_train(tmp_path):
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost:2\n")
    r = _run_example("elastic_train.py",
                     ["--epochs", "2", "--n-train", "128",
                      "--batch-size", "32"],
                     np_=None,
                     launcher_args=["--host-discovery-script",
                                    f"cat {hostfile}",
                                    "--min-np", "1", "--max-np", "2"])
    _assert_done(r)
    assert "world=2" in r.stdout


def test_example_vit_classify():
    r = _run_example("vit_classify.py",
                     ["--tiny", "--num-iters", "2", "--num-warmup", "1",
                      "--batch-size", "4"])
    _assert_done(r)
    assert "img/s" in r.stdout


def test_example_gpt2_import_generate():
    r = _run_example("gpt2_import_generate.py", np_=1)
    _assert_done(r)
    assert "logits parity" in r.stdout
