"""Pipelined data plane, hermetic tier: chunked fused reductions must be
bitwise-identical to the single-chunk program, chunk COUNTS (not raw chunk
bytes) must key the program cache, and the priority drain must order
dispatch.  Runs on the 8-virtual-device CPU mesh (single-controller mode —
the in-flight window itself is multi-process-only and covered by
tests/data/worker_pipeline.py plus the no-jax ring tests in
test_scheduler.py)."""

import numpy as np
import pytest


def _engine(hvd):
    from horovod_tpu.common import basics
    return basics._get_state().engine


@pytest.fixture()
def chunk_knob(hvd):
    """Save/restore the engine's pipeline knobs around a test."""
    eng = _engine(hvd)
    saved = (eng.pipeline_chunk_bytes, eng.max_inflight)
    yield eng
    eng.pipeline_chunk_bytes, eng.max_inflight = saved


def _stacked(world, shape, seed, scale=1.0):
    rng = np.random.RandomState(seed)
    return np.stack([rng.randn(*shape).astype(np.float32) * scale * (r + 1)
                     for r in range(world)])


def test_chunked_allreduce_bitwise_matches_single_chunk(hvd, world_size,
                                                        chunk_knob):
    """Chunk boundaries never change which ranks reduce which element, so
    the chunked program's results are bitwise-identical — fp32 and with
    bf16 wire compression."""
    eng = chunk_knob
    xs = [_stacked(world_size, (257,), 0), _stacked(world_size, (33, 5), 1)]
    for comp in (None, "bf16"):
        eng.pipeline_chunk_bytes = 0          # single chunk (legacy)
        base = [np.asarray(o) for o in hvd.grouped_allreduce(
            [x.copy() for x in xs], name=f"chunk_base_{comp}", op=hvd.Sum,
            compression=comp)]
        eng.pipeline_chunk_bytes = 256        # 64 elems/chunk -> many chunks
        out = [np.asarray(o) for o in hvd.grouped_allreduce(
            [x.copy() for x in xs], name=f"chunk_on_{comp}", op=hvd.Sum,
            compression=comp)]
        for b, o in zip(base, out):
            np.testing.assert_array_equal(b, o)


def test_chunked_average_and_scale_factors(hvd, world_size, chunk_knob):
    eng = chunk_knob
    x = _stacked(world_size, (129,), 2)
    eng.pipeline_chunk_bytes = 0
    base = np.asarray(hvd.allreduce(x.copy(), name="chunk_avg_base",
                                    op=hvd.Average, prescale_factor=0.5,
                                    postscale_factor=3.0))
    eng.pipeline_chunk_bytes = 128
    out = np.asarray(hvd.allreduce(x.copy(), name="chunk_avg_on",
                                   op=hvd.Average, prescale_factor=0.5,
                                   postscale_factor=3.0))
    np.testing.assert_array_equal(base, out)


def test_chunk_count_not_chunk_bytes_keys_program_cache(hvd, world_size,
                                                        chunk_knob):
    """Two knob values that produce the SAME chunk plan must share one
    compiled program; a different plan compiles a new one.  This is what
    bounds program count while autotune walks the knob."""
    eng = chunk_knob
    x = _stacked(world_size, (64,), 3)        # 256 bytes per rank shard
    eng.pipeline_chunk_bytes = 128            # -> 2 chunks
    hvd.allreduce(x.copy(), name="keying_a", op=hvd.Sum)
    misses = eng.cache.misses
    eng.pipeline_chunk_bytes = 130            # still ceil(256/130) = 2
    hvd.allreduce(x.copy(), name="keying_b", op=hvd.Sum)
    assert eng.cache.misses == misses, (
        "same chunk plan under a different byte knob recompiled")
    eng.pipeline_chunk_bytes = 64             # -> 4 chunks: a new plan
    hvd.allreduce(x.copy(), name="keying_c", op=hvd.Sum)
    assert eng.cache.misses == misses + 1


def test_chunk_plan_is_count_per_dtype_group(hvd, world_size, chunk_knob):
    from horovod_tpu.ops.engine import CollectiveType
    eng = chunk_knob
    eng.pipeline_chunk_bytes = 1024
    shapes = ((world_size, 512), (world_size, 512), (world_size, 100))
    dtypes = ("float32", "float32", "int32")
    # fp32 group: 2*512*4 = 4096 B -> 4 chunks; int32 group: 400 B -> 1.
    assert eng._chunk_plan(CollectiveType.ALLREDUCE, shapes, dtypes) == (4, 1)
    # Non-reduction ops never chunk.
    assert eng._chunk_plan(CollectiveType.ALLGATHER, shapes, dtypes) == ()
    # Degenerate: chunk bound never exceeds the element count.
    eng.pipeline_chunk_bytes = 1
    assert eng._chunk_plan(
        CollectiveType.ALLREDUCE, ((world_size, 3),), ("float32",)) == (3,)


def test_priority_orders_single_controller_dispatch(hvd, world_size):
    """Two non-fusible ops enqueued low-priority-first must dispatch
    high-priority-first: the compiled-program cache records build order."""
    from horovod_tpu.ops import eager
    eng = _engine(hvd)
    # Enqueue while HOLDING the cycle lock: the background thread (woken by
    # enqueue) blocks at run_loop_once until we have drained both entries
    # in one deterministic cycle of our own.
    with eng._cycle_lock:
        x = _stacked(world_size, (977,), 4)   # unseen shape: both ops miss
        h_lo = eager.allreduce_async(x.copy(), name="prio.lo", op=hvd.Max,
                                     priority=0)
        h_hi = eager.allreduce_async(x.copy(), name="prio.hi", op=hvd.Min,
                                     priority=7)
        before = list(eng.cache._cache)
        eng._run_cycle_locked()
    eager.synchronize([h_lo, h_hi])
    new = [k for k in eng.cache._cache if k not in before]
    ops = [k[0][1] for k in new]             # fusion key -> reduce_op
    from horovod_tpu.ops import collectives as C
    assert ops == [C.ReduceOp.MIN, C.ReduceOp.MAX], (
        f"high-priority entry did not dispatch first: {ops}")


@pytest.mark.parametrize("opname", ["SUM", "AVERAGE", "PRODUCT", "MIN",
                                    "MAX"])
@pytest.mark.parametrize("dtname", ["float32", "float16", "bfloat16",
                                    "int32", "int64", "bool"])
def test_join_fill_value_is_reduction_identity(opname, dtname):
    """Property: a joined rank's synthesized contribution must be the true
    identity of the reduction — reducing it with ANY value x returns x —
    for every (op, dtype) combination."""
    import ml_dtypes
    from horovod_tpu.ops import collectives as C
    from horovod_tpu.ops.engine import CollectiveEngine, CollectiveType

    op = C.ReduceOp[opname]
    dt = np.dtype(getattr(ml_dtypes, dtname, None) or dtname)
    fill = CollectiveEngine._join_fill_value(CollectiveType.ALLREDUCE, op, dt)
    fill_arr = np.full((16,), fill, dt)

    rng = np.random.RandomState(hash((opname, dtname)) % (1 << 31))
    if dt == np.bool_:
        x = rng.rand(16) > 0.5
    elif np.issubdtype(dt, np.integer):
        x = rng.randint(-50, 50, 16).astype(dt)
    else:
        x = (rng.randn(16) * 10).astype(dt)

    if op in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
        # AVERAGE divides by world AFTER the sum, so the identity
        # requirement is on the sum itself.
        reduced = x + fill_arr if dt != np.bool_ else x | fill_arr
    elif op == C.ReduceOp.PRODUCT:
        reduced = x * fill_arr if dt != np.bool_ else x & fill_arr
    elif op == C.ReduceOp.MIN:
        reduced = np.minimum(x, fill_arr)
    else:
        reduced = np.maximum(x, fill_arr)
    np.testing.assert_array_equal(reduced.astype(dt), x)
