"""Ray/Spark integration tests — the parts runnable without ray/pyspark
(the reference tests placement and store logic the same way: pure logic
with no cluster, SURVEY.md §4 test_ray.py/test_spark.py)."""

import os

import numpy as np
import pytest

from horovod_tpu.ray import NodeResources, RayExecutor, pack, spread
from horovod_tpu.spark import LocalStore, Store


NODES = [NodeResources("a", cpus=8, accelerators=4),
         NodeResources("b", cpus=8, accelerators=4),
         NodeResources("c", cpus=8, accelerators=2)]


def test_pack_fills_nodes_in_order():
    allocs = pack(NODES, 6)
    assert [(a.hostname, a.local_rank, a.rank) for a in allocs] == [
        ("a", 0, 0), ("a", 1, 1), ("a", 2, 2), ("a", 3, 3),
        ("b", 0, 4), ("b", 1, 5)]
    assert allocs[4].cross_rank == 1


def test_spread_round_robins():
    allocs = spread(NODES, 6)
    by_host = {}
    for a in allocs:
        by_host.setdefault(a.hostname, 0)
        by_host[a.hostname] += 1
    assert by_host == {"a": 2, "b": 2, "c": 2}
    # Ranks grouped per host, host order preserved.
    assert [a.hostname for a in allocs] == ["a", "a", "b", "b", "c", "c"]


def test_spread_uneven_capacity():
    allocs = spread(NODES, 9)
    by_host = {}
    for a in allocs:
        by_host[a.hostname] = by_host.get(a.hostname, 0) + 1
    assert by_host == {"a": 4, "b": 3, "c": 2}


def test_placement_capacity_errors():
    with pytest.raises(ValueError):
        pack(NODES, 11)
    with pytest.raises(ValueError):
        spread(NODES, 11)
    assert len(pack(NODES, 10)) == 10


def test_ray_executor_env_construction():
    ex = RayExecutor(num_workers=6, placement="pack")
    allocs = ex.compute_placement(NODES)
    env = ex.worker_env(allocs[4], ("a", 1111, 2222))
    assert env["HOROVOD_RANK"] == "4"
    assert env["HOROVOD_SIZE"] == "6"
    assert env["HOROVOD_LOCAL_RANK"] == "0"
    assert env["HOROVOD_LOCAL_SIZE"] == "2"
    assert env["HOROVOD_CROSS_RANK"] == "1"
    assert env["HOROVOD_CROSS_SIZE"] == "2"
    assert env["HOROVOD_CONTROLLER_ADDR"] == "a"
    assert env["HOROVOD_HOSTNAME"] == "b"


def test_ray_executor_requires_ray_to_start():
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()
    ex.shutdown()  # no-op without workers


def test_spark_run_requires_pyspark():
    import horovod_tpu.spark as hvd_spark
    with pytest.raises(ImportError, match="pyspark"):
        hvd_spark.run(lambda: None, num_proc=2)


def test_local_store(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    assert isinstance(store, LocalStore)
    ckpt = store.get_checkpoint_path("run1")
    assert "run1" in ckpt
    store.write(os.path.join(ckpt, "model.bin"), b"\x00\x01")
    assert store.exists(os.path.join(ckpt, "model.bin"))
    assert store.read(os.path.join(ckpt, "model.bin")) == b"\x00\x01"
    assert store.get_train_data_path(3).endswith("intermediate_train_data.3")
    assert store.get_logs_path("run1") != ckpt
    store.delete(ckpt)
    assert not store.exists(ckpt)


def test_store_unknown_scheme():
    with pytest.raises(NotImplementedError):
        Store.create("abfs://container/path")


class DictFS:
    """Injectable filesystem double for RemoteStore (DESIGN.md: remote I/O
    is environment-blocked; the layout + plumbing are not)."""

    def __init__(self):
        self.blobs = {}

    def exists(self, path):
        return path in self.blobs or any(
            k.startswith(path.rstrip("/") + "/") for k in self.blobs)

    def read(self, path):
        return self.blobs[path]

    def write(self, path, data):
        self.blobs[path] = data

    def delete(self, path):
        for k in [k for k in self.blobs
                  if k == path or k.startswith(path.rstrip("/") + "/")]:
            del self.blobs[k]


@pytest.mark.parametrize("cls_name,prefix", [
    ("HDFSStore", "hdfs://namenode:9000/horovod"),
    ("S3Store", "s3://bucket/horovod"),
    ("GCSStore", "gs://bucket/horovod"),
])
def test_remote_store_layout_and_io(cls_name, prefix):
    import horovod_tpu.spark as hs
    fs = DictFS()
    store = getattr(hs, cls_name)(prefix, fs=fs)
    # Reference layout over URL joins.
    assert store.get_train_data_path(3, run_id="r1") == \
        f"{prefix}/r1/intermediate_train_data.3"
    assert store.get_val_data_path(run_id="r1") == \
        f"{prefix}/r1/intermediate_val_data"
    ckpt = store.get_checkpoint_path("r1")
    assert ckpt == f"{prefix}/r1/checkpoint"
    assert store.get_logs_path("r1") != ckpt
    # I/O round trip + recursive delete through the adapter.
    store.write(ckpt + "/model.bin", b"\x01\x02")
    assert store.exists(ckpt + "/model.bin") and store.exists(ckpt)
    assert store.read(ckpt + "/model.bin") == b"\x01\x02"
    store.delete(ckpt)
    assert not store.exists(ckpt)


def test_remote_store_requires_client_library():
    """Without an injected fs, each remote store must raise a clear
    ImportError naming the missing client (none are in the image)."""
    from horovod_tpu.spark import HDFSStore, S3Store
    with pytest.raises(ImportError, match="pyarrow"):
        HDFSStore("hdfs://nn/horovod")
    with pytest.raises(ImportError, match="boto3"):
        S3Store("s3://bucket/horovod")
    # Store.create dispatches schemes to the right classes.
    with pytest.raises(ImportError):
        Store.create("s3://bucket/horovod")
    with pytest.raises(ImportError):
        Store.create("hdfs://nn/horovod")


def test_spark_task_env_consistency():
    """Every task computes a consistent world from the same gang view."""
    from horovod_tpu.spark import _task_env
    addresses = ["nodeA:1001", "nodeA:1002", "nodeB:1003"]
    envs = [_task_env(i, addresses, port_seed=42, extra_env={"X": 1})
            for i in range(3)]
    assert [e["HOROVOD_RANK"] for e in envs] == ["0", "1", "2"]
    assert all(e["HOROVOD_SIZE"] == "3" for e in envs)
    assert [e["HOROVOD_LOCAL_RANK"] for e in envs] == ["0", "1", "0"]
    assert [e["HOROVOD_LOCAL_SIZE"] for e in envs] == ["2", "2", "1"]
    assert [e["HOROVOD_CROSS_RANK"] for e in envs] == ["0", "0", "1"]
    assert all(e["HOROVOD_CONTROLLER_ADDR"] == "nodeA" for e in envs)
    # Same seed -> same ports on every task; consecutive pair.
    ports = {(e["HOROVOD_CONTROLLER_PORT"], e["HOROVOD_CONTROLLER_PORT2"])
             for e in envs}
    assert len(ports) == 1
    assert all(e["X"] == "1" for e in envs)


def test_remote_ports_deterministic():
    from horovod_tpu.common.net import remote_ports
    assert remote_ports(2, 7) == remote_ports(2, 7)
    assert remote_ports(2, 7) != remote_ports(2, 8)
    p = remote_ports(3, 123)
    assert all(20000 <= x < 60000 for x in p)


# ---------------------------------------------------------------- estimator
class FakeRow(dict):
    pass


class FakeDataFrame:
    """Test double with the DataFrame API surface the estimator touches
    (reference test style: mock Spark, assert on behavior)."""

    def __init__(self, rows):
        self._rows = [FakeRow(r) for r in rows]

    def select(self, *cols):
        return FakeSelected([[r[c] for c in cols] for r in self._rows])

    def collect(self):
        return self._rows


class FakeSelected:
    def __init__(self, rows):
        self._rows = rows

    def collect(self):
        return self._rows


def _linear_df(n=64, noise=0.01, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5], np.float32)
    y = X @ w + noise * rng.randn(n).astype(np.float32)
    return FakeDataFrame(
        [{"f0": float(a), "f1": float(b), "f2": float(c), "label": float(t)}
         for (a, b, c), t in zip(X, y)])


def test_jax_estimator_fit_transform(hvd, tmp_path):
    """fit(df) materializes shards, trains through the coordinator, and
    returns a transformer (VERDICT missing #3 'done' criterion)."""
    import jax.numpy as jnp
    from horovod_tpu.spark import JaxEstimator, JaxModel, LocalStore

    def init_fn(rng, sample_x):
        return {"w": jnp.zeros((sample_x.shape[1],)), "b": jnp.zeros(())}

    def apply_fn(params, X):
        return X @ params["w"] + params["b"]

    def loss_fn(pred, y):
        return (pred - y.reshape(pred.shape)) ** 2

    store = LocalStore(str(tmp_path))
    est = JaxEstimator(init_fn=init_fn, apply_fn=apply_fn, loss_fn=loss_fn,
                       feature_cols=["f0", "f1", "f2"], label_cols=["label"],
                       store=store, epochs=30, batch_size=16,
                       learning_rate=0.1, run_id="jaxrun")
    model = est.fit(_linear_df())
    assert isinstance(model, JaxModel)
    # learned ≈ the generating weights
    np.testing.assert_allclose(np.asarray(model.params["w"]),
                               [1.0, -2.0, 0.5], atol=0.1)
    # materialization used the reference Store layout
    assert store.exists(store.get_train_data_path(0, run_id="jaxrun"))
    assert store.exists(store.get_checkpoint_path("jaxrun"))
    # transform appends the prediction column
    out = model.transform(_linear_df(n=8))
    assert len(out) == 8 and all("prediction" in r for r in out)
    preds = model.predict(np.array([[1.0, 0.0, 0.0]], np.float32))
    assert abs(float(preds[0]) - 1.0) < 0.2


def test_torch_estimator_fit_transform(hvd, tmp_path):
    import torch
    from horovod_tpu.spark import LocalStore, TorchEstimator, TorchModel

    def model_factory():
        return torch.nn.Linear(3, 1)

    store = LocalStore(str(tmp_path))
    est = TorchEstimator(model_factory=model_factory,
                         loss=lambda p, t: torch.nn.functional.mse_loss(
                             p, t.reshape(p.shape)),
                         feature_cols=["f0", "f1", "f2"],
                         label_cols=["label"], store=store, epochs=30,
                         batch_size=16, learning_rate=0.1, run_id="torchrun")
    model = est.fit(_linear_df())
    assert isinstance(model, TorchModel)
    w = model.params["weight"].numpy().reshape(-1)
    np.testing.assert_allclose(w, [1.0, -2.0, 0.5], atol=0.15)
    out = model.transform(_linear_df(n=5))
    assert len(out) == 5 and all("prediction" in r for r in out)


def test_keras_estimator_fit_transform(hvd, tmp_path):
    import keras
    from horovod_tpu.spark import KerasEstimator, KerasModel, LocalStore

    def model_factory():
        return keras.Sequential([keras.layers.Input((3,)),
                                 keras.layers.Dense(1, use_bias=False)])

    est = KerasEstimator(model_factory=model_factory, loss="mse",
                         feature_cols=["f0", "f1", "f2"],
                         label_cols=["label"],
                         store=LocalStore(str(tmp_path)), epochs=30,
                         batch_size=16, learning_rate=0.1, run_id="kerasrun")
    model = est.fit(_linear_df())
    assert isinstance(model, KerasModel)
    w = np.asarray(model.params[0]).reshape(-1)
    np.testing.assert_allclose(w, [1.0, -2.0, 0.5], atol=0.15)
    out = model.transform(_linear_df(n=5))
    assert len(out) == 5 and all("prediction" in r for r in out)
    preds = model.predict(np.array([[1.0, 0.0, 0.0]], np.float32))
    assert abs(float(preds.reshape(-1)[0]) - 1.0) < 0.2


def test_estimator_empty_df_raises(hvd, tmp_path):
    from horovod_tpu.spark import JaxEstimator, LocalStore
    est = JaxEstimator(init_fn=lambda r, x: {}, apply_fn=lambda p, X: X,
                       loss_fn=lambda p, y: p,
                       feature_cols=["f0"], label_cols=["label"],
                       store=LocalStore(str(tmp_path)))
    with pytest.raises(ValueError, match="empty"):
        est.fit(FakeDataFrame([]))


# ------------------------------------------------------------- ray elastic
class FakeRef:
    """Stands in for a Ray ObjectRef: completes (ok or failed) on demand."""

    def __init__(self):
        self.done = False
        self.failed = False


class FakeActor:
    def __init__(self):
        self.killed = False


class FakeRay:
    """The slice of the Ray API the elastic executor touches (reference
    tests elastic_v2 against mock clusters the same way)."""

    def __init__(self, nodes):
        self._nodes = nodes
        self.actors = []        # (actor, ref, env) in spawn order

    def nodes(self):
        return [dict(n) for n in self._nodes]

    def wait(self, refs, timeout=0):
        (ref,) = refs
        return ([ref] if ref.done else []), ([] if ref.done else [ref])

    def get(self, ref):
        if ref.failed:
            raise RuntimeError("actor died")
        return "ok"

    def kill(self, actor):
        actor.killed = True


def _fake_make_actor(executor, fake_ray):
    from horovod_tpu.ray.elastic import _ActorProc

    def make(hostname, env):
        actor, ref = FakeActor(), FakeRef()
        fake_ray.actors.append((actor, ref, dict(env), hostname))
        return _ActorProc(fake_ray, actor, ref)

    executor._make_actor = make


def test_ray_host_discovery_nodes_to_hosts():
    from horovod_tpu.ray import RayHostDiscovery
    fake = FakeRay([
        {"Alive": True, "NodeManagerAddress": "10.0.0.1",
         "Resources": {"CPU": 8, "TPU": 4}},
        {"Alive": True, "NodeManagerAddress": "10.0.0.2",
         "Resources": {"CPU": 8}},
        {"Alive": False, "NodeManagerAddress": "10.0.0.3",
         "Resources": {"CPU": 8, "TPU": 4}},
    ])
    d = RayHostDiscovery(use_accelerators=True, cpus_per_worker=2,
                         ray_api=fake)
    hosts = d.find_available_hosts_and_slots()
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("10.0.0.1", 4), ("10.0.0.2", 4)]   # dead node excluded; cpu fallback


def test_ray_elastic_actor_death_resumes_reduced_world():
    """VERDICT missing #7 'done' criterion (mock cluster): kill an actor
    mid-run -> its node is blacklisted, the world re-forms at reduced size,
    and training completes."""
    import threading
    import time
    from horovod_tpu.ray import ElasticRayExecutor, RayHostDiscovery

    fake = FakeRay([
        {"Alive": True, "NodeManagerAddress": "nodeA",
         "Resources": {"CPU": 1}},
        {"Alive": True, "NodeManagerAddress": "nodeB",
         "Resources": {"CPU": 1}},
    ])
    ex = ElasticRayExecutor(min_workers=1, use_accelerators=False,
                            discovery_interval_s=0.05,
                            start_timeout_s=20, _ray_api=fake)
    _fake_make_actor(ex, fake)
    rc = {}
    t = threading.Thread(target=lambda: rc.setdefault(
        "rc", ex.run(lambda: "trained")), daemon=True)
    t.start()

    # Wait for the first generation's 2 actors.
    deadline = time.monotonic() + 10
    while len(fake.actors) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(fake.actors) == 2, fake.actors
    first_hosts = {a[3] for a in fake.actors}
    assert first_hosts == {"nodeA", "nodeB"}

    # Kill nodeB's actor: ref fails -> blacklist -> reduced regeneration.
    victim = next(a for a in fake.actors if a[3] == "nodeB")
    victim[1].failed = True
    victim[1].done = True

    deadline = time.monotonic() + 10
    while len(fake.actors) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    # The regenerated world must exclude the blacklisted node.
    new = fake.actors[2:]
    assert new and all(a[3] == "nodeA" for a in new), fake.actors
    assert all(a[2]["HOROVOD_SIZE"] == "1" for a in new), \
        [a[2] for a in new]

    # Surviving actor finishes -> run() returns success.
    for a in new:
        a[1].done = True
    surviving = fake.actors[0]
    surviving[1].done = True
    t.join(timeout=15)
    assert rc.get("rc") == 0, rc


def test_ray_elastic_coordinator_host_death_moves_world():
    """Variant killing the *coordinator-adjacent* actor (host 0 carries the
    controller): the world must re-form on the surviving host with the
    controller address moved off the blacklisted node."""
    import threading
    import time
    from horovod_tpu.ray import ElasticRayExecutor

    fake = FakeRay([
        {"Alive": True, "NodeManagerAddress": "nodeA",
         "Resources": {"CPU": 1}},
        {"Alive": True, "NodeManagerAddress": "nodeB",
         "Resources": {"CPU": 1}},
    ])
    ex = ElasticRayExecutor(min_workers=1, use_accelerators=False,
                            discovery_interval_s=0.05,
                            start_timeout_s=20, _ray_api=fake)
    _fake_make_actor(ex, fake)
    rc = {}
    t = threading.Thread(target=lambda: rc.setdefault(
        "rc", ex.run(lambda: "trained")), daemon=True)
    t.start()

    deadline = time.monotonic() + 10
    while len(fake.actors) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(fake.actors) == 2, fake.actors

    # Kill host 0's actor (rank 0 / controller host).
    victim = next(a for a in fake.actors if a[3] == "nodeA")
    victim[1].failed = True
    victim[1].done = True

    deadline = time.monotonic() + 10
    while len(fake.actors) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    new = fake.actors[2:]
    assert new and all(a[3] == "nodeB" for a in new), fake.actors
    assert all(a[2]["HOROVOD_SIZE"] == "1" for a in new)
    assert all(a[2]["HOROVOD_RANK"] == "0" for a in new)
    # The controller no longer lives on the blacklisted host.
    assert all(a[2]["HOROVOD_CONTROLLER_ADDR"] == "nodeB" for a in new)

    for a in new:
        a[1].done = True
    t.join(timeout=15)
    assert rc.get("rc") == 0, rc


def test_ray_elastic_requires_ray_without_fake():
    from horovod_tpu.ray import ElasticRayExecutor
    ex = ElasticRayExecutor(min_workers=1)
    with pytest.raises(ImportError, match="ray"):
        ex.start()
