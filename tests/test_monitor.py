"""Cross-rank telemetry & health subsystem (tier-1, no jax in the core).

Covers the jax-free monitor package (registry, aggregator, agent, HTTP
exporter, CLI), the coordinator monitor side-channel end-to-end through
the real native server, the steady-state frame guard WITH monitoring
enabled (metrics frames must never ride the per-tensor metadata path),
the sanitizer content-hash mode, HVD302 peer-ledger enrichment, and the
fast-tier purity guard: ``horovod_tpu/monitor`` and ``ops/scheduler``
import with jax blocked.
"""

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.common.controller import TCPController
from horovod_tpu.monitor import (
    Counter, Gauge, Histogram, MetricRegistry, MonitorAgent, RankAggregator,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- registry
def test_registry_counter_gauge_histogram():
    reg = MetricRegistry()
    c = reg.counter("hvd_things_total", "things")
    c.inc()
    c.inc(4)
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("hvd_depth")
    g.set(7)
    g.dec(2)
    h = reg.histogram("hvd_lat_us", buckets=(10.0, 100.0))
    for v in (5, 50, 500):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["hvd_things_total"] == 5
    assert snap["hvd_depth"] == 5
    assert snap["hvd_lat_us"]["count"] == 3
    assert snap["hvd_lat_us"]["sum"] == 555
    assert snap["hvd_lat_us"]["buckets"] == {10.0: 1, 100.0: 2}
    # Same name returns the same handle; a kind conflict raises.
    assert reg.counter("hvd_things_total") is c
    with pytest.raises(TypeError):
        reg.gauge("hvd_things_total")


def test_registry_counter_set_total_never_regresses():
    c = MetricRegistry().counter("x")
    c.set_total(10)
    c.set_total(7)          # mirrored external totals never move backwards
    assert c.value == 10


def test_registry_prometheus_rendering():
    reg = MetricRegistry()
    reg.counter("hvd_cycles_total", "cycles run").inc(3)
    reg.gauge("weird name-with.chars").set(1.5)
    reg.histogram("hvd_lat_us", buckets=(10.0,)).observe(4)
    text = reg.to_prometheus('rank="2"')
    assert '# TYPE hvd_cycles_total counter' in text
    assert 'hvd_cycles_total{rank="2"} 3' in text
    assert 'weird_name_with_chars{rank="2"} 1.5' in text
    assert 'hvd_lat_us_bucket{rank="2",le="10"} 1' in text
    assert 'hvd_lat_us_count{rank="2"} 1' in text
    # Unlabelled rendering stays valid exposition format too.
    assert "hvd_cycles_total 3" in reg.to_prometheus()


def test_registry_collectors_run_at_snapshot_and_never_raise():
    reg = MetricRegistry()
    reg.register_collector(lambda r: r.gauge("live").set(42))

    def bad(r):
        raise RuntimeError("collector bug")
    reg.register_collector(bad)
    assert reg.snapshot()["live"] == 42


# -------------------------------------------------------------- aggregator
def test_aggregator_skew_and_health():
    agg = RankAggregator(world=3)
    agg.update(0, {"cycle_us_avg": 100.0, "cycle": 10,
                   "last_cycle_age_s": 0.1, "stalled": []})
    agg.update(1, {"cycle_us_avg": 900.0, "cycle": 10,
                   "last_cycle_age_s": 0.1, "stalled": ["grad.3"],
                   "ledger": ["#7 grad.3 [...] at train.py:12"]})
    skew = agg.skew()
    assert skew["slowest_rank"] == 1
    assert skew["cycle_us_spread"] == 800.0
    health = agg.health(interval_s=5.0)
    assert health["status"] == "stalled"          # rank 1 reports a stall
    assert health["ranks"]["1"]["stalled"] == ["grad.3"]
    assert health["ranks"]["2"]["alive"] is False  # never reported
    tails = agg.peer_ledger_tails(exclude_rank=0)
    assert 1 in tails and "grad.3" in tails[1][0]
    agg.flush()
    assert agg.ranks() == [] and agg.flushes == 1


def test_aggregator_health_ok_and_degraded():
    agg = RankAggregator(world=2)
    agg.update(0, {"stalled": []})
    assert agg.health(5.0)["status"] == "degraded"   # rank 1 missing
    agg.update(1, {"stalled": []})
    assert agg.health(5.0)["status"] == "ok"


# ---------------------------------------------------- controller side-channel
def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class E:
    def __init__(self, name, shape=(4,)):
        self.name = name
        self.tensor = np.zeros((2,) + tuple(shape), np.float32)


class FakeEngine:
    """Duck-typed engine surface the MonitorAgent collectors read."""

    def __init__(self, cycle_us_avg=100.0):
        self.cycle_count = 10
        self.cycle_us_total = cycle_us_avg * 10
        self.last_cycle_ts = time.time()
        self._cycle_index = 10
        self.negotiation_us_total = 0.0
        self.negotiation_cycles = 0
        self.pipeline_chunks_total = 0
        self.pipeline_dispatches = 0
        self.monitor = None


def _pair(fn, cache_capacity=2048):
    port = _free_port()
    results, errors = {}, {}
    peer_done = threading.Event()

    def worker(rank):
        ctl = TCPController("127.0.0.1", port, rank=rank, world=2,
                            stall_warn_s=60.0,
                            cache_capacity=cache_capacity)
        try:
            results[rank] = fn(ctl, rank)
        except Exception as exc:  # noqa: BLE001 - surfaced by the assert
            errors[rank] = exc
        finally:
            if rank == 1:
                peer_done.set()
                ctl.shutdown()
            else:
                peer_done.wait(timeout=20)
                ctl.shutdown()

    t1 = threading.Thread(target=worker, args=(1,), daemon=True)
    t1.start()
    worker(0)
    t1.join(timeout=20)
    assert not errors, errors
    assert set(results) == {0, 1}, results
    return results


def _steps(ctl, make_entries, n_steps, max_rounds=20):
    orders = []
    for _ in range(n_steps):
        entries = list(make_entries())
        got = []
        for _round in range(max_rounds):
            if not entries:
                break
            ready, errs = ctl.negotiate(entries)
            assert not errs, errs
            got += [e.name for e in ready]
            entries = [e for e in entries if e.name not in set(got)]
        assert not entries, f"never ready: {[e.name for e in entries]}"
        orders.append(tuple(got))
    return orders


def test_monitor_frames_aggregate_across_ranks():
    """The tentpole wire path, no jax: two ranks' agents ship snapshots
    through the native coordinator; every rank's aggregation table ends up
    holding both ranks, and skew attribution names the slower one."""
    names = [f"grad.{i}" for i in range(6)]

    def fn(ctl, rank):
        eng = FakeEngine(cycle_us_avg=100.0 if rank == 0 else 900.0)
        agent = MonitorAgent(engine=eng, controller=ctl, rank=rank,
                             world=2, interval_s=0.05)
        mk = lambda: [E(n) for n in names]           # noqa: E731
        _steps(ctl, mk, 2)
        deadline = time.monotonic() + 10
        while (len(agent.aggregator.ranks()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.06)
            _steps(ctl, mk, 1)
        assert agent.aggregator.ranks() == [0, 1], agent.aggregator.table()
        skew = agent.aggregator.skew()
        assert skew["slowest_rank"] == 1, skew
        assert skew["cycle_us_spread"] == 800.0, skew
        assert ctl.peer_monitor_proto
        assert ctl.monitor_bytes_sent > 0
        assert agent.frames_received >= 2
        return True

    _pair(fn)


def test_frame_guard_holds_with_monitoring_enabled():
    """Acceptance guard: with a MonitorAgent attached, steady-state cycles
    still send ZERO per-tensor metadata, and the negotiation-critical
    bytes (total minus the separately-accounted monitor frames) stay the
    same fixed handful per cycle as with monitoring off."""
    names = [f"grad.{i}.with.a.long.parameter.path" for i in range(12)]

    def fn(ctl, rank):
        agent = MonitorAgent(engine=FakeEngine(), controller=ctl, rank=rank,
                             world=2, interval_s=0.05)
        mk = lambda: [E(n) for n in names]           # noqa: E731
        _steps(ctl, mk, 2)                           # warm-up: learn slots
        time.sleep(0.06)                             # arm the frame interval
        st = ctl.cache_stats
        full_before = st.full_announces
        bytes_before = ctl.bytes_sent
        mon_before = ctl.monitor_bytes_sent
        orders = _steps(ctl, mk, 5)
        assert st.full_announces == full_before, (
            "monitoring pushed steady-state cycles off the bitvector path")
        assert st.bit_announces >= 5 * len(names)
        mon_bytes = ctl.monitor_bytes_sent - mon_before
        assert mon_bytes > 0, "no monitor frame rode the measured window"
        per_cycle = (ctl.bytes_sent - bytes_before - mon_bytes) / 5
        assert per_cycle <= 16, per_cycle
        return orders

    res = _pair(fn)
    assert res[0] == res[1]


def test_monitor_source_errors_never_fail_negotiation():
    def fn(ctl, rank):
        def bomb():
            raise RuntimeError("telemetry bug")
        ctl.monitor_source = bomb
        orders = _steps(ctl, lambda: [E("t")], 3)
        return orders

    res = _pair(fn)
    assert res[0] == res[1]


# ------------------------------------------------------------ HTTP exporter
def test_http_exporter_metrics_health_snapshot():
    eng = FakeEngine()
    agent = MonitorAgent(engine=eng, rank=0, world=1, interval_s=0.1)
    srv = agent.serve_http(0)           # ephemeral port
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'hvd_cycles_total{rank="0"} 10' in text
        assert "hvd_rank_alive" in text
        health = json.loads(urllib.request.urlopen(base + "/health").read())
        assert health["status"] == "ok" and health["world"] == 1
        assert health["ranks"]["0"]["alive"] is True
        snap = json.loads(urllib.request.urlopen(base + "/snapshot").read())
        assert "0" in snap["table"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope")
        assert ei.value.code == 404
    finally:
        agent.close()


def test_http_exporter_carries_zero_rtt_counters():
    """ISSUE 11 observability: with a real controller attached, /metrics
    exports the speculation outcome counters and the in-flight round
    gauges alongside the response-cache family."""

    def fn(ctl, rank):
        _steps(ctl, lambda: [E("t")], 3)
        if rank != 0:
            return True
        agent = MonitorAgent(engine=FakeEngine(), controller=ctl,
                             rank=0, world=2, interval_s=0.1)
        srv = agent.serve_http(0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            text = urllib.request.urlopen(base + "/metrics").read().decode()
            for name in ("hvd_spec_hits_total", "hvd_spec_mispredicts_total",
                         "hvd_spec_rounds_total", "hvd_inflight_rounds",
                         "hvd_inflight_rounds_high_water",
                         "hvd_response_cache_hits_total"):
                assert name in text, name
        finally:
            agent.close()
        return True

    _pair(fn)


def test_http_health_returns_503_when_stalled():
    # The stall is on a PEER rank: the agent refreshes its own entry on
    # every /health render, so self-seeded state would be overwritten.
    agent = MonitorAgent(rank=0, world=2, interval_s=0.1)
    agent.aggregator.update(1, {"stalled": ["grad.0"]})
    srv = agent.serve_http(0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health")
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["status"] == "stalled"
    finally:
        agent.close()


# --------------------------------------------------------------------- CLI
def test_cli_renders_dump(tmp_path, capsys):
    from horovod_tpu.monitor.__main__ import main
    dump = {
        "rank": 0, "world": 2,
        "health": {"status": "stalled", "world": 2,
                   "monitor_interval_s": 5.0, "slowest_rank": 1,
                   "cycle_us_spread": 800.0,
                   "ranks": {"0": {"alive": True, "last_seen_s": 0.2,
                                   "cycle": 12, "last_cycle_age_s": 0.1,
                                   "stalled": ["grad.0"]},
                             "1": {"alive": False, "last_seen_s": None,
                                   "cycle": None, "last_cycle_age_s": None,
                                   "stalled": []}}},
        "table": {"1": {"ledger": ["#7 grad.0 [...] at train.py:12"],
                        "metrics": {"hvd_stalled_collectives": 0}}},
    }
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(dump))
    assert main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "fleet status: STALLED" in out
    assert "slowest rank 1" in out
    assert "grad.0" in out and "train.py:12" in out
    # Raw mode round-trips the JSON.
    assert main([str(path), "--json"]) == 0
    assert json.loads(capsys.readouterr().out) == dump


def test_cli_rejects_bad_usage(tmp_path):
    from horovod_tpu.monitor.__main__ import main
    with pytest.raises(SystemExit):
        main([])                        # neither file nor --url
    assert main([str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------- sanitizer hash mode
def test_sanitizer_content_hash_tags():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    class Entry:
        def __init__(self, name, value):
            self.name = name
            self.tensor = np.full((4,), value, np.float32)
            self.process_set_id = 0

    a0 = Entry("t", 1.0)
    a1 = Entry("t", 1.0)
    b = Entry("t", 2.0)
    san = CollectiveSanitizer(content_hash=True)
    san.observe([a0], site="train.py:10")
    san.observe([a1], site="train.py:10")
    san.observe([b], site="train.py:10")
    h0 = a0.sanitizer_tag.split(";h=")[1]
    h1 = a1.sanitizer_tag.split(";h=")[1]
    hb = b.sanitizer_tag.split(";h=")[1]
    assert h0 == h1, "identical content must hash identically"
    assert h0 != hb, "divergent content must hash differently"
    # Barriers (no tensor) carry no hash field but still tag seq/site.
    class Barrier:
        name = "b"
        tensor = None
        process_set_id = 0
    bar = Barrier()
    san.observe([bar], site="train.py:11")
    assert ";h=" not in bar.sanitizer_tag
    assert bar.sanitizer_tag.startswith("seq=0:3")


def test_sanitizer_hash_mode_rollback_still_works():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    class Entry:
        def __init__(self, name):
            self.name = name
            self.tensor = np.ones((2,), np.float32)
            self.process_set_id = 0

    san = CollectiveSanitizer(content_hash=True)
    e = Entry("dup")
    san.observe([e], site="train.py:10")
    assert san._seq[0] == 1
    san.rollback([e])
    assert san._seq[0] == 0 and len(san.ledger) == 0


def test_mode_parsing(monkeypatch):
    from horovod_tpu.analysis import runtime_sanitizer as rts
    monkeypatch.delenv("HVD_TPU_SANITIZER", raising=False)
    assert rts.mode() is None and not rts.enabled()
    monkeypatch.setenv("HVD_TPU_SANITIZER", "1")
    assert rts.mode() == "tag" and rts.enabled()
    monkeypatch.setenv("HVD_TPU_SANITIZER", "hash")
    assert rts.mode() == "hash" and rts.enabled()
    monkeypatch.setenv("HVD_TPU_SANITIZER", "0")
    assert rts.mode() is None


# ------------------------------------------------- HVD302 peer-ledger path
def test_hvd302_report_includes_peer_ledger_tail():
    from horovod_tpu.analysis.runtime_sanitizer import (
        CollectiveSanitizer, SanitizerStallInspector)
    from horovod_tpu.ops.scheduler import StallInspector
    from horovod_tpu.utils.logging import get_logger

    inner = StallInspector(warn_after_s=0.01, shutdown_after_s=0)
    san = CollectiveSanitizer()
    insp = SanitizerStallInspector(inner, san, warn_after_s=0.01)
    agent = MonitorAgent(rank=0, world=2, interval_s=0.1)
    agent.aggregator.update(
        1, {"ledger": ["#41 grad.7 [allreduce|float32|(4,)|SUM] "
                       "at laggard.py:99"]})
    insp.peer_ledger_source = agent.peer_ledger_report

    class W:
        name = "stuck.t"
        enqueue_time = time.monotonic() - 1.0
        sanitizer_tag = "seq=0:5;site=train.py:30"

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    log = get_logger()
    log.addHandler(handler)
    try:
        insp.check([W()])
    finally:
        log.removeHandler(handler)
    msgs = [m for m in records if "HVD302" in m]
    assert msgs, records
    assert "peer ledgers" in msgs[0], msgs[0]
    assert "rank 1 last submissions" in msgs[0]
    assert "laggard.py:99" in msgs[0]
    # Live stall state (the /health export) reflects and then clears.
    assert "stuck.t" in insp.stalled
    insp.progressed("stuck.t")
    assert "stuck.t" not in insp.stalled


# ------------------------------------------------------------ purity guard
_PURITY_SRC = r"""
import importlib, os, sys, types

class BlockJax:
    def find_spec(self, name, path=None, target=None):
        if name.split('.')[0] in ('jax', 'jaxlib'):
            raise ImportError('tier-1 purity: %s must not import jax'
                              % name)
        return None

sys.meta_path.insert(0, BlockJax())
root = sys.argv[1]
# Shell parent packages: real submodules load from disk, but the real
# horovod_tpu/__init__.py (which imports jax) never runs.
for name, sub in (('horovod_tpu', ''), ('horovod_tpu.ops', 'ops'),
                  ('horovod_tpu.utils', 'utils'),
                  ('horovod_tpu.common', 'common'),
                  ('horovod_tpu.analysis', 'analysis'),
                  ('horovod_tpu.parallel', 'parallel')):
    m = types.ModuleType(name)
    m.__path__ = [os.path.join(root, sub)] if sub else [root]
    sys.modules[name] = m
importlib.import_module('horovod_tpu.ops.scheduler')
importlib.import_module('horovod_tpu.monitor')
importlib.import_module('horovod_tpu.monitor.__main__')
importlib.import_module('horovod_tpu.monitor.http')
importlib.import_module('horovod_tpu.analysis.findings')
# Slice topology (ISSUE 17): derives the two-level (cross, local) mesh
# structure for the engine but is itself pure Python — the analyzer and
# bench model wire bytes with it from the jax-free tier.
topo = importlib.import_module('horovod_tpu.parallel.topology')
st = topo.slice_topology(None, world=8, slice_map='4')
assert st.num_slices == 2 and st.leaders == (0, 4), st
assert topo.hier_bit_orders(4, 2) == ([0, 1], [0])
legs = topo.modeled_leg_bytes(1 << 20, 8, 4)
assert legs['cross'] <= legs['flat'] / 4, legs
# Per-process-set sanitizer namespace (ISSUE 16): the ledger recorder
# must import AND keep per-set books correctly with jax hard-blocked —
# it runs in launcher-adjacent tooling and the jax-free test tier.
rs = importlib.import_module('horovod_tpu.analysis.runtime_sanitizer')
san = rs.CollectiveSanitizer(capacity=4)
class _E:
    def __init__(self, name, ps):
        self.name = name
        self.tensor = None
        self.process_set_id = ps
a, b, c = _E('w', 0), _E('t', 7), _E('w2', 0)
san.observe([a], site='x.py:1')
san.observe([b], site='x.py:2')
san.observe([c], site='x.py:3')
assert a.sanitizer_tag.startswith('seq=0:0;'), a.sanitizer_tag
assert b.sanitizer_tag.startswith('seq=7:0;'), b.sanitizer_tag
assert c.sanitizer_tag.startswith('seq=0:1;'), c.sanitizer_tag
assert [e.name for e in san.tail(process_set=7)] == ['t']
assert [e.name for e in san.tail()] == ['w', 't', 'w2']
assert 'process set 7' in san.render_tail(process_set=7)
# Distributed tracing: the span core, the merge/analyze halves and the CLI
# must run standalone (operators merge traces on machines without jax).
importlib.import_module('horovod_tpu.trace')
importlib.import_module('horovod_tpu.trace.merge')
importlib.import_module('horovod_tpu.trace.analyze')
importlib.import_module('horovod_tpu.trace.__main__')
# Control-plane fault tolerance: the harness and the typed error taxonomy
# carry the jax-free fault tests and the acceptance workers' arming path.
importlib.import_module('horovod_tpu.testing')
importlib.import_module('horovod_tpu.testing.faults')
# Churn-scenario runner (ISSUE 12): drives simulated worlds + HostAgents
# against the native server from the jax-free test tier and the bench.
importlib.import_module('horovod_tpu.testing.churn')
importlib.import_module('horovod_tpu.common.exceptions')
importlib.import_module('horovod_tpu.common.net')
# Hierarchical control plane: the per-host aggregation agent runs in
# launcher-adjacent processes and the jax-free negotiation test tier.
importlib.import_module('horovod_tpu.common.host_agent')
# Closed-loop autoscaling: the REAL elastic package surface (state objects
# load lazily via PEP 562), the policy engine, the elastic driver (which
# hosts it) and the worker notification layer all run in the LAUNCHER
# process and the synthetic-load acceptance workers — none may drag jax
# in.  NB: horovod_tpu.elastic is imported for real, not shelled — the
# lazy __init__ IS the thing under test.
importlib.import_module('horovod_tpu.elastic')
importlib.import_module('horovod_tpu.elastic.autoscale')
importlib.import_module('horovod_tpu.elastic.driver')
importlib.import_module('horovod_tpu.elastic.worker')
importlib.import_module('horovod_tpu.elastic.rendezvous')
# Resilient state plane (ISSUE 14): sharded checkpoint writes + the
# peer-to-peer restore path run in the jax-free acceptance workers, the
# churn runner and the bench — and the chunk items it hands the engine
# come from the (already covered) jax-free ops/scheduler.
importlib.import_module('horovod_tpu.elastic.stateplane')
# Serving plane (ISSUE 19): the REAL serve package surface (the Replica
# loads lazily via PEP 562 — the lazy __init__ IS the thing under test),
# plus a behavioral pass through the continuous batcher: admission,
# padded-bucket formation, deadline expiry, backpressure.
serve = importlib.import_module('horovod_tpu.serve')
importlib.import_module('horovod_tpu.serve.batcher')
importlib.import_module('horovod_tpu.serve.frontdoor')
clock = [0.0]
bt = serve.ContinuousBatcher(max_batch=4, deadline_ms=100.0,
                             max_inflight=1, queue_depth=3,
                             clock=lambda: clock[0])
r1 = bt.submit([1]); r2 = bt.submit([2]); r3 = bt.submit([3])
try:
    bt.submit([4])
    raise AssertionError('queue_depth=3 admitted a 4th request')
except serve.QueueFull:
    pass
batch = bt.next_batch(timeout=0.0)
assert batch.size == 3 and batch.bucket == 4, (batch.size, batch.bucket)
assert bt.next_batch(timeout=0.0) is None      # in-flight window full
bt.complete(batch, [[10], [20], [30]])
assert r1.wait(0.0) == [10] and r3.wait(0.0) == [30]
r4 = bt.submit([5])
clock[0] = 1.0                                  # past the 100ms deadline
assert bt.next_batch(timeout=0.0) is None
try:
    r4.wait(0.0)
    raise AssertionError('expired request returned a result')
except serve.DeadlineExceeded:
    pass
assert serve.parse_buckets('2,4', 8) == (2, 4, 8)
# Serving fault tolerance (ISSUE 20): the resilience module and the
# retry/breaker/quarantine surface are all front-door-side — jax-free by
# construction — and the behavioral pass walks the breaker state machine
# plus the retryable/terminal error taxonomy.
importlib.import_module('horovod_tpu.serve.resilience')
br = serve.CircuitBreaker(threshold=2, reset_s=5.0, probes=1,
                          clock=lambda: clock[0])
assert br.allow() and br.state == 'closed'
br.record_failure(); br.record_failure()
assert br.state == 'open' and not br.allow()
clock[0] += 5.0
assert br.allow() and br.state == 'half_open'
br.record_success()
assert br.state == 'closed'
assert issubclass(serve.ReplicaFaulted, serve.Retryable)
assert issubclass(serve.ForwardFailed, serve.Retryable)
assert not issubclass(serve.RequestQuarantined, serve.Retryable)
bq = serve.ContinuousBatcher(max_batch=1, deadline_ms=1000.0,
                             quarantine_after=2, clock=lambda: clock[0])
assert bq.submit([1], request_id='a') is bq.submit([1], request_id='a')
bq.fail(bq.next_batch(timeout=0.0), RuntimeError('x'))
bq.submit([1], request_id='a')
bq.fail(bq.next_batch(timeout=0.0), RuntimeError('x'))
assert bq.stats()['quarantined_total'] == 1
print('PURITY_OK')
"""


def test_monitor_and_scheduler_import_without_jax():
    """Fast-tier purity: the monitor package, ops/scheduler.py, the trace
    package, the fault-injection harness (horovod_tpu/testing) and the
    control-plane exception taxonomy must be importable with jax imports
    hard-blocked — they carry the jax-free unit-test tier and the
    standalone CLIs."""
    res = subprocess.run(
        [sys.executable, "-c", _PURITY_SRC,
         os.path.join(REPO, "horovod_tpu")],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0 and "PURITY_OK" in res.stdout, (
        f"rc={res.returncode}\nstdout:\n{res.stdout}\n"
        f"stderr:\n{res.stderr}")
