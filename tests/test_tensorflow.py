"""TF/Keras binding tests (single-controller tier).

Models the reference's ``test/parallel/test_tensorflow.py`` +
``test_tensorflow2_keras.py`` assertions (SURVEY.md §4) in the hermetic
8-virtual-rank harness: single-controller mode submits the same tensor for
every rank, so AVERAGE is the identity and SUM multiplies by size().
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")


@pytest.fixture()
def tfhvd(hvd):
    import horovod_tpu.tensorflow as tfhvd
    return tfhvd


def test_allreduce(tfhvd):
    w = tfhvd.size()
    t = tf.constant([1.0, 2.0, 3.0])
    out = tfhvd.allreduce(t, name="tf_ar", op=tfhvd.Sum)
    assert isinstance(out, tf.Tensor) and out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), np.array([1, 2, 3.0]) * w)
    out = tfhvd.allreduce(t, name="tf_ar_avg", op=tfhvd.Average)
    np.testing.assert_allclose(out.numpy(), [1, 2, 3.0])


def test_allreduce_compression_fp16(tfhvd):
    t = tf.constant(np.linspace(-2, 2, 8, dtype=np.float32))
    out = tfhvd.allreduce(t, name="tf_ar_c", op=tfhvd.Average,
                          compression=tfhvd.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=2e-3)


def test_grouped_allreduce(tfhvd):
    w = tfhvd.size()
    outs = tfhvd.grouped_allreduce(
        [tf.ones([2, 3]), tf.constant([4.0, 5.0])], name="tf_grp",
        op=tfhvd.Sum)
    np.testing.assert_allclose(outs[0].numpy(), np.ones((2, 3)) * w)
    np.testing.assert_allclose(outs[1].numpy(), np.array([4.0, 5.0]) * w)


def test_allgather_broadcast(tfhvd):
    w = tfhvd.size()
    out = tfhvd.allgather(tf.ones([2, 3]), name="tf_ag")
    assert out.shape == (2 * w, 3)
    out = tfhvd.broadcast(tf.constant([7.0, 8.0]), root_rank=0, name="tf_bc")
    np.testing.assert_allclose(out.numpy(), [7.0, 8.0])


def test_alltoall_even_and_ragged(tfhvd):
    w = tfhvd.size()
    t = tf.reshape(tf.range(w * 2, dtype=tf.float32), (w, 2))
    out = tfhvd.alltoall(t, name="tf_a2a")
    # identical contributions: this rank receives everyone's chunk r.
    r = tfhvd.rank()
    np.testing.assert_allclose(out.numpy(),
                               np.tile(t.numpy()[r:r + 1], (w, 1)))
    splits = tf.constant([j + 1 for j in range(w)])
    n = int(sum(j + 1 for j in range(w)))
    tr = tf.reshape(tf.range(n, dtype=tf.float32), (n, 1))
    out, rsp = tfhvd.alltoall(tr, splits=splits, name="tf_a2av")
    assert rsp.numpy().tolist() == [r + 1] * w
    off = sum(j + 1 for j in range(r))
    chunk = tr.numpy()[off:off + r + 1]
    np.testing.assert_allclose(out.numpy(), np.tile(chunk, (w, 1)))


def test_reducescatter(tfhvd):
    w = tfhvd.size()
    t = tf.ones([2 * w, 3])
    out = tfhvd.reducescatter(t, name="tf_rs", op=tfhvd.Sum)
    np.testing.assert_allclose(out.numpy(), np.ones((2, 3)) * w)


def test_distributed_gradient_tape(tfhvd):
    x = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(x * x)
    tape = tfhvd.DistributedGradientTape(tape)
    (grad,) = tape.gradient(loss, [x])
    # identical per-rank grads: average == local value 2x.
    np.testing.assert_allclose(grad.numpy(), [2.0, 4.0])


def test_distributed_optimizer_apply(tfhvd):
    opt = tfhvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.5))
    assert isinstance(opt, keras.optimizers.SGD)  # dynamic subclass
    v = tf.Variable([1.0, 1.0])
    opt.apply_gradients([(tf.constant([0.2, 0.4]), v)])
    np.testing.assert_allclose(v.numpy(), [0.9, 0.8], rtol=1e-6)


def test_distributed_optimizer_bpps_aggregates(tfhvd):
    """backward_passes_per_step=2: the first apply must not touch weights;
    the second must apply the micro-batch average — identical to one
    bpps=1 step on the pre-averaged gradient (VERDICT r2 #5)."""
    opt2 = tfhvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.5), backward_passes_per_step=2)
    opt1 = tfhvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.5))
    va = tf.Variable([1.0, -1.0])
    vb = tf.Variable([1.0, -1.0])
    g1 = tf.constant([0.1, 0.2])
    g2 = tf.constant([0.3, -0.1])

    opt2.apply_gradients([(g1, va)])
    np.testing.assert_allclose(va.numpy(), [1.0, -1.0])  # aggregated only
    opt2.apply_gradients([(g2, va)])
    opt1.apply_gradients([((g1 + g2) / 2.0, vb)])
    np.testing.assert_allclose(va.numpy(), vb.numpy(), rtol=1e-6)

    # A second aggregation window behaves identically (buffers were reset).
    opt2.apply_gradients([(g1, va)])
    np.testing.assert_allclose(va.numpy(), vb.numpy(), rtol=1e-6)
    opt2.apply_gradients([(g2, va)])
    opt1.apply_gradients([((g1 + g2) / 2.0, vb)])
    np.testing.assert_allclose(va.numpy(), vb.numpy(), rtol=1e-6)


def test_distributed_optimizer_bpps_none_grads_skip_var(tfhvd):
    """A var whose gradient stays None for the whole window must receive
    None at the boundary (not an explicit zero), matching bpps=1 so frozen
    branches are untouched by decay-style updates."""
    opt = tfhvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.5), backward_passes_per_step=2)
    live = tf.Variable([1.0])
    frozen = tf.Variable([2.0])
    g = tf.constant([0.2])
    opt.apply_gradients([(g, live), (None, frozen)])
    opt.apply_gradients([(g, live), (None, frozen)])
    np.testing.assert_allclose(live.numpy(), [0.9], rtol=1e-6)
    np.testing.assert_allclose(frozen.numpy(), [2.0])   # never touched


def test_broadcast_variables(tfhvd):
    v = tf.Variable([5.0, 6.0])
    tfhvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [5.0, 6.0])


def test_keras_fit_end_to_end(tfhvd):
    """Keras model.fit with the horovod optimizer + callbacks: the compiled
    train step reduces via py_function; loss decreases; callbacks attach."""
    import horovod_tpu.keras as khvd
    from horovod_tpu.keras import callbacks as kcb

    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = X @ true_w + 0.01 * rng.randn(64, 1).astype(np.float32)

    model = keras.Sequential([keras.layers.Dense(1, use_bias=False)])
    opt = khvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.05))
    model.compile(optimizer=opt, loss="mse")
    hist = model.fit(
        X, y, batch_size=16, epochs=3, verbose=0,
        callbacks=[kcb.BroadcastGlobalVariablesCallback(0),
                   kcb.MetricAverageCallback(),
                   kcb.LearningRateWarmupCallback(
                       initial_lr=0.05, warmup_epochs=2,
                       momentum_correction=False)])
    losses = hist.history["loss"]
    assert losses[-1] < losses[0] * 0.5, losses
    # warmup took LR toward initial_lr * size() during epochs 0-1
    final_lr = float(model.optimizer.learning_rate.numpy())
    assert final_lr == pytest.approx(0.05 * tfhvd.size(), rel=1e-5)
