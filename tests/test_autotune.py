"""Autotune (parameter manager) tests — reference test_autotune.py analogue.

Unit tier drives ParameterManager with a fake engine and injected clock;
the integration tier runs a real HOROVOD_AUTOTUNE=1 engine over many eager
allreduces and asserts tuning converges and collectives stay correct.
"""

import os

import numpy as np
import pytest

from horovod_tpu.ops.autotune import ParameterManager


class FakeEngine:
    def __init__(self):
        self.fusion_threshold = 64 * 1024 * 1024
        self.cycle_time_s = 0.001


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive_sample(pm, clock, nbytes, dt):
    """One full sample window: steps_per_sample work cycles of dt seconds."""
    for _ in range(pm._steps_per_sample):
        clock.t += dt
        pm.on_cycle(nbytes)


def test_parameter_manager_explores_and_picks_best(tmp_path, monkeypatch):
    eng = FakeEngine()
    clock = FakeClock()
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(eng, warmup_samples=1, steps_per_sample=4,
                          log_path=str(log), clock=clock)
    base_thr = eng.fusion_threshold

    # Warmup + schedule-advance sample: params unchanged.
    _drive_sample(pm, clock, 1000, 0.01)
    assert eng.fusion_threshold == base_thr
    _drive_sample(pm, clock, 1000, 0.01)
    first = (eng.fusion_threshold, eng.cycle_time_s)
    assert first == (int(pm._candidates[0][0]), pm._candidates[0][1])

    # Run every candidate; make candidate index 4 (the 1.0x/1.0x point)
    # fastest by giving it the shortest cycle latency.
    final_broadcasts = []
    monkeypatch.setattr(pm, "_begin_finalize",
                        lambda: final_broadcasts.append(pm._local_best()) or
                        pm._apply_final(*pm._local_best()))
    for i in range(len(pm._candidates)):
        dt = 0.001 if i == 4 else 0.05
        _drive_sample(pm, clock, 1000, dt)

    assert not pm.tuning
    assert final_broadcasts == [pm._candidates[4]]
    assert eng.fusion_threshold == int(pm._candidates[4][0])
    assert eng.cycle_time_s == pm._candidates[4][1]

    text = log.read_text()
    assert text.startswith("sample,fusion_threshold_bytes")
    assert "# final:" in text
    # One scored line per candidate.
    assert len([l for l in text.splitlines()
                if l and not l.startswith(("#", "sample"))]) == \
        len(pm._candidates)


def test_parameter_manager_ignores_idle_cycles():
    eng = FakeEngine()
    clock = FakeClock()
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=2,
                          clock=clock)
    for _ in range(100):
        pm.on_cycle(0)  # idle cycles must not advance the schedule
    assert pm._cycles_in_sample == 0
    assert pm._sample_idx == -1


def test_autotune_end_to_end(monkeypatch):
    """Real engine under HOROVOD_AUTOTUNE=1: tuning completes (including the
    rank-0 agreement broadcast through the engine itself) and results stay
    correct throughout."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    basics.shutdown()
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    try:
        hvd.init()
        eng = basics._get_state().engine
        assert eng.autotuner is not None
        x = hvd.replicated(np.ones(128, np.float32))
        n_needed = (1 + 1 + len(eng.autotuner._candidates) + 3) * 2 + 8
        for i in range(n_needed):
            out = hvd.to_local(hvd.allreduce(x, name=f"tune.{i}", op=hvd.Sum))
            np.testing.assert_allclose(out, np.full(128, 8.0))
            if not eng.autotuner.tuning:
                break
        assert not eng.autotuner.tuning, (
            eng.autotuner._sample_idx, len(eng.autotuner._scores))
        # Tuned params are one of the candidates (rank 0's pick).
        assert (eng.fusion_threshold, eng.cycle_time_s) in [
            (int(t), c) for t, c in eng.autotuner._candidates]
        # Collectives still correct after tuning.
        out = hvd.to_local(hvd.allreduce(x, name="after", op=hvd.Sum))
        np.testing.assert_allclose(out, np.full(128, 8.0))
    finally:
        basics.shutdown()
        monkeypatch.delenv("HOROVOD_AUTOTUNE")
        monkeypatch.delenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES")
        monkeypatch.delenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE")
        hvd.init()
