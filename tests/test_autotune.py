"""Autotune (parameter manager) tests — reference test_autotune.py analogue.

Unit tier drives the coordinate-descent search and the ParameterManager
with a fake engine, injected clock, and loopback agreement transport; the
integration tier runs a real HOROVOD_AUTOTUNE=1 engine over many eager
allreduces and asserts tuning converges and collectives stay correct.
"""

import math
import os

import numpy as np
import pytest

from horovod_tpu.ops.autotune import LogCoordinateDescent, ParameterManager


class FakeEngine:
    def __init__(self, thr=64 * 1024 * 1024, cyc=0.001):
        self.fusion_threshold = thr
        self.cycle_time_s = cyc
        self.fast_lane_threshold = 0


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _surface(thr_bytes: float, cyc_s: float) -> float:
    """Synthetic throughput surface (bytes/s): unimodal with its optimum at
    (64MB, 1ms), far from a deliberately bad 1KB start — shaped like the
    real tradeoff (tiny fusion = per-op overhead dominates; huge cycle =
    latency dominates)."""
    lt = math.log2(max(thr_bytes, 1.0))
    lc = math.log2(max(cyc_s, 1e-6))
    return 1e9 * math.exp(-((lt - 26.0) / 6.0) ** 2) \
        * math.exp(-((lc - math.log2(1e-3)) / 4.0) ** 2)


# The grid the pre-round-3 autotuner explored: multipliers around the start.
_OLD_GRID_THR = (0.25, 1.0, 4.0)
_OLD_GRID_CYC = (0.2, 1.0, 5.0)


def test_search_converges_from_bad_start_beats_old_grid():
    """VERDICT r2 #4 'done' criterion: from a 1KB fusion threshold the
    online search must reach within 20% of the surface optimum — beating
    every corner of the old 3×3 multiplier grid, which can never leave the
    bad regime."""
    start_thr, start_cyc = 1024.0, 0.001
    search = LogCoordinateDescent(
        start=(math.log2(start_thr), math.log2(start_cyc)),
        bounds=((10.0, 30.0), (math.log2(1e-4), math.log2(0.1))))
    evals = 0
    while not search.done and evals < 100:
        thr, cyc = (2.0 ** p for p in search.proposal())
        search.record(_surface(thr, cyc))
        evals += 1
    assert search.done
    thr, cyc = (2.0 ** p for p in search.point)
    achieved = _surface(thr, cyc)
    optimum = _surface(64 * 1024 * 1024, 1e-3)
    assert achieved >= 0.8 * optimum, (thr, cyc, achieved / optimum)

    best_grid = max(_surface(start_thr * tm, start_cyc * cm)
                    for tm in _OLD_GRID_THR for cm in _OLD_GRID_CYC)
    assert achieved > best_grid, (achieved, best_grid)
    # The search must have moved far from the bad start.
    assert thr > 1024 * 64


def test_search_respects_bounds_and_terminates():
    search = LogCoordinateDescent(start=(10.0, -13.0),
                                  bounds=((10.0, 30.0),
                                          (math.log2(1e-4), math.log2(0.1))),
                                  max_evals=200)
    evals = 0
    while not search.done and evals < 300:
        p = search.proposal()
        assert 10.0 - 1e-9 <= p[0] <= 30.0 + 1e-9
        search.record(1.0)  # flat surface: must terminate by step decay
        evals += 1
    assert search.done
    assert evals < 60  # step decay, not max_evals, ended it


def _loopback_transport():
    """Broadcast transport double: payload comes straight back (what the
    engine broadcast does for the single-process world)."""
    sent = []

    def broadcaster(payload):
        sent.append(np.asarray(payload).copy())
        return ("h", sent[-1])

    def poller(handle):
        return handle[1]

    return broadcaster, poller, sent


def _drive_sample(pm, clock, nbytes, dt):
    """One full sample window then the agreement poll cycle."""
    for _ in range(pm._steps_per_sample):
        clock.t += dt
        pm.on_cycle(nbytes)
    # One more work cycle delivers the broadcast payload.
    clock.t += dt
    pm.on_cycle(nbytes)


def test_parameter_manager_tunes_on_surface(tmp_path):
    """Full sampling loop against the synthetic surface: cycle latency is
    derived from the surface, so the manager should walk the engine's
    parameters out of the bad-start regime and finish."""
    eng = FakeEngine(thr=1024, cyc=0.001)
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(eng, warmup_samples=1, steps_per_sample=2,
                          log_path=str(log), clock=clock,
                          broadcaster=bc, poller=poll, max_evals=48)
    nbytes = 1 << 20
    for _ in range(200):
        if not pm.tuning:
            break
        score = _surface(eng.fusion_threshold, eng.cycle_time_s)
        dt = nbytes / max(score, 1.0)
        _drive_sample(pm, clock, nbytes, dt)
    assert not pm.tuning
    final = _surface(eng.fusion_threshold, eng.cycle_time_s)
    optimum = _surface(64 * 1024 * 1024, 1e-3)
    assert final >= 0.8 * optimum, (
        eng.fusion_threshold, eng.cycle_time_s, final / optimum)
    # Every move was agreed through the broadcast transport.
    assert len(sent) == pm.search.evals
    text = log.read_text()
    assert text.startswith("sample,fusion_threshold_bytes")
    assert "# final:" in text


def test_parameter_manager_ignores_idle_cycles():
    eng = FakeEngine()
    clock = FakeClock()
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=2,
                          clock=clock)
    for _ in range(100):
        pm.on_cycle(0)  # idle cycles must not advance the schedule
    assert pm._cycles_in_sample == 0
    assert pm.search.evals == 0


def test_parameter_manager_pipeline_coordinates(tmp_path):
    """With a controller present the search gains the response-cache,
    chunk-bytes, in-flight, fast-lane and round-pipeline coordinates
    (7-point search, 8-float agreement payload; spec_ready_after=0 is an
    explicit opt-out, exactly like cache capacity 0 — no dead knob in the
    search); every agreed move lands on the engine knobs and stays inside
    the coordinate bounds."""

    class FakeCtl:
        cache_enabled = True
        cache_capacity = 256
        spec_ready_after = 0               # speculation off: not searched
        round_pipeline = 1

    eng = FakeEngine(thr=1 << 20, cyc=0.001)
    eng.controller = FakeCtl()
    eng.pipeline_chunk_bytes = 0           # start derives from threshold
    eng.max_inflight = 2
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    log = tmp_path / "autotune_pipeline.csv"
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          log_path=str(log), clock=clock,
                          broadcaster=bc, poller=poll, max_evals=10)
    assert pm._tune_cache and pm._tune_pipeline and pm._tune_fast_lane
    assert not pm._tune_spec and pm._tune_round_pipeline
    assert len(pm.search.point) == 7
    for _ in range(40):
        if not pm.tuning:
            break
        _drive_sample(pm, clock, 1 << 20, 0.01)
    assert sent and all(len(p) == 8 for p in sent), \
        [len(p) for p in sent]      # [thr,cyc,cap,chunk,infl,fl,rp,done]
    assert 1 <= eng.max_inflight <= 8
    assert (1 << 16) <= eng.pipeline_chunk_bytes <= (1 << 30)
    assert 1 <= eng.controller.cache_capacity <= 256
    assert (1 << 8) <= eng.fast_lane_threshold <= (1 << 24)
    assert 1 <= eng.controller.round_pipeline <= 4
    header = log.read_text().splitlines()[0]
    assert "pipeline_chunk_bytes" in header and "max_inflight" in header
    assert "fast_lane_threshold" in header
    assert "round_pipeline" in header and "spec_ready_after" not in header


def test_parameter_manager_hier_threshold_coordinate(tmp_path):
    """ISSUE 17: with the two-level mode ARMED the search gains the
    hier_threshold coordinate (flat-vs-hierarchical crossover, learned
    per pod instead of hand-set); it lands on engine.hier_threshold_bytes
    inside bounds and rides the log header + final line.  Mode off →
    coordinate off (no dead knob in the search)."""

    class FakeCtl:
        cache_enabled = False
        cache_capacity = 0
        spec_ready_after = 0
        round_pipeline = 1

    eng = FakeEngine(thr=1 << 20, cyc=0.001)
    eng.controller = FakeCtl()
    eng.pipeline_chunk_bytes = 0
    eng.max_inflight = 2
    eng.hierarchical_allreduce = True
    eng.hier_threshold_bytes = 0           # start derives from the floor
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    log = tmp_path / "autotune_hier.csv"
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          log_path=str(log), clock=clock,
                          broadcaster=bc, poller=poll, max_evals=8)
    assert pm._tune_hier
    # thr, cyc, chunk, inflight, fast_lane, hier, round_pipeline
    assert len(pm.search.point) == 7
    for _ in range(40):
        if not pm.tuning:
            break
        _drive_sample(pm, clock, 1 << 20, 0.01)
    assert sent and all(len(p) == 8 for p in sent), [len(p) for p in sent]
    assert (1 << 10) <= eng.hier_threshold_bytes <= (1 << 28)
    text = log.read_text()
    assert "hier_threshold_bytes" in text.splitlines()[0]
    assert "hier_threshold_bytes=" in text.splitlines()[-1]

    # Mode disarmed → the coordinate never enters the search.
    eng2 = FakeEngine()
    eng2.controller = FakeCtl()
    eng2.pipeline_chunk_bytes = 0
    eng2.max_inflight = 2
    pm2 = ParameterManager(eng2, warmup_samples=0, steps_per_sample=1,
                           clock=FakeClock(), broadcaster=bc, poller=poll,
                           max_evals=4)
    assert not pm2._tune_hier
    assert len(pm2.search.point) == 6


def test_parameter_manager_checkpoint_lane_coordinates(tmp_path):
    """ISSUE 15 (the ISSUE 14 carry-over): with the state plane armed the
    search gains the checkpoint-lane pair — shard-chunk bytes and the
    per-cycle lane budget.  Gated on the plane (no dead knobs without a
    durability stream), moves land on stateplane.chunk_bytes /
    engine.ckpt_lane_budget within bounds, and the log carries the
    columns.  Controller-less engine: the gradient-side pipeline
    coordinates stay off, so the payload is [thr, cyc, chunk, budget,
    done]."""

    class FakePlane:
        chunk_bytes = 1 << 20

    eng = FakeEngine(thr=1 << 20, cyc=0.001)
    eng.stateplane = FakePlane()
    eng.ckpt_lane_budget = 2
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    log = tmp_path / "autotune_ckpt.csv"
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          log_path=str(log), clock=clock,
                          broadcaster=bc, poller=poll, max_evals=10)
    assert pm._tune_ckpt
    assert not pm._tune_pipeline and not pm._tune_cache
    assert len(pm.search.point) == 4
    for _ in range(40):
        if not pm.tuning:
            break
        _drive_sample(pm, clock, 1 << 20, 0.01)
    assert sent and all(len(p) == 5 for p in sent), [len(p) for p in sent]
    assert (1 << 16) <= eng.stateplane.chunk_bytes <= (1 << 26)
    assert 1 <= eng.ckpt_lane_budget <= 8
    header = log.read_text().splitlines()[0]
    assert "ckpt_chunk_bytes" in header and "ckpt_lane_budget" in header
    assert not pm.tuning or pm.search.evals <= 10


def test_parameter_manager_no_ckpt_coordinates_without_plane():
    """No state plane armed: the checkpoint pair must NOT enter the
    search (a dead coordinate would burn a third of the eval budget)."""
    eng = FakeEngine()
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          clock=FakeClock())
    assert not pm._tune_ckpt
    assert len(pm.search.point) == 2


def test_parameter_manager_zero_rtt_coordinates(tmp_path):
    """ISSUE 11: with speculation armed (spec_ready_after > 0) the search
    gains BOTH zero-RTT coordinates (8-point search, 9-float payload);
    moves land on the controller's spec_ready_after / round_pipeline and
    respect the bounds (spec never tuned down to 0 — 0 is the config-
    level opt-out, not a search point), and the log/final paths carry
    the columns."""

    class FakeCtl:
        cache_enabled = True
        cache_capacity = 256
        spec_ready_after = 2
        round_pipeline = 1

    eng = FakeEngine(thr=1 << 20, cyc=0.001)
    eng.controller = FakeCtl()
    eng.pipeline_chunk_bytes = 0
    eng.max_inflight = 2
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    log = tmp_path / "autotune_zero_rtt.csv"
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          log_path=str(log), clock=clock,
                          broadcaster=bc, poller=poll, max_evals=12)
    assert pm._tune_spec and pm._tune_round_pipeline
    assert len(pm.search.point) == 8
    for _ in range(60):
        if not pm.tuning:
            break
        _drive_sample(pm, clock, 1 << 20, 0.01)
    assert sent and all(len(p) == 9 for p in sent), [len(p) for p in sent]
    assert 1 <= eng.controller.spec_ready_after <= 32
    assert 1 <= eng.controller.round_pipeline <= 4
    text = log.read_text()
    header = text.splitlines()[0]
    assert "spec_ready_after" in header and "round_pipeline" in header
    assert "# final:" in text.splitlines()[-1]
    assert "spec_ready_after=" in text.splitlines()[-1]
    assert "round_pipeline=" in text.splitlines()[-1]


def test_parameter_manager_single_controller_skips_pipeline_coords():
    """No controller -> the legacy 2-coordinate search and 3-float
    payload: single-controller mode must not tune dead knobs."""
    eng = FakeEngine()
    clock = FakeClock()
    bc, poll, sent = _loopback_transport()
    pm = ParameterManager(eng, warmup_samples=0, steps_per_sample=1,
                          clock=clock, broadcaster=bc, poller=poll,
                          max_evals=4)
    assert not pm._tune_cache and not pm._tune_pipeline
    assert not pm._tune_fast_lane
    assert not pm._tune_spec and not pm._tune_round_pipeline
    assert len(pm.search.point) == 2
    _drive_sample(pm, clock, 1 << 20, 0.01)
    assert sent and all(len(p) == 3 for p in sent)


def test_autotune_end_to_end(monkeypatch):
    """Real engine under HOROVOD_AUTOTUNE=1: tuning completes (including the
    per-move rank-0 agreement broadcasts through the engine itself) and
    results stay correct throughout."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    basics.shutdown()
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_MAX_EVALS", "6")
    try:
        hvd.init()
        eng = basics._get_state().engine
        assert eng.autotuner is not None
        x = hvd.replicated(np.ones(128, np.float32))
        # warmup + evals*(sample + agreement) with slack.
        for i in range(120):
            out = hvd.to_local(hvd.allreduce(x, name=f"tune.{i}", op=hvd.Sum))
            np.testing.assert_allclose(out, np.full(128, 8.0))
            if not eng.autotuner.tuning:
                break
        assert not eng.autotuner.tuning, (
            eng.autotuner.search.evals, eng.autotuner._sample_no)
        # Tuned params are inside the search bounds.  The bounds live in
        # log2 space, so a walk clamped at the edge round-trips through
        # 2.0 ** log2(bound) — one float ulp of slack keeps a noisy-box
        # run that pins cycle_time at its floor from flaking here.
        assert 1024 * 0.999 <= eng.fusion_threshold <= (1 << 30) * 1.001
        assert 1e-4 * 0.999 <= eng.cycle_time_s <= 0.1 * 1.001
        # Collectives still correct after tuning.
        out = hvd.to_local(hvd.allreduce(x, name="after", op=hvd.Sum))
        np.testing.assert_allclose(out, np.full(128, 8.0))
    finally:
        basics.shutdown()
        monkeypatch.delenv("HOROVOD_AUTOTUNE")
        monkeypatch.delenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES")
        monkeypatch.delenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE")
        monkeypatch.delenv("HOROVOD_AUTOTUNE_MAX_EVALS")
        hvd.init()
