"""Serving plane: continuous batcher, front door, readiness, percentiles,
serving autoscale signals (tier-1, no jax, no process spawns).

Covers the jax-free halves of the data-parallel serving plane (ISSUE 19,
``docs/serving.md``): ``serve/batcher.ContinuousBatcher`` admission /
deadline / padded-bucket / backpressure semantics under a scripted clock,
the ``serve/frontdoor.FrontDoor`` HTTP status mapping (200/429/503/504),
the monitor's ``/ready``-vs-``/health`` split, ``Histogram.percentile``
plus the p50/p99 Prometheus export, the aggregator's fleet
``request_rate``/``latency_p99_ms`` gauges, and the ``ScalePolicy``
request-rate / latency-target / serving-idle decisions.  The jax-backed
replica half (broadcast fan-out, batched-vs-sequential parity, drain with
in-flight work) lives in ``tests/data/worker_serve.py`` via
``test_multiprocess.py``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.elastic.autoscale import (
    HOLD, SCALE_IN, SCALE_OUT, ScalePolicy,
)
from horovod_tpu.monitor.agent import MonitorAgent
from horovod_tpu.monitor.aggregator import (
    EwmaTrend, RankAggregator, merged_percentile,
)
from horovod_tpu.monitor.http import MonitorHTTPServer
from horovod_tpu.monitor.registry import Histogram, MetricRegistry
from horovod_tpu.serve.batcher import (
    Batch, ContinuousBatcher, DeadlineExceeded, Draining, QueueFull,
    parse_buckets,
)
from horovod_tpu.serve.frontdoor import FrontDoor


def _clocked(**kw):
    """Batcher on a scripted clock; returns (batcher, tick)."""
    clock = [0.0]
    b = ContinuousBatcher(clock=lambda: clock[0], **kw)

    def tick(dt):
        clock[0] += dt
    return b, tick


# ----------------------------------------------------------------- batcher
def test_batcher_admission_and_positional_routing():
    b, _ = _clocked(max_batch=8)
    reqs = [b.submit([i]) for i in range(3)]
    batch = b.next_batch(timeout=0.0)
    assert batch.size == 3
    assert [r.id for r in batch.requests] == [r.id for r in reqs]
    b.complete(batch, [[i * 10] for i in range(3)])
    assert [r.wait(0.0) for r in reqs] == [[0], [10], [20]]


def test_batcher_padded_bucket_shapes():
    """Batch sizes snap UP to the bucket menu — the replica compiles one
    program per bucket, never one per ragged size."""
    b, _ = _clocked(max_batch=8)
    assert b.buckets == (1, 2, 4, 8)
    for n, want in ((1, 1), (2, 2), (3, 4), (5, 8), (8, 8)):
        assert b.bucket_for(n) == want, n
    for _ in range(5):
        b.submit([0])
    batch = b.next_batch(timeout=0.0)
    assert (batch.size, batch.bucket) == (5, 8)
    assert b.stats()["padding_rows_total"] == 3


def test_batcher_explicit_bucket_menu():
    b, _ = _clocked(max_batch=6, buckets=(2, 6))
    assert b.buckets == (2, 6)
    assert b.bucket_for(1) == 2 and b.bucket_for(3) == 6
    assert parse_buckets("1,3,9", 6) == (1, 3, 6)   # 9 > max dropped
    assert parse_buckets("", 8) == (1, 2, 4, 8)


def test_batcher_inflight_window_blocks_dispatch():
    """HOROVOD_MAX_INFLIGHT semantics: at most ``max_inflight`` batches
    dispatched-but-unsettled; settling reopens the window."""
    b, _ = _clocked(max_batch=2, max_inflight=1)
    for i in range(4):
        b.submit([i])
    first = b.next_batch(timeout=0.0)
    assert first is not None
    assert b.next_batch(timeout=0.0) is None        # window full
    b.complete(first, [[0], [0]])
    second = b.next_batch(timeout=0.0)
    assert second is not None and second.size == 2
    b.complete(second, [[0], [0]])


def test_batcher_deadline_expires_queued_requests():
    b, tick = _clocked(max_batch=4, deadline_ms=100.0)
    stale = b.submit([1])
    tick(0.2)                                       # past 100ms
    fresh = b.submit([2], deadline_ms=1000.0)
    batch = b.next_batch(timeout=0.0)
    assert [r.id for r in batch.requests] == [fresh.id]
    with pytest.raises(DeadlineExceeded):
        stale.wait(0.0)
    assert b.stats()["expired_total"] == 1
    b.complete(batch, [[2]])


def test_batcher_backpressure_and_drain():
    b, _ = _clocked(max_batch=4, queue_depth=2)
    b.submit([1])
    b.submit([2])
    with pytest.raises(QueueFull):
        b.submit([3])
    assert b.stats()["rejected_total"] == 1
    b.drain()
    with pytest.raises(Draining):
        b.submit([4])
    # The drain contract: queued work still dispatches and settles.
    batch = b.next_batch(timeout=0.0)
    assert batch.size == 2
    b.complete(batch, [[1], [2]])
    assert b.next_batch(timeout=0.0) is None        # drained + empty
    assert b.pending() == 0


def test_batcher_fail_routes_error_to_callers():
    b, _ = _clocked(max_batch=2)
    r = b.submit([1])
    batch = b.next_batch(timeout=0.0)
    b.fail(batch, RuntimeError("forward blew up"))
    with pytest.raises(RuntimeError, match="forward blew up"):
        r.wait(0.0)
    # The window slot was returned: new work still dispatches.
    b.submit([2])
    assert b.next_batch(timeout=0.0) is not None


# -------------------------------------------------------------- front door
def _door():
    b = ContinuousBatcher(max_batch=4, deadline_ms=2000.0, queue_depth=4)
    fd = FrontDoor(b).start()
    return b, fd


def _worker(b, stop, fn=lambda v: [x * 2 for x in v]):
    def loop():
        while not stop.is_set():
            batch = b.next_batch(timeout=0.02)
            if batch is not None:
                b.complete(batch, [fn(r.inputs) for r in batch.requests])
    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def _post(port, body, path="/v1/infer"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


def test_frontdoor_http_roundtrip_and_stats():
    b, fd = _door()
    stop = threading.Event()
    t = _worker(b, stop)
    try:
        out = _post(fd.port, {"inputs": [1, 2, 3]})
        assert out["outputs"] == [2, 4, 6]
        assert out["latency_ms"] >= 0
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{fd.port}/v1/stats", timeout=10).read())
        assert stats["requests_total"] == 1
        assert stats["batches_total"] == 1
    finally:
        stop.set()
        t.join(2)
        fd.stop()


def test_frontdoor_maps_overload_to_429_and_drain_to_503():
    b, fd = _door()
    try:
        for i in range(4):                          # fill, no worker
            b.submit([i])
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(fd.port, {"inputs": [9]})
        assert exc.value.code == 429
        body = json.loads(exc.value.read())
        assert body["queue_depth"] == 4             # the autoscale signal
        assert exc.value.headers["Retry-After"]
        fd.drain()
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(fd.port, {"inputs": [9]})
        assert exc.value.code == 503
    finally:
        fd.stop()


def test_frontdoor_maps_deadline_to_504_and_bad_input_to_400():
    b, fd = _door()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(fd.port, {"inputs": [1], "deadline_ms": 30})  # no worker
        assert exc.value.code == 504
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(fd.port, {"nope": 1})
        assert exc.value.code == 400
    finally:
        fd.stop()


# ------------------------------------------------------ readiness vs health
def test_ready_endpoint_splits_from_health():
    agent = MonitorAgent(rank=0, world=1)
    srv = MonitorHTTPServer(agent, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        ready = json.loads(urllib.request.urlopen(
            base + "/ready", timeout=10).read())
        assert ready["ready"] is True
        agent.set_ready(False, "draining: driver cordon ping received")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/ready", timeout=10)
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert "draining" in body["reason"]
        # /health stays truthful liveness: a draining replica is healthy.
        health = json.loads(urllib.request.urlopen(
            base + "/health", timeout=10).read())
        assert health["status"] == "ok"
        assert health["ready"] is False
        agent.set_ready(True)
        ready = json.loads(urllib.request.urlopen(
            base + "/ready", timeout=10).read())
        assert ready["ready"] is True
    finally:
        srv.stop()
        agent.close()


def test_peer_failure_forces_not_ready():
    agent = MonitorAgent(rank=0, world=2)
    agent._peer_failure = {"reason": "rank 1 died", "dead_ranks": [1]}
    r = agent.readiness()
    assert r["ready"] is False and "rank 1" in r["reason"]
    agent.close()


# ------------------------------------------------------------- percentiles
def test_histogram_percentile_interpolates_and_clamps():
    h = Histogram("lat", buckets=(10.0, 100.0, 1000.0))
    assert h.percentile(0.5) is None                # empty: no estimate
    for v in (5.0,) * 50 + (50.0,) * 40 + (500.0,) * 10:
        h.observe(v)
    assert h.percentile(0.5) == 10.0                # crossing at bucket edge
    assert 10.0 < h.percentile(0.9) <= 100.0
    assert 100.0 < h.percentile(0.99) <= 1000.0
    h.observe(1e9)                                  # +Inf overflow
    assert h.percentile(1.0) == 1000.0              # clamped to last bound
    with pytest.raises(ValueError):
        h.percentile(1.5)


def test_prometheus_export_includes_p50_p99():
    reg = MetricRegistry()
    h = reg.histogram("hvd_serve_latency_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 5.0):
        h.observe(v)
    text = reg.to_prometheus(extra_label='rank="0"')
    assert 'hvd_serve_latency_ms_p50{rank="0"}' in text
    assert 'hvd_serve_latency_ms_p99{rank="0"}' in text
    empty = MetricRegistry()
    empty.histogram("h", buckets=(1.0,))
    assert "_p50" not in empty.to_prometheus()      # no data, no estimate


def test_merged_percentile_across_rank_histograms():
    a = Histogram("h", buckets=(10.0, 100.0))
    b = Histogram("h", buckets=(10.0, 100.0))
    for _ in range(90):
        a.observe(5.0)
    for _ in range(10):
        b.observe(50.0)
    p99 = merged_percentile(
        [a.snapshot_value(), b.snapshot_value()], 0.99)
    assert 10.0 < p99 <= 100.0                      # tail lives in rank b
    assert merged_percentile([], 0.99) is None


# --------------------------------------------------- serving fleet summary
def _serve_snap(total, hist):
    return {"rank": 0, "cycle_us_avg": 100.0,
            "metrics": {"hvd_serve_requests_total": total,
                        "hvd_serve_latency_ms": hist}}


def test_aggregator_fleet_request_rate_and_latency():
    agg = RankAggregator(world=1)
    h = Histogram("hvd_serve_latency_ms", buckets=(10.0, 100.0))
    for _ in range(100):
        h.observe(50.0)
    snap = h.snapshot_value()
    t0 = time.monotonic()
    # Rate needs a baseline first, then deltas; trends fill at 3 samples.
    for i, total in enumerate((0, 100, 200, 300, 400)):
        agg.update(0, _serve_snap(float(total), snap))
        if i < 4:
            time.sleep(0.02)
    s = agg.summary()
    assert s["request_rate"] is not None and s["request_rate"] > 0
    assert s["latency_p99_ms"] is not None
    assert 10.0 < s["latency_p99_ms"] <= 100.0
    agg.flush()                                     # world resize: reset
    assert agg.summary().get("request_rate") is None


def test_aggregator_without_serving_metrics_stays_null():
    agg = RankAggregator(world=1)
    for _ in range(6):
        agg.update(0, {"rank": 0, "cycle_us_avg": 100.0, "metrics": {}})
    s = agg.summary()
    assert s.get("request_rate") is None
    assert s.get("latency_p99_ms") is None


def test_ewma_level_null_until_filled():
    t = EwmaTrend(min_samples=3)
    t.update(10.0)
    t.update(20.0)
    assert t.level is None
    t.update(30.0)
    assert t.level is not None and t.level > 10.0


# ---------------------------------------------------- serving-mode policy
def _pol(**kw):
    kw.setdefault("min_np", 1)
    kw.setdefault("max_np", 8)
    kw.setdefault("persistence", 2)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("idle_s", 30.0)
    return ScalePolicy(**kw)


def test_policy_request_rate_triggers_scale_out():
    pol = _pol(rate_high=100.0)
    mk = lambda r: {"request_rate": r, "queue_depth": 0}   # noqa: E731
    assert pol.observe(mk(150.0), size=2, now=0.0).action == HOLD  # 75/rep
    assert pol.observe(mk(300.0), size=2, now=1.0).action == HOLD  # hit 1
    d = pol.observe(mk(300.0), size=2, now=2.0)                    # hit 2
    assert d.action == SCALE_OUT and d.target_size == 3
    assert "request_rate" in d.reason


def test_policy_latency_target_triggers_scale_out():
    pol = _pol(latency_target_ms=50.0)
    mk = lambda p: {"request_rate": 10.0, "latency_p99_ms": p,  # noqa: E731
                    "queue_depth": 0}
    assert pol.observe(mk(20.0), size=2, now=0.0).action == HOLD
    assert pol.observe(mk(80.0), size=2, now=1.0).action == HOLD
    d = pol.observe(mk(80.0), size=2, now=2.0)
    assert d.action == SCALE_OUT
    assert "p99" in d.reason


def test_policy_nulls_never_scale_serving():
    pol = _pol(rate_high=100.0, latency_target_ms=50.0)
    for i in range(5):
        d = pol.observe({"request_rate": None, "latency_p99_ms": None,
                         "queue_depth": 0}, size=2, now=float(i))
        assert d.action == HOLD


def test_policy_serving_idle_scales_in_on_low_qps():
    """With ``idle_qps`` set, idleness is rate-below-floor — training
    progress is irrelevant to a serving fleet."""
    pol = _pol(idle_qps=5.0, idle_s=10.0)
    mk = lambda r: {"request_rate": r, "queue_depth": 0,   # noqa: E731
                    "progress_total": 42.0}                # never moves
    assert pol.observe(mk(50.0), size=2, now=0.0).action == HOLD
    assert pol.observe(mk(1.0), size=2, now=5.0).action == HOLD
    d = pol.observe(mk(1.0), size=2, now=16.0)             # 11s below floor
    assert d.action == SCALE_IN and d.target_size == 1
    # Busy fleet: the timer must never accrue, even with zero progress.
    pol2 = _pol(idle_qps=5.0, idle_s=10.0)
    for i in range(5):
        assert pol2.observe(mk(50.0), size=2,
                            now=float(i * 10)).action == HOLD


def test_policy_training_idle_unaffected_without_idle_qps():
    """Serving knobs off: the progress-based idle test is untouched —
    a summary with request_rate present but idle_qps unset behaves
    exactly as before ISSUE 19."""
    pol = _pol(idle_s=10.0)
    mk = {"request_rate": 0.0, "queue_depth": 0, "progress_total": 1.0}
    # First sight of progress_total counts as progress (None -> 1.0), so
    # the idle timer starts at the SECOND unchanged observation.
    assert pol.observe(dict(mk), size=2, now=0.0).action == HOLD
    assert pol.observe(dict(mk), size=2, now=5.0).action == HOLD
    assert pol.observe(dict(mk), size=2, now=20.0).action == SCALE_IN
