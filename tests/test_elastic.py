"""Elastic driver tests.

Unit tier mirrors the reference's ``test/single/test_elastic_driver.py``
pattern (fake discovery from temp files, assert on rank assignment /
blacklist / rendezvous logic with no real training); the integration tier
(``test_elastic_integration``) runs a REAL elastic job on localhost whose
discovery output mutates mid-run, like ``test/integration/
test_elastic_torch.py`` (SURVEY.md §4).
"""

import json
import os
import stat
import subprocess
import sys
import tempfile
import time

import pytest

from horovod_tpu.elastic.discovery import (
    DiscoveredHost, FixedHostDiscovery, HostDiscoveryScript)
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.registration import WorkerStateRegistry
from horovod_tpu.elastic.rendezvous import (
    RendezvousServer, fetch_assignment, register_notification_port)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ discovery
def test_discovery_parse():
    d = HostDiscoveryScript("true", default_slots=2)
    hosts = d.parse("a:4\nb\n# comment\n\nc:1 # tail\na:9\n")
    assert hosts == [DiscoveredHost("a", 4), DiscoveredHost("b", 2),
                     DiscoveredHost("c", 1)]


def test_discovery_script_execution(tmp_path):
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("localhost:2\nnode1:4\n")
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\ncat {hostfile}\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    d = HostDiscoveryScript(str(script))
    assert d.find_available_hosts_and_slots() == [
        DiscoveredHost("localhost", 2), DiscoveredHost("node1", 4)]
    # Mutating the file changes the next poll (the elastic contract).
    hostfile.write_text("localhost:2\n")
    assert d.find_available_hosts_and_slots() == [
        DiscoveredHost("localhost", 2)]


def test_discovery_script_failure():
    d = HostDiscoveryScript("exit 3")
    with pytest.raises(RuntimeError):
        d.find_available_hosts_and_slots()


# ----------------------------------------------------------------- registry
def test_registry_blacklist():
    r = WorkerStateRegistry()
    r.record_ready("a:0")
    r.record_failure("a:0")
    assert r.is_blacklisted("a")
    assert not r.is_blacklisted("b")
    assert r.failure_count("a") == 1
    r.record_success("b:0")
    assert r.success_count() == 1


# -------------------------------------------------------------- assignments
def _driver(min_np=1, max_np=None):
    return ElasticDriver(FixedHostDiscovery([]), ["true"], min_np=min_np,
                         max_np=max_np)


def test_compute_assignments_order_and_shape():
    d = _driver(min_np=2)
    try:
        a = d.compute_assignments([DiscoveredHost("h0", 2),
                                   DiscoveredHost("h1", 1)])
        assert set(a) == {"h0:0", "h0:1", "h1:0"}
        assert a["h0:0"]["rank"] == 0
        assert a["h0:1"]["rank"] == 1
        assert a["h1:0"]["rank"] == 2
        assert all(v["size"] == 3 for v in a.values())
        assert a["h1:0"]["cross_rank"] == 1
        assert a["h0:1"]["local_size"] == 2
        assert all(v["controller_addr"] == "h0" for v in a.values())
    finally:
        d.rendezvous.stop()


def test_compute_assignments_max_np_cap_and_min_np():
    d = _driver(min_np=2, max_np=2)
    try:
        a = d.compute_assignments([DiscoveredHost("h0", 4)])
        assert set(a) == {"h0:0", "h0:1"}
        assert all(v["size"] == 2 for v in a.values())
        assert d.compute_assignments([DiscoveredHost("h0", 1)]) == {}
    finally:
        d.rendezvous.stop()


def test_blacklisted_host_excluded():
    d = _driver(min_np=1)
    try:
        d.registry.record_failure("bad:0")
        hosts = d.active_hosts([DiscoveredHost("bad", 2),
                                DiscoveredHost("good", 1)])
        assert hosts == [DiscoveredHost("good", 1)]
    finally:
        d.rendezvous.stop()


# --------------------------------------------------------------- rendezvous
def test_rendezvous_publish_fetch_versioning():
    s = RendezvousServer()
    try:
        v1 = s.publish({"h:0": {"rank": 0, "size": 1}})
        assert v1 == 1
        a = fetch_assignment("127.0.0.1", s.port, "h:0", timeout_s=5)
        assert a["rank"] == 0 and a["version"] == 1
        # min_version gating: nothing at version 2 yet.
        with pytest.raises(TimeoutError):
            fetch_assignment("127.0.0.1", s.port, "h:0", min_version=2,
                             timeout_s=1.0)
        v2 = s.publish({"h:0": {"rank": 0, "size": 2}})
        a = fetch_assignment("127.0.0.1", s.port, "h:0", min_version=2,
                             timeout_s=5)
        assert a["size"] == 2 and a["version"] == v2
        # Unknown identity stays pending.
        with pytest.raises(TimeoutError):
            fetch_assignment("127.0.0.1", s.port, "nope:0", timeout_s=1.0)
        register_notification_port("127.0.0.1", s.port, "h:0", 12345)
        assert s.notification_ports() == {"h:0": 12345}
    finally:
        s.stop()


def test_rendezvous_rollback_to_surviving_host_set():
    """The PeerFailureError recovery path's rendezvous half: a worker that
    reset after a dead peer long-polls for a STRICTLY newer generation and
    lands in the shrunk world — never re-joins the stale one, and a dead
    identity gets nothing from the new table."""
    s = RendezvousServer()
    try:
        s.publish({"a:0": {"rank": 0, "size": 2},
                   "b:0": {"rank": 1, "size": 2}})
        a = fetch_assignment("127.0.0.1", s.port, "a:0", timeout_s=5)
        assert a["size"] == 2 and a["version"] == 1
        # b:0 died; the driver republished over the survivors only.
        v2 = s.publish({"a:0": {"rank": 0, "size": 1}})
        a = fetch_assignment("127.0.0.1", s.port, "a:0",
                             min_version=a["version"] + 1, timeout_s=5)
        assert a["size"] == 1 and a["rank"] == 0 and a["version"] == v2
        # The dead identity is gone from the new generation.
        with pytest.raises(TimeoutError):
            fetch_assignment("127.0.0.1", s.port, "b:0", min_version=v2,
                             timeout_s=1.0)
    finally:
        s.stop()


# -------------------------------------------- state restore/rollback paths
def _identity_bcast(obj, root_rank=0):
    return obj


def test_object_state_restore_after_peer_failure_byte_identical():
    """State.restore() after a simulated PeerFailureError must roll every
    registered attribute back to the last commit, byte-identically — the
    half of elastic recovery that runs before re-rendezvous."""
    import pickle

    from horovod_tpu.common.exceptions import PeerFailureError
    from horovod_tpu.elastic.state import ObjectState

    state = ObjectState(bcast_object=_identity_bcast,
                        epoch=3, batch=7,
                        table={"w": [1.0, 2.0], "meta": {"k": (1, 2)}})
    state.commit()
    committed = pickle.dumps((state.epoch, state.batch, state.table))
    # Mutate mid-epoch (including a nested structure), then fail.
    state.epoch = 4
    state.batch = 0
    state.table["w"].append(3.0)
    state.table["meta"]["k"] = (9,)
    try:
        raise PeerFailureError("HVD303 peer died", dead_ranks=[1])
    except PeerFailureError:
        state.restore()
    assert pickle.dumps((state.epoch, state.batch, state.table)) == committed
    # Restore hands back COPIES: mutating post-restore state must not
    # corrupt the saved snapshot a second restore depends on.
    state.table["w"].append(99.0)
    state.restore()
    assert pickle.dumps((state.epoch, state.batch, state.table)) == committed


def test_jax_state_restore_after_peer_failure_byte_identical():
    """JaxState: pytree leaves committed to host memory restore to device
    byte-identically after a control-plane fault."""
    import numpy as np

    from horovod_tpu.common.exceptions import PeerFailureError
    from horovod_tpu.elastic.state import JaxState

    params = {"w": np.arange(8, dtype=np.float32).reshape(2, 4),
              "b": np.float32(0.5)}
    state = JaxState(bcast_object=_identity_bcast, params=params, step=11)
    state.commit()
    committed = {k: np.asarray(v).tobytes()
                 for k, v in state.params.items()}
    state.params = {"w": state.params["w"] * 2.0,
                    "b": state.params["b"] + 1.0}
    state.step = 12
    try:
        raise PeerFailureError("HVD303 peer died", dead_ranks=[0])
    except PeerFailureError:
        state.restore()
    assert state.step == 11
    for k, blob in committed.items():
        assert np.asarray(state.params[k]).tobytes() == blob, k


def test_state_should_commit_consumes_driver_commit_request():
    """Checkpoint pacing (ISSUE 12): ``state.should_commit()`` reads the
    notification manager's one-shot COMMIT flag — True exactly once per
    driver ping, False with no manager attached (non-elastic runs)."""
    from horovod_tpu.elastic.state import ObjectState

    state = ObjectState(bcast_object=_identity_bcast, epoch=0)
    assert state.should_commit() is False      # no manager attached

    class _Mgr:
        def __init__(self):
            self.pending = True

        def consume_commit_request(self):
            p, self.pending = self.pending, False
            return p

    state._notification_manager = _Mgr()
    assert state.should_commit() is True
    assert state.should_commit() is False      # one-shot


def test_run_wrapper_resets_on_peer_failure(monkeypatch):
    """@hvd.elastic.run over a step that hits a PeerFailureError once:
    restore-to-commit, runtime reset, retry — and completion on the second
    attempt (the re-rendezvous itself is covered by the integration
    tier)."""
    from horovod_tpu.common import basics
    from horovod_tpu.common.exceptions import PeerFailureError
    from horovod_tpu.elastic.state import ObjectState, run

    resets = []
    monkeypatch.setattr(basics, "shutdown", lambda: resets.append("down"))
    monkeypatch.setattr(basics, "init", lambda: resets.append("up"))

    attempts = []

    @run
    def train(state):
        attempts.append(state.epoch)
        if len(attempts) == 1:
            state.epoch = 99          # uncommitted progress, must roll back
            raise PeerFailureError("HVD303 peer died", dead_ranks=[1])
        return state.epoch

    state = ObjectState(bcast_object=_identity_bcast, epoch=5)
    state.commit()
    assert train(state) == 5
    assert attempts == [5, 5], "restore did not roll back to the commit"
    assert resets == ["down", "up"], "runtime was not reset between tries"


def test_run_wrapper_peer_restore_only_when_stale(monkeypatch):
    """Review fix: the wrapper's peer-first restore runs only while this
    rank's live state is STALE — a fresh process, or right after a fault
    rolled it back to its last commit.  A survivor re-entering on a clean
    HostsUpdatedInterrupt holds the fleet's current state (its plane
    epoch may lag a peer's on skewed commit cadence), and pulling that
    peer's older commit would roll live training backwards fleet-wide."""
    from horovod_tpu.common import basics
    from horovod_tpu.common.exceptions import (
        HostsUpdatedInterrupt, PeerFailureError,
    )
    from horovod_tpu.elastic import stateplane as spl
    from horovod_tpu.elastic.state import ObjectState, run

    monkeypatch.setattr(basics, "shutdown", lambda: None)
    monkeypatch.setattr(basics, "init", lambda: None)
    plane = object()
    monkeypatch.setattr(spl, "attach", lambda state, p=None: plane)
    restores = []
    attempts = []
    monkeypatch.setattr(spl, "maybe_restore",
                        lambda state, p: restores.append(len(attempts)))

    @run
    def train(state):
        attempts.append(1)
        if len(attempts) == 1:
            raise HostsUpdatedInterrupt(skip_sync=False)   # clean change
        if len(attempts) == 2:
            raise PeerFailureError("HVD303 peer died", dead_ranks=[1])
        return "done"

    state = ObjectState(bcast_object=_identity_bcast, epoch=5)
    state.commit()
    assert train(state) == "done"
    # Restored on the fresh entry (before attempt 1) and after the fault
    # rollback (before attempt 3) — NOT on the clean re-entry (a restore
    # before attempt 2 would record a 1 here).
    assert restores == [0, 2], restores


# ------------------------------------------------- driver process lifecycle
@pytest.mark.slow
def test_driver_success_on_worker_exit_zero():
    d = ElasticDriver(
        FixedHostDiscovery([DiscoveredHost("localhost", 2)]),
        [sys.executable, "-c", "pass"], min_np=2, start_timeout_s=30)
    assert d.run() == 0
    assert d.registry.success_count() >= 1


@pytest.mark.slow
def test_driver_gives_up_below_min_np():
    d = ElasticDriver(FixedHostDiscovery([DiscoveredHost("localhost", 1)]),
                      [sys.executable, "-c", "pass"], min_np=4,
                      start_timeout_s=2, discovery_interval_s=0.2)
    assert d.run() == 1


@pytest.mark.slow
def test_driver_failure_blacklists_and_aborts():
    # Workers always fail; localhost gets blacklisted; below min_np -> abort
    # with the worker's rc.
    d = ElasticDriver(
        FixedHostDiscovery([DiscoveredHost("localhost", 2)]),
        [sys.executable, "-c", "import sys; sys.exit(7)"],
        min_np=2, start_timeout_s=30)
    rc = d.run()
    assert rc == 7
    assert d.registry.is_blacklisted("localhost")


# ------------------------------------------------------------- integration
WORKER = os.path.join(REPO, "tests", "data", "worker_elastic.py")


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["grow", "shrink"])
def test_elastic_integration(tmp_path, mode):
    """Real elastic run on localhost: discovery output mutates mid-run."""
    hostfile = tmp_path / "hosts.txt"
    start, end = (("localhost:1", "localhost:2") if mode == "grow"
                  else ("localhost:2", "localhost:1"))
    hostfile.write_text(start + "\n")
    marker = tmp_path / "epoch_marker"
    result = tmp_path / "result"

    env = dict(os.environ)
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTIC_TEST_MARKER"] = str(marker)
    env["ELASTIC_TEST_RESULT"] = str(result)
    env["ELASTIC_TEST_EPOCHS"] = "6"
    env.pop("HOROVOD_TIMELINE", None)

    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--host-discovery-script", f"cat {hostfile}",
           "--min-np", "1", "--max-np", "2",
           sys.executable, WORKER]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        # Wait for a worker to pass epoch 2, then mutate the host set.
        deadline = time.time() + 120
        while not marker.exists() and time.time() < deadline:
            time.sleep(0.2)
        assert marker.exists(), "worker never reached the marker epoch"
        hostfile.write_text(end + "\n")
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-4000:]
    assert result.exists(), out[-4000:]
    res = json.loads(result.read_text())
    assert res["epochs"] == 6
    final_size = 2 if mode == "grow" else 1
    assert res["final_size"] == final_size, (res, out[-4000:])
    assert res["resets"] >= 1, (res, out[-4000:])


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["grow", "shrink"])
def test_elastic_integration_hierarchical(tmp_path, mode):
    """ISSUE 12 — elastic × hierarchical, real jax workers: the SAME
    grow/shrink run with ``--hierarchical-controller`` armed.
    ``run_elastic`` honors the knob (no silent flat fallback): the driver
    allocates a stable per-host agent port, every generation's rendezvous
    assignment carries it, and the surviving local_rank-0 process's
    HostAgent serves BOTH generations via new_generation while the rank
    set changes under it."""
    hostfile = tmp_path / "hosts.txt"
    start, end = (("localhost:1", "localhost:2") if mode == "grow"
                  else ("localhost:2", "localhost:1"))
    hostfile.write_text(start + "\n")
    marker = tmp_path / "epoch_marker"
    result = tmp_path / "result"

    env = dict(os.environ)
    other_paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + other_paths)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTIC_TEST_MARKER"] = str(marker)
    env["ELASTIC_TEST_RESULT"] = str(result)
    env["ELASTIC_TEST_EPOCHS"] = "6"
    env.pop("HOROVOD_TIMELINE", None)

    logs = tmp_path / "logs"
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--host-discovery-script", f"cat {hostfile}",
           "--min-np", "1", "--max-np", "2",
           "--hierarchical-controller",
           "--output-filename", str(logs),
           sys.executable, WORKER]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while not marker.exists() and time.time() < deadline:
            time.sleep(0.2)
        assert marker.exists(), "worker never reached the marker epoch"
        hostfile.write_text(end + "\n")
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    def _logs():
        return "\n\n".join(f"--- {p} ---\n" + p.read_text()[-2500:]
                           for p in sorted(logs.glob("*/std*"))
                           if p.exists())

    assert proc.returncode == 0, out[-3000:] + _logs()
    assert result.exists(), out[-3000:] + _logs()
    res = json.loads(result.read_text())
    assert res["epochs"] == 6
    final_size = 2 if mode == "grow" else 1
    assert res["final_size"] == final_size, (res, out[-4000:])
    assert res["resets"] >= 1, (res, out[-4000:])


# ------------------------------------------------- TPU metadata discovery
class _FakeMetadataServer:
    """Minimal GCE-metadata-shaped HTTP server whose attribute map the test
    mutates mid-run (VERDICT r2 #6: fake HTTP server drops a host)."""

    def __init__(self):
        import http.server
        import threading
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.headers.get("Metadata-Flavor") != "Google":
                    self.send_response(403)
                    self.end_headers()
                    return
                key = self.path.lstrip("/")
                if key in server.attributes:
                    body = server.attributes[key].encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self.attributes = {}
        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self.url = f"http://127.0.0.1:{self._httpd.server_port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()


def test_tpu_metadata_discovery_membership_and_preemption():
    from horovod_tpu.elastic.discovery import TPUMetadataDiscovery
    srv = _FakeMetadataServer()
    try:
        srv.attributes["instance/attributes/worker-network-endpoints"] = (
            "uid0:8470:10.0.0.1, uid1:8470:10.0.0.2,10.0.0.3")
        d = TPUMetadataDiscovery(base_url=srv.url, slots_per_host=4)
        assert d.find_available_hosts_and_slots() == [
            DiscoveredHost("10.0.0.1", 4), DiscoveredHost("10.0.0.2", 4),
            DiscoveredHost("10.0.0.3", 4)]   # record formats + 404 notices

        # A preemption notice KEEPS the worker in the membership (the
        # hardware is still up) and surfaces it through
        # preemption_notices() instead — the driver's cue to DRAIN it
        # proactively (ISSUE 12) rather than dropping it into a crash.
        srv.attributes["instance/attributes/preempted-workers"] = "10.0.0.2"
        assert d.find_available_hosts_and_slots() == [
            DiscoveredHost("10.0.0.1", 4), DiscoveredHost("10.0.0.2", 4),
            DiscoveredHost("10.0.0.3", 4)]
        assert d.preemption_notices() == {"10.0.0.2"}

        # Membership change (a worker vanishes from the slice): a notice
        # for a host no longer in the membership clears with it.
        srv.attributes["instance/attributes/worker-network-endpoints"] = (
            "uid0:8470:10.0.0.1")
        assert d.find_available_hosts_and_slots() == [
            DiscoveredHost("10.0.0.1", 4)]
        assert d.preemption_notices() == set()
    finally:
        srv.stop()


def test_tpu_metadata_discovery_missing_endpoint_raises():
    from horovod_tpu.elastic.discovery import TPUMetadataDiscovery
    srv = _FakeMetadataServer()
    try:
        d = TPUMetadataDiscovery(base_url=srv.url)
        with pytest.raises(Exception):
            d.find_available_hosts_and_slots()   # membership must exist
    finally:
        srv.stop()


@pytest.mark.slow
def test_elastic_integration_tpu_metadata_preemption(tmp_path):
    """Full elastic run driven by the metadata source: the fake server
    posts a preemption notice for one worker mid-run and training resumes
    at reduced world — the metadata twin of test_elastic_integration."""
    from horovod_tpu.elastic.discovery import TPUMetadataDiscovery

    srv = _FakeMetadataServer()
    srv.attributes["instance/attributes/worker-network-endpoints"] = (
        "localhost,127.0.0.1")
    marker = tmp_path / "epoch_marker"
    result = tmp_path / "result"

    other_paths = [p for p in os.environ.get("PYTHONPATH",
                                             "").split(os.pathsep)
                   if p and "axon" not in p]
    env = {"ELASTIC_TEST_MARKER": str(marker),
           "ELASTIC_TEST_RESULT": str(result),
           "ELASTIC_TEST_EPOCHS": "6",
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join([REPO] + other_paths)}
    d = ElasticDriver(
        TPUMetadataDiscovery(base_url=srv.url, slots_per_host=1),
        [sys.executable, WORKER], min_np=1, max_np=2, env=env,
        discovery_interval_s=0.2, start_timeout_s=60)

    import threading
    rc = {}
    t = threading.Thread(target=lambda: rc.setdefault("rc", d.run()),
                         daemon=True)
    t.start()
    try:
        deadline = time.time() + 120
        while not marker.exists() and time.time() < deadline:
            time.sleep(0.2)
        assert marker.exists(), "worker never reached the marker epoch"
        # Preemption notice for the second worker.
        srv.attributes["instance/attributes/preempted-workers"] = (
            "127.0.0.1")
        t.join(timeout=180)
        assert not t.is_alive(), "elastic driver did not finish"
    finally:
        srv.stop()
        if t.is_alive():
            d._shutdown_workers()
    assert rc.get("rc") == 0, rc
    res = json.loads(result.read_text())
    assert res["epochs"] == 6
    assert res["final_size"] == 1, res
    assert res["resets"] >= 1, res


def test_discovery_parse_malformed_line_skipped():
    """ADVICE: a garbled slots field degrades to a warning, not a crash."""
    d = HostDiscoveryScript("true")
    hosts = d.parse("hostA:4\nhostB:oops\nhostC\n")
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("hostA", 4), ("hostC", 1)]


def test_is_local_host_fqdn_and_ip():
    """ADVICE: FQDN / resolved-IP references to this machine are local."""
    import socket
    from horovod_tpu.common.net import is_local_host, routable_addr
    assert is_local_host("localhost")
    assert is_local_host("127.0.0.1")
    assert is_local_host(socket.gethostname())
    assert is_local_host(socket.getfqdn())
    addr = routable_addr()
    if addr and addr[0].isdigit():
        assert is_local_host(addr)
    assert not is_local_host("definitely-not-this-host.invalid")


def test_elastic_rendezvous_addr_routable_for_remote_hosts(monkeypatch):
    """ADVICE (medium): with any remote worker, the published rendezvous
    address must be a routable driver address, not 127.0.0.1."""
    drv = ElasticDriver(HostDiscoveryScript("true"),
                        [sys.executable, "-c", "pass"], min_np=1)
    monkeypatch.setattr(drv, "_spawn", lambda *a, **k: None)
    monkeypatch.setattr(drv, "_notify_workers", lambda *a, **k: None)
    try:
        assert drv._new_generation([DiscoveredHost("localhost", 2)])
        assert drv._rdv_addr == "127.0.0.1"
        assert drv._new_generation(
            [DiscoveredHost("localhost", 1),
             DiscoveredHost("remote-worker-1", 1)])
        assert drv._rdv_addr != "127.0.0.1"
        # explicit address always wins
        drv2 = ElasticDriver(HostDiscoveryScript("true"),
                             [sys.executable, "-c", "pass"], min_np=1,
                             rendezvous_addr="10.0.0.7")
        monkeypatch.setattr(drv2, "_spawn", lambda *a, **k: None)
        monkeypatch.setattr(drv2, "_notify_workers", lambda *a, **k: None)
        assert drv2._new_generation([DiscoveredHost("remote-worker-1", 2)])
        assert drv2._rdv_addr == "10.0.0.7"
        drv2.rendezvous.stop()
    finally:
        drv.rendezvous.stop()


# ------------------------------------------------- post-fault exit guard
def _run_guarded(tail: str) -> subprocess.CompletedProcess:
    src = (
        "import atexit, sys\n"
        "atexit.register(lambda: print('EARLY_HOOK_RAN', flush=True))\n"
        "from horovod_tpu.elastic import worker\n"
        "worker._install_exit_guard()\n"
        + tail)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    return subprocess.run([sys.executable, "-c", src], env=env,
                          capture_output=True, text=True, timeout=120)


def test_exit_guard_preserves_exit_codes_and_early_atexit_hooks():
    """The post-fault exit guard ends the process via os._exit (parked
    jax worlds must not reach interpreter finalization), but it must not
    LAUNDER failures into successes: the elastic driver judges workers
    by exit code.  Uncaught SystemExit never reaches sys.excepthook, so
    sys.exit(3) needs the guard's sys.exit wrap to survive; and atexit
    hooks registered before the fault (coverage writers...) still run."""
    res = _run_guarded("sys.exit(3)")
    assert res.returncode == 3, (res.returncode, res.stdout, res.stderr)
    assert "EARLY_HOOK_RAN" in res.stdout, (res.stdout, res.stderr)

    res = _run_guarded("raise RuntimeError('worker failed')")
    assert res.returncode == 1, (res.returncode, res.stdout, res.stderr)
    assert "EARLY_HOOK_RAN" in res.stdout, (res.stdout, res.stderr)

    res = _run_guarded("print('work done', flush=True)")
    assert res.returncode == 0, (res.returncode, res.stdout, res.stderr)
    assert "EARLY_HOOK_RAN" in res.stdout, (res.stdout, res.stderr)
