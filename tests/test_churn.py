"""Churn verbs + scenario runner (ISSUE 12; tier-1, no jax, no spawns).

The churn-script grammar in ``horovod_tpu.testing.faults`` (leave / join /
agent_crash / preempt_notice, round-gated like the fault points' nth gate)
and the ``horovod_tpu.testing.churn.ChurnRunner`` replaying scripts
against the REAL native server — flat and hierarchical.  The scaled
version of these scenarios (to 2048 simulated ranks) rides the
``negotiation_scaling`` bench; the full-stack driver/worker churn lives in
``tests/test_multiprocess.py``.
"""

import pytest

from horovod_tpu.testing.churn import ChurnRunner
from horovod_tpu.testing.faults import (
    CHURN_VERBS, ChurnEvent, parse_churn,
)


# ---------------------------------------------------------------- grammar
def test_churn_event_parse_valid_forms():
    assert ChurnEvent.parse("leave:3@10") == ChurnEvent("leave", "3", 10)
    assert ChurnEvent.parse(" join:*@2 ") == ChurnEvent("join", "*", 2)
    assert ChurnEvent.parse("agent_crash:1@7") == ChurnEvent(
        "agent_crash", "1", 7)
    assert ChurnEvent.parse("preempt_notice:0@4") == ChurnEvent(
        "preempt_notice", "0", 4)
    assert ChurnEvent.parse("rejoin_restore:2@9") == ChurnEvent(
        "rejoin_restore", "2", 9)
    assert set(CHURN_VERBS) == {"leave", "join", "agent_crash",
                                "preempt_notice", "rejoin_restore"}


def test_churn_script_parse_orders_by_round_stably():
    script = parse_churn("join:*@8,leave:1@3,preempt_notice:1@3")
    assert [(e.verb, e.at_round) for e in script] == [
        ("leave", 3), ("preempt_notice", 3), ("join", 8)]
    assert parse_churn("") == [] and parse_churn(None) == []


@pytest.mark.parametrize("bad", [
    "leave:1",                 # no round
    "leave@5",                 # no target
    "vanish:1@5",              # unknown verb
    "leave:*@5",               # '*' is join-only
    "leave:x@5",               # non-integer target
    "leave:1@0",               # rounds are 1-based
    "leave:1@x",               # non-integer round
    "leave:-1@5",              # negative target
])
def test_churn_event_parse_rejects(bad):
    with pytest.raises(ValueError):
        ChurnEvent.parse(bad)


def test_churn_runner_validates_script_against_world():
    with pytest.raises(ValueError):   # agent verbs need agents
        ChurnRunner(4, ranks_per_host=2, hier=False, rounds=10,
                    script=parse_churn("agent_crash:0@5"))
    with pytest.raises(ValueError):   # host index out of range
        ChurnRunner(4, ranks_per_host=2, hier=True, rounds=10,
                    script=parse_churn("preempt_notice:5@5"))
    with pytest.raises(ValueError):   # rank out of range
        ChurnRunner(4, ranks_per_host=2, rounds=10,
                    script=parse_churn("leave:9@5"))
    with pytest.raises(ValueError):   # event beyond the run
        ChurnRunner(4, ranks_per_host=2, rounds=10,
                    script=parse_churn("leave:1@99"))
    with pytest.raises(ValueError):   # host verbs need a host grouping
        ChurnRunner(4, rounds=10,
                    script=parse_churn("preempt_notice:0@5"))


# ----------------------------------------------------------------- runner
def test_churn_runner_flat_leave_and_join_survive():
    """Flat plane: a clean LEAVE mid-run plus a fleet-wide join epoch —
    the run completes with the survivors, no abort, the leaver recorded,
    and per-phase root-service readings across the churn."""
    rep = ChurnRunner(6, ranks_per_host=3, hier=False, rounds=16, warm=3,
                      script=parse_churn("leave:5@5,join:*@10")).run()
    assert rep["survived"] is True, rep
    assert rep["left_ranks"] == [5], rep
    assert rep["root_us_pre"] and rep["root_us_post"], rep
    verbs = [e["verb"] for e in rep["events_fired"]]
    assert verbs == ["leave", "join"], rep["events_fired"]
    # The join epoch fired over the SURVIVORS only.
    join_ev = rep["events_fired"][1]
    assert 5 not in join_ev["ranks"] and len(join_ev["ranks"]) == 5, join_ev
    assert len(rep["phases"]) >= 2, rep["phases"]


def test_churn_runner_hier_preempt_drain_then_agent_death_survives():
    """Hierarchical plane: a preemption notice drains a whole host (its
    ranks depart via clean LEAVEs — the DRAIN → LEAVE path compressed to
    the wire), then the drained host's agent dies.  The fleet survives
    both: zero dead-peer verdicts for the drained host, and a dead agent
    with no live ranks is not a failure."""
    rep = ChurnRunner(
        8, ranks_per_host=4, hier=True, rounds=16, warm=3,
        script=parse_churn("preempt_notice:1@5,agent_crash:1@8")).run()
    assert rep["survived"] is True, rep
    assert rep["left_ranks"] == [4, 5, 6, 7], rep
    assert rep["drained_hosts"] == [1], rep
    assert rep["abort_reason"] is None, rep
    # Post-churn phases kept measuring on the surviving host.
    assert rep["root_us_post"] and rep["root_us_post"] > 0, rep


def test_churn_runner_agent_crash_with_live_ranks_fails_attributed():
    """The control: killing an agent UNDER live ranks is a host-granular
    failure — the run reports it instead of wedging (the surviving host's
    ranks observe the typed abort; the dead host's observe the sever)."""
    rep = ChurnRunner(
        4, ranks_per_host=2, hier=True, rounds=12, warm=3,
        script=parse_churn("agent_crash:1@5")).run()
    assert rep["survived"] is False, rep
    assert rep["abort_reason"], rep
    kinds = " ".join(why for _r, why in rep["failures"])
    assert "abort" in kinds or "severed" in kinds, rep["failures"]


def test_churn_runner_is_jax_free():
    import horovod_tpu.testing.churn as churn
    src = open(churn.__file__).read()
    assert "import jax" not in src


# ---------------------------------------- rejoin_restore verb (ISSUE 14)
def test_churn_runner_validates_rejoin_restore_needs_prior_departure():
    with pytest.raises(ValueError):   # never departed
        ChurnRunner(4, rounds=10,
                    script=parse_churn("rejoin_restore:1@5"))
    with pytest.raises(ValueError):   # departs AFTER the rejoin
        ChurnRunner(4, rounds=10,
                    script=parse_churn("rejoin_restore:1@5,leave:1@8"))
    with pytest.raises(ValueError):   # rank out of range
        ChurnRunner(4, rounds=10,
                    script=parse_churn("leave:9@3,rejoin_restore:9@5"))


def test_churn_rejoin_restore_records_peer_source(tmp_path):
    """The satellite's assertion: a rank that left at round 4 rejoins at
    round 9 as a fresh replacement and restores FROM THE SURVIVORS'
    SHARD SERVERS — the phase output records source=peer, the epoch the
    survivors advanced to after the departure, and zero disk reads."""
    rep = ChurnRunner(
        4, ranks_per_host=2, rounds=14, warm=3,
        script=parse_churn("leave:3@4,rejoin_restore:3@9"),
        state_dir=str(tmp_path)).run()
    assert rep["survived"] is True, rep
    assert rep["left_ranks"] == [3], rep
    (restore,) = rep["restores"]
    assert restore["rank"] == 3, restore
    assert restore["restore_source"] == "peer", restore
    assert restore["disk_reads"] == 0, restore
    # The survivors committed PAST the departure epoch; the rejoiner got
    # exactly that newest epoch, shard-by-shard from the live peers.
    assert restore["restore_epoch"] == rep["state_epoch"] == 2, rep
    assert restore["peer_shards"] >= 1, restore
    # Shard-native optimizer restore (ISSUE 15): the recovered sharded-
    # optimizer saveable re-slices to exactly the rejoiner's 1/N shard.
    assert restore["opt_shard_ok"] is True, restore
    assert restore["opt_shard_len"] == 64, restore
    ev = next(e for e in rep["events_fired"]
              if e["verb"] == "rejoin_restore")
    assert ev["restore_source"] == "peer", ev


def test_churn_rejoin_restore_disk_fallback_without_peer_quorum(tmp_path):
    """serve_state=False models survivors whose shard servers are
    unreachable: no quorum — the rejoiner recovers from the newest
    complete on-disk epoch instead, and the record says so."""
    rep = ChurnRunner(
        4, ranks_per_host=2, rounds=14, warm=3,
        script=parse_churn("leave:3@4,rejoin_restore:3@9"),
        state_dir=str(tmp_path), serve_state=False).run()
    assert rep["survived"] is True, rep
    (restore,) = rep["restores"]
    assert restore["restore_source"] == "disk", restore
    assert restore["disk_reads"] >= 1, restore
    assert restore["restore_epoch"] == 2, restore
