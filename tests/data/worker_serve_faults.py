"""Serving-plane fault-tolerance acceptance worker (ISSUE 20's scripted
chaos scenario).  Launched under the ELASTIC driver (two single-slot
local "hosts") with ``HVD_TPU_FAULT=replica_crash:1@3``: rank 1 dies
UNCLEANLY (os._exit) inside its 3rd dispatched batch — mid-batch, after
dispatch, before results route back — while both replicas are serving a
ramp of 24 concurrent front-door requests.

The hard invariant under test: every ACCEPTED request gets exactly one
terminal response, and the retried ones are BITWISE identical to their
single-request references.  Scripted flow:

- ramp: 24 concurrent clients POST through ``FrontDoor.infer_detailed``
  (retries + idempotent request ids), queued before the first dispatch
  so both ranks form the same deterministic 6 x full-bucket schedule;
- every dispatched batch rides an allreduce-of-zeros liveness probe:
  sum of zeros is world-size invariant (results stay bitwise identical
  after the world shrinks), but it makes each batch a COLLECTIVE
  participant, so rank 1's crash surfaces in the survivor's serve loop
  as a typed peer fault (or the data-plane gloo failure the verdict
  poll resolves) instead of staying invisible to a purely local forward;
- the survivor's ``serve_loop`` fails the interrupted batch RETRYABLY
  (queued requests keep their original deadlines — ``requeued_total``
  pins that), re-raises the typed verdict, and the worker heals through
  the elastic path: ``shutdown() → init()`` re-rendezvouses into the
  shrunk world, the versioned ``load()`` re-arm is a rank-local no-op,
  and the SAME batcher resumes serving;
- the interrupted batch's requests re-enter via front-door retries
  (attempts == 2, same request id), complete bitwise-identical to the
  per-request references, and ZERO accepted requests are lost.

Launched by test_multiprocess.py::test_torovodrun_serving_fault_recovery
under BOTH control planes (flat and --hierarchical-controller); the
proof is the result file the survivor writes.
"""

import json
import os
import threading
import time

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_serve.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.exceptions import (
    HorovodInternalError, HostsUpdatedInterrupt,
)
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.frontdoor import FrontDoor
from horovod_tpu.serve.replica import Replica
from horovod_tpu.serve.resilience import CircuitBreaker

RESULT = os.environ.get("FAULT_RESULT", "")
NREQ = 24                 # 6 full buckets per rank
BUCKET = 4                # single-bucket menu: every batch, pre- and
                          # post-heal, runs the SAME jitted program, so
                          # all results are bitwise-comparable
DEADLINE_MS = 90000.0     # generous: the heal is charged against it


def _write_result(payload: dict):
    tmp = RESULT + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, RESULT)   # atomic: the test never reads a torn file


def apply_fn(params, x):
    return x @ params["w"] + params["b"]


def weights(seed):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(16, 8).astype(np.float32),
            "b": rng.randn(8).astype(np.float32)}


class ProbedReplica(Replica):
    """Replica whose ``forward_batch`` rides a liveness probe: an
    allreduce of zeros before the local forward.  World-size invariant
    (0 + 0 == 0 == 0), so the serving math is untouched by the heal —
    but a dead peer now fails the batch instead of going unnoticed."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.probes = 0

    def forward_batch(self, batch):
        self.probes += 1
        probe = hvd.allreduce(np.zeros(1, np.float32),
                              name=f"serve.sync.{self.probes}", op=hvd.Sum)
        np.testing.assert_array_equal(
            np.asarray(hvd.to_local(probe)).reshape(1),
            np.zeros(1, np.float32))
        return super().forward_batch(batch)


def main():
    hvd.init()
    rank = hvd.rank()
    assert hvd.size() == 2, hvd.size()

    # Weight fan-out: rank 0 owns the tree, rank 1 ends bitwise identical.
    rep = ProbedReplica(apply_fn)
    v1 = weights(1) if rank == 0 else \
        {"w": np.zeros((16, 8), np.float32), "b": np.zeros(8, np.float32)}
    assert rep.load(v1, version=1) is True

    batcher = ContinuousBatcher(max_batch=BUCKET, buckets=(BUCKET,),
                                deadline_ms=DEADLINE_MS, max_inflight=1,
                                queue_depth=64)
    # Breaker effectively disabled: 4 simultaneous retryable failures
    # must RETRY, not fast-fail — the breaker's own state machine is
    # pinned in the jax-free tier (tests/test_serve_faults.py).
    door = FrontDoor(batcher, retries=4, hedge_ms=0.0,
                     breaker=CircuitBreaker(threshold=10000))

    # Per-request references through the SAME bucket-4 program the
    # serving batches use (Replica.forward pads 4 rows onto bucket 4):
    # row i alone must equal row i co-batched, before or after the heal.
    x = np.random.RandomState(7).randn(NREQ, 16).astype(np.float32)
    ref = []
    for i in range(NREQ):
        alone = np.zeros((BUCKET, 16), np.float32)
        alone[0] = x[i]
        ref.append(rep.forward(alone)[0])
    ref = np.stack(ref)

    # ---- ramp: 24 concurrent clients through the front door -------------
    outcomes = [None] * NREQ

    def client(i):
        outcomes[i] = door.infer_detailed(
            x[i], deadline_ms=DEADLINE_MS, request_id=f"req-{i}")

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(NREQ)]
    for t in threads:
        t.start()
    # Admission barrier: every request queued before the first dispatch,
    # so both ranks form the same 6 x full-bucket schedule and the probe
    # allreduces stay lock-step until the scripted crash.
    t0 = time.monotonic()
    while batcher.pending() < NREQ:
        assert time.monotonic() - t0 < 60, batcher.pending()
        time.sleep(0.005)

    stop = threading.Event()

    def watcher():      # cordon the loop once every client is terminal
        for t in threads:
            t.join()
        stop.set()

    threading.Thread(target=watcher, daemon=True).start()

    # ---- serve; heal through the elastic path on the scripted crash -----
    # Rank 1 os._exit(13)s inside its 3rd batch (after its probe): the
    # survivor's 4th probe fails, serve_loop fails THAT batch retryably,
    # preserves the queued two buckets, and re-raises the typed verdict.
    faults_caught = []
    batches = 0
    t_fault = t_ready = None
    while True:
        try:
            batches += rep.serve_loop(batcher, stop=stop, poll_s=0.05,
                                      fault_grace_s=10.0)
            break
        except (HorovodInternalError, HostsUpdatedInterrupt) as verdict:
            t_fault = time.monotonic()
            faults_caught.append([type(verdict).__name__,
                                  list(getattr(verdict, "dead_ranks", []))])
            # Re-rendezvous into the shrunk generation over the surviving
            # host set, then re-arm: re-delivering the serving version is
            # a rank-local no-op on survivors — no broadcast, no restart.
            basics.shutdown()
            basics.init()
            assert rep.load(rep.params, version=rep.version) is False
            assert rep.loads == 1, rep.loads
            t_ready = time.monotonic()
    # Only the survivor gets here (rank 1 died inside forward_batch).

    for t in threads:
        t.join(timeout=120)
    lost = sum(1 for o in outcomes if o is None)
    assert lost == 0, f"{lost} accepted request(s) got no terminal response"
    codes = sorted({o["_code"] for o in outcomes})
    assert codes == [200], [o for o in outcomes if o["_code"] != 200]

    # Bitwise: every response — first-attempt, queued-across-the-heal and
    # retried alike — equals its single-request reference.
    got = np.stack([np.asarray(o["outputs"], np.float32) for o in outcomes])
    np.testing.assert_array_equal(got, ref)

    # Exactly the interrupted bucket retried (same ids, second attempt);
    # the two queued buckets were PRESERVED, not failed.
    retried = [o for o in outcomes if o["attempts"] > 1]
    assert len(retried) == BUCKET, [o["attempts"] for o in outcomes]
    assert all(o["attempts"] == 2 for o in retried), retried
    st = door.stats()
    assert faults_caught and st["replica_faults_total"] == 1, \
        (faults_caught, st)
    assert st["requeued_total"] == 2 * BUCKET, st
    assert st["retries_total"] == BUCKET, st
    assert st["quarantined_total"] == 0, st
    assert st["responses_ok_total"] == NREQ, st
    assert st["responses_error_total"] == 0, st
    assert st["availability"] == 1.0, st
    assert hvd.size() == 1, hvd.size()

    _write_result({
        "ok": True, "lost": lost, "retried": len(retried),
        "batches": batches, "final_size": hvd.size(),
        "faults": faults_caught,
        "requeued": st["requeued_total"],
        "availability": st["availability"],
        "recovery_s": round(t_ready - t_fault, 3),
    })
    print("SERVE_FAULTS_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    assert RESULT, "FAULT_RESULT must point at a writable path"
    main()
