"""Per-process-set sanitizer namespace worker (ISSUE 16 acceptance).

Two singleton tenant sets (A = rank 0, B = rank 1) run collectives
concurrently with world traffic.  The ranks deliberately interleave the
WORLD lane in opposite orders — the cross-set submission-order divergence
the static analyzer flags as HVD111 on this very file — while each
tenant's own stream is clean.

With ``HVD_TPU_SANITIZER=1`` the divergence is attributed to the world
namespace (``seq=0:<i>`` tags) as a fail-fast NegotiationError; each
tenant's collective completes undisturbed and its per-set ledger view
shows exactly its own submission at ``seq=<set>:0``.  With
``HVD_TPU_SANITIZER_STATIC_INDEX`` pointing at this file's emitted index,
the ledger tail names the HVD111 node that flagged the divergent sites
statically.

Prints ``PROCESS_SET_OK`` when attribution lands on the right namespace
and the tenant streams survive.
"""

import os

# Each worker is one rank with ONE cpu device: strip the 8-virtual-device
# flag inherited from the test process, use gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.controller import NegotiationError


def main():
    hvd.init()
    rank = hvd.rank()
    assert hvd.size() == 2, "worker expects -np 2"

    # Both ranks register BOTH sets (registration must be uniform); each
    # rank is the sole member of its own tenant set.
    a_set = hvd.add_process_set([0])
    b_set = hvd.add_process_set([1])
    tenant = a_set if rank == 0 else b_set
    mine = float(rank + 1)

    a = np.ones(4, np.float32)
    b = np.full((4,), 2.0, np.float32)

    try:
        if rank == 0:   # hvd-lint: disable=HVD101  (deliberate divergence)
            ha = hvd.allreduce_async(a, name="world.a")  # hvd-lint: disable=HVD101,HVD102
            t_out = hvd.to_local(hvd.allreduce(  # hvd-lint: disable=HVD101
                np.full((2,), mine, np.float32), name="tenant.t",
                op=hvd.Sum, process_set=a_set))
            hb = hvd.allreduce_async(b, name="world.b")  # hvd-lint: disable=HVD101,HVD102
        else:
            t_out = hvd.to_local(hvd.allreduce(  # hvd-lint: disable=HVD101
                np.full((2,), mine, np.float32), name="tenant.t",
                op=hvd.Sum, process_set=b_set))
            hb = hvd.allreduce_async(b, name="world.b")  # hvd-lint: disable=HVD101,HVD102
            ha = hvd.allreduce_async(a, name="world.a")  # hvd-lint: disable=HVD101,HVD102  (deliberate world-lane order swap)
        # The tenant stream already completed (singleton negotiation) —
        # only the world lane is entangled.
        np.testing.assert_allclose(np.asarray(t_out).reshape(2),
                                   np.full(2, mine, np.float32))
        hvd.synchronize([ha, hb])
        print("PROCESS_SET_MISSED", flush=True)
    except NegotiationError as e:
        msg = str(e)
        # Attributed to the WORLD namespace, at this file's call sites.
        assert "seq=0:" in msg, msg
        assert "site=worker_process_sets.py" in msg, msg
        # NOT attributed to either tenant's namespace.
        assert f"seq={a_set.process_set_id}:" not in msg, msg
        assert f"seq={b_set.process_set_id}:" not in msg, msg

        san = basics._get_state().engine.sanitizer
        assert san is not None
        # This tenant's ledger view: exactly its own clean submission,
        # numbered in its own namespace, untouched by world traffic.
        view = san.tail(process_set=tenant.process_set_id)
        assert [en.name for en in view] == ["tenant.t"], view
        assert view[0].seq == 0 and \
            view[0].process_set == tenant.process_set_id
        scoped = san.render_tail(process_set=tenant.process_set_id)
        assert f"process set {tenant.process_set_id}" in scoped, scoped
        assert f"#{tenant.process_set_id}:0 tenant.t" in scoped, scoped
        # World view holds ONLY the divergent world pair, in this rank's
        # submission order.
        world = [en.name for en in san.tail(process_set=0)]
        want = ["world.a", "world.b"] if rank == 0 \
            else ["world.b", "world.a"]
        assert world == want, world
        # Static linkage: the combined tail names the HVD111 node the
        # whole-package analyzer pinned on these sites before launch.
        tail = san.render_tail()
        assert "HVD111" in tail and "statically" in tail, tail
        print("PROCESS_SET_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
