"""Control-plane fault-tolerance acceptance worker (ISSUE 5's two-process
proof).  Launched with ``HVD_TPU_FAULT=mid_round_exit:1:crash:<nth>`` so
rank 1 dies UNCLEANLY (os._exit) at a deterministic protocol point — after
its request frame is sent, before the response is read: the classic
"died mid-negotiation" shape the pre-v4 control plane answered with an
eternal recv.

Two modes (``FAULT_MODE``):

``static``   plain torovodrun -np 2.  Rank 0 must raise a typed HVD303
             ``PeerFailureError`` naming rank 1 within
             ``HOROVOD_ROUND_TIMEOUT_S`` — including for a waiter that was
             already pending when the peer died (no wedged waiters, no
             wedged InflightRing) — and new work must fail fast instead of
             queueing.  Rank 0 records the proof in ``FAULT_RESULT``
             (a file, not stdout: the launcher reaps survivors after the
             crash and may truncate pipes).

``elastic``  under the elastic driver (two single-slot "hosts" so the
             crashed host can be blacklisted without killing the world).
             The survivor catches the typed error, restores committed
             state, re-initializes, re-rendezvouses into the shrunk
             generation and finishes every epoch; the result file records
             the caught exception types, reset count and final world size.
"""

import json
import os
import time

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.exceptions import (
    HorovodInternalError, PeerFailureError,
)

RESULT = os.environ.get("FAULT_RESULT", "")


def _write_result(payload: dict):
    tmp = RESULT + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, RESULT)   # atomic: the test never reads a torn file


def main_static():
    hvd.init()
    rank = hvd.rank()
    eng = basics._get_state().engine
    round_timeout = float(os.environ.get("HOROVOD_ROUND_TIMEOUT_S", "30"))
    pending = None
    if rank == 0:
        # A waiter that can never complete normally (rank 1 never submits
        # this name): the engine's clean shutdown must settle it with the
        # fault — THE "no wedged waiters" assertion.
        pending = hvd.allreduce_async(np.ones(4, np.float32),
                                      name="never.ready", op=hvd.Sum)
    t_step = time.monotonic()
    try:
        for k in range(100000):
            t_step = time.monotonic()
            out = hvd.allreduce(np.ones(2, np.float32), name="grad",
                                op=hvd.Sum)
            np.testing.assert_allclose(
                np.asarray(hvd.to_local(out)).reshape(2),
                np.full(2, float(hvd.size()), np.float32))
        raise AssertionError("fault never fired")
    except (PeerFailureError, ValueError) as exc:
        # The crash can surface on the blocking step through either plane,
        # whichever loses the race: the typed control-plane abort
        # (PeerFailureError), or — when the dead rank's FINAL frame made a
        # collective ready that it never executed — the data-plane
        # collective failing underneath XLA (ValueError from the gloo
        # transport here; the analogous ICI failure on TPU).  Either way
        # the CONTROL plane must converge on the typed verdict within the
        # round deadline, delivered through every outstanding waiter:
        first_error = type(exc).__name__
        assert rank == 0, "only the survivor should get this far"
        try:
            eng.synchronize(pending, timeout=round_timeout)
            raise AssertionError("never.ready completed?!")
        except PeerFailureError as verdict:
            typed = verdict
        elapsed = time.monotonic() - t_step
        assert typed.dead_ranks == [1], typed.dead_ranks
        assert "HVD303" in str(typed), str(typed)
        assert elapsed < round_timeout, (
            f"typed verdict took {elapsed:.1f}s against a {round_timeout}s "
            f"round deadline")
        # New work fails fast instead of queueing into a dead world.
        t0 = time.monotonic()
        try:
            hvd.allreduce(np.ones(2, np.float32), name="after.death",
                          op=hvd.Sum)
            raise AssertionError("post-fault enqueue did not fail")
        except (PeerFailureError, RuntimeError):
            pass
        assert time.monotonic() - t0 < 5
        _write_result({"ok": True, "mode": "static",
                       "dead_ranks": typed.dead_ranks,
                       "hvd303": "HVD303" in str(typed),
                       "first_error": first_error,
                       "elapsed_s": round(elapsed, 3)})
        print("FAULT_STATIC_OK", flush=True)
    # rank 1 never reaches here (os._exit inside the fault point).


def _control_plane_verdict(exc, grace_s: float = 10.0):
    """Resolve an exception from a blocking collective against the
    engine's control-plane verdict.

    A dying peer races two planes: the typed HVD303 abort (control), and
    the in-flight device collective failing underneath XLA (data — a gloo
    ValueError here, the analogous ICI failure on TPU).  When the data
    plane loses a peer, the engine's fault latch converges within the
    round deadline — so wait for it, and treat the exception as a
    world-failure only when the control plane confirms; anything else is
    a genuine application bug and re-raises."""
    if isinstance(exc, HorovodInternalError):
        return exc
    eng = basics._get_state().engine
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        fault = getattr(eng, "fault", None)
        if fault is not None:
            return fault
        time.sleep(0.05)
    return None


def main_elastic():
    from horovod_tpu.elastic import worker as elastic_worker
    from horovod_tpu.elastic.state import HostsUpdatedInterrupt, ObjectState

    epochs = int(os.environ.get("FAULT_EPOCHS", "8"))
    steps = int(os.environ.get("FAULT_STEPS_PER_EPOCH", "150"))
    hvd.init()
    caught = []
    resets = {"n": 0}
    state = ObjectState(epoch=0)
    elastic_worker.attach_notification_manager(state)

    # Manual retry loop (the @hvd.elastic.run control flow, unrolled so the
    # test can record WHICH exception type triggered each reset — the
    # wrapper swallows it).
    while True:
        try:
            state.sync()
            while state.epoch < epochs:
                # A burst of BLOCKING allreduces per epoch: every one
                # forces at least one lock-step negotiation round, so the
                # nth-armed fault (a ROUND count) fires at a work-
                # determined point mid-run.  Pacing off the idle cycle
                # tick instead would be wall-clock flaky: on a loaded
                # machine all epochs can complete before the idle rounds
                # ever reach nth, and the fault would never fire.
                for i in range(steps):
                    contrib = np.full((2,), float(hvd.rank() + 1),
                                      np.float32)
                    out = hvd.to_local(hvd.allreduce(
                        contrib, name=f"epoch.{state.epoch}.s{i}",
                        op=hvd.Sum))
                    expected = sum(r + 1.0 for r in range(hvd.size()))
                    np.testing.assert_allclose(
                        out, np.full((2,), expected))
                state.epoch += 1
                state.commit()      # host-update check may raise here
                time.sleep(0.1)
            break
        except HostsUpdatedInterrupt:
            caught.append(["HostsUpdatedInterrupt", []])
        except Exception as exc:  # noqa: BLE001 - resolved below
            verdict = _control_plane_verdict(exc)
            if verdict is None:
                raise               # a real bug, not a dead peer
            caught.append([type(verdict).__name__,
                           list(getattr(verdict, "dead_ranks", []))])
            state.restore()
        resets["n"] += 1
        # Reset: tear the world down, re-init (which re-rendezvouses into
        # the next generation over the surviving host set).
        basics.shutdown()
        basics.init()

    if hvd.rank() == 0:
        _write_result({"ok": True, "mode": "elastic",
                       "epochs": state.epoch, "final_size": hvd.size(),
                       "resets": resets["n"], "caught": caught})
        print("FAULT_ELASTIC_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    mode = os.environ.get("FAULT_MODE", "static")
    assert RESULT, "FAULT_RESULT must point at a writable path"
    if mode == "elastic":
        main_elastic()
    else:
        main_static()
