"""Two-slice topology worker: 2 processes × 4 local devices each.

Emulates a cross-slice TPU deployment on CPU (SURVEY.md §5 "DCN
collectives between slices"): the intra-process device group stands in
for one slice's ICI domain, the gloo TCP hop between the two processes
for DCN.  With ``HOROVOD_HIERARCHICAL_ALLREDUCE=1`` the engine runs
RS(local) → AR(cross) → AG(local) — the reduce-scatter and all-gather
stay inside each "slice", only the reduced shards cross the process
boundary — end-to-end through negotiate → fuse → execute.

Launched by test_multiprocess.py::test_hierarchical_two_slices with
``torovodrun -np 2 --hierarchical-allreduce``.
"""

import os
import sys

# 4 virtual CPU devices per process — the "slice" — via the shared
# harness (tests/slice_harness.py): strips the inherited 8-device flag,
# declares the local count through the compat shim (``jax_num_cpu_devices``
# does not exist on jax 0.4.x, where only the XLA flag works), pins CPU +
# gloo.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from slice_harness import configure_slice_world

jax = configure_slice_world(4)

import numpy as np
import horovod_tpu as hvd


def main():
    hvd.init()
    size, local = hvd.size(), hvd.local_size()
    proc = jax.process_index()
    assert jax.process_count() == 2, jax.process_count()
    assert size == 8, f"expected 8 global device ranks, got {size}"
    assert local == 4, f"expected 4 local devices per slice, got {local}"

    from horovod_tpu.common import basics
    eng = basics._get_state().engine
    assert eng.hierarchical_allreduce, \
        "HOROVOD_HIERARCHICAL_ALLREDUCE did not reach the engine"

    # Rank-dependent contributions: this process speaks for 4 global
    # ranks [4*proc, 4*proc+4); the hierarchical allreduce must land on
    # the same global sum a flat one would.
    my_ranks = range(4 * proc, 4 * proc + 4)
    x = np.stack([np.arange(8, dtype=np.float32) + 10.0 * r
                  for r in my_ranks])
    out = hvd.to_local(hvd.allreduce(x, name="hier_ar", op=hvd.Sum))
    expected = sum(np.arange(8, dtype=np.float32) + 10.0 * r
                   for r in range(8))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)

    # Fused batch through the same hierarchical path (two tensors, one
    # cycle) + average op.
    outs = hvd.grouped_allreduce(
        [np.stack([np.full((4,), float(r + 1), np.float32)
                   for r in my_ranks]),
         np.stack([np.full((2, 2), float(r), np.float32)
                   for r in my_ranks])],
        name="hier_grp", op=hvd.Average)
    np.testing.assert_allclose(
        np.asarray(hvd.to_local(outs[0])),
        np.full((4,), np.mean([r + 1.0 for r in range(8)])), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(hvd.to_local(outs[1])),
        np.full((2, 2), np.mean([float(r) for r in range(8)])), rtol=1e-6)

    hvd.barrier()
    print(f"WORKER_OK proc={proc} size={size} local={local}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
