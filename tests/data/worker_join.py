"""Multi-process join() test: uneven batch counts across ranks
(reference: ``hvd.join`` in ``horovod/torch/mpi_ops.py`` — a rank that runs
out of data joins; peers keep reducing and the joined rank auto-contributes
zeros until everyone joins).  Launched by torovodrun in
test_multiprocess.py.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import horovod_tpu as hvd


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    # Rank r processes (r + 1) batches: rank 0 joins first, last rank last.
    n_batches = rank + 1
    x = np.full((4,), float(rank + 1), np.float32)
    for step in range(n_batches):
        out = hvd.to_local(hvd.allreduce(x, name=f"grad_{step}", op=hvd.Sum))
        # Ranks with fewer batches have joined and contribute zeros.
        expected = sum(float(r + 1) for r in range(size) if r + 1 > step)
        np.testing.assert_allclose(out, np.full((4,), expected), rtol=1e-6,
                                   err_msg=f"step={step} rank={rank}")
    last = hvd.join()
    assert last == size - 1, f"join returned {last}, want {size - 1}"

    # The world resumes normal operation after everyone joined.
    out = hvd.to_local(hvd.allreduce(x, name="after_join", op=hvd.Sum))
    expected = sum(float(r + 1) for r in range(size))
    np.testing.assert_allclose(out, np.full((4,), expected), rtol=1e-6)

    # Epoch 2: a joined rank must contribute the reduction IDENTITY (not
    # plain zeros: zeros would clamp a MAX of negatives / zero a PRODUCT),
    # and synthesized grouped entries must batch exactly like the peers'.
    if size >= 2:
        if rank == 0:
            last = hvd.join()
        else:
            active = range(1, size)
            out = hvd.to_local(hvd.allreduce(  # hvd-lint: disable=HVD101
                np.full((3,), -(rank + 2.0), np.float32), name="mx",
                op=hvd.Max))
            np.testing.assert_allclose(
                out, np.full((3,), max(-(r + 2.0) for r in active)))
            out = hvd.to_local(hvd.allreduce(  # hvd-lint: disable=HVD101
                np.full((2,), float(rank + 2), np.float32), name="pr",
                op=hvd.Product))
            np.testing.assert_allclose(
                out, np.full((2,), np.prod([float(r + 2) for r in active])))
            outs = hvd.grouped_allreduce(  # hvd-lint: disable=HVD101  (deliberate: join() covers rank 0)
                [np.full((2,), float(rank), np.float32),
                 np.full((5,), 2.0 * rank, np.float32)],
                name="jgrp", op=hvd.Sum)
            np.testing.assert_allclose(
                hvd.to_local(outs[0]), np.full((2,), sum(float(r) for r in active)))
            np.testing.assert_allclose(
                hvd.to_local(outs[1]), np.full((5,), sum(2.0 * r for r in active)))
            last = hvd.join()
        assert last == size - 1, f"epoch-2 join returned {last}"

    # Epoch 3a (regression): group counters have DIVERGED across ranks
    # (rank 0 ran no grouped calls in epoch 2; others ran one) — a
    # consistent grouped collective must still negotiate, because the group
    # id travels outside the digest-mismatch comparison.
    outs = hvd.grouped_allreduce(
        [np.full((2,), 1.0 + rank, np.float32),
         np.full((3,), 2.0 * rank, np.float32)], name="pg", op=hvd.Sum)
    np.testing.assert_allclose(
        hvd.to_local(outs[0]),
        np.full((2,), sum(1.0 + r for r in range(size))))
    np.testing.assert_allclose(
        hvd.to_local(outs[1]),
        np.full((3,), sum(2.0 * r for r in range(size))))

    # Epoch 3b: collectives that need a joined rank's REAL data must fail
    # fast with a clear error — never silently deliver fabricated values.
    if size >= 2:
        if rank == 0:
            last = hvd.join()
        else:
            try:
                hvd.broadcast(np.ones(3, np.float32), root_rank=0,  # hvd-lint: disable=HVD101
                              name="bc_joined_root")
                raise AssertionError(
                    "broadcast from a joined root did not error")
            except AssertionError:
                raise
            except Exception as exc:
                assert "joined" in str(exc), exc
            try:
                hvd.allgather(np.ones((2,), np.float32), name="ag_joined")  # hvd-lint: disable=HVD101  (deliberate: joined-root error path)
                raise AssertionError("allgather with a joined rank did "
                                     "not error")
            except AssertionError:
                raise
            except Exception as exc:
                assert "joined" in str(exc), exc
            last = hvd.join()
        assert last == size - 1, f"epoch-3 join returned {last}"

    print(f"JOIN_OK rank={rank}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
