"""Multi-process torch-binding worker: per-rank collective semantics +
DistributedOptimizer convergence to identical averaged-gradient updates —
the rebuild's version of the reference's ``test/parallel/test_torch.py``
run under ``horovodrun -np 2`` (SURVEY.md §4).
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import torch

import horovod_tpu.torch as hvd


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # allreduce over rank-dependent tensors
    t = torch.full((4,), float(rank + 1))
    out = hvd.allreduce(t, op=hvd.Sum, name="t_ar")
    expected = sum(float(r + 1) for r in range(size))
    assert torch.allclose(out, torch.full((4,), expected)), (out, expected)

    out = hvd.allreduce(t, op=hvd.Average, name="t_ar_avg")
    assert torch.allclose(out, torch.full((4,), expected / size))

    # broadcast from rank 1
    b = torch.full((3,), float(rank))
    hvd.broadcast_(b, root_rank=1, name="t_bc")
    assert torch.allclose(b, torch.full((3,), 1.0))

    # allgather: rank-striped rows
    g = torch.full((2, 3), float(rank))
    out = hvd.allgather(g, name="t_ag")
    assert out.shape == (2 * size, 3)
    for r in range(size):
        assert torch.allclose(out[2 * r:2 * r + 2], torch.full((2, 3), float(r)))

    # alltoall: rank r sends chunk j to rank j; receives chunk r from all
    t = torch.arange(size * 2, dtype=torch.float32) + 100 * rank
    out = hvd.alltoall(t, name="t_a2a")
    out = out.reshape(-1)
    assert out.shape == (size * 2,), out.shape
    for src in range(size):
        chunk = out[2 * src:2 * src + 2]
        expected_chunk = torch.tensor([2.0 * rank, 2.0 * rank + 1]) + 100 * src
        assert torch.allclose(chunk, expected_chunk), (rank, src, out)

    # reducescatter
    t = torch.ones(size * 2, 3) * (rank + 1)
    out = hvd.reducescatter(t, op=hvd.Sum, name="t_rs")
    out = out.reshape(-1, 3)
    assert out.shape == (2, 3), out.shape
    total = sum(r + 1 for r in range(size))
    assert torch.allclose(out, torch.full((2, 3), float(total)))

    # DistributedOptimizer: rank-dependent data -> identical averaged updates
    torch.manual_seed(42)  # same init on every rank
    model = torch.nn.Linear(4, 2)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    torch.manual_seed(rank)  # per-rank batches
    for _ in range(2):
        x, y = torch.randn(8, 4), torch.randn(8, 2)
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()

    # all ranks must hold identical params now
    for name, p in model.named_parameters():
        gathered = hvd.allgather(p.data.flatten().unsqueeze(0),
                                 name=f"t_check.{name}")
        for r in range(size):
            assert torch.allclose(gathered[r], gathered[0], atol=1e-6), name

    # SyncBatchNorm with rank-dependent batches: running stats identical
    # across ranks and equal to global-batch stats.
    sbn = hvd.SyncBatchNorm(3, momentum=1.0)
    sbn.train()
    torch.manual_seed(100 + rank)
    x = torch.randn(6, 3)
    y = sbn(x)
    y.sum().backward()
    allx = hvd.allgather(x, name="t_sbn_gather")
    gm = allx.mean(0)
    assert torch.allclose(sbn.running_mean, gm, atol=1e-5), (
        sbn.running_mean, gm)
    n = allx.shape[0]
    gv = allx.var(0, unbiased=False) * n / (n - 1)
    assert torch.allclose(sbn.running_var, gv, atol=1e-5)

    # broadcast_optimizer_state parity
    adam = torch.optim.Adam(model.parameters(), lr=1e-3 * (rank + 1))
    hvd.broadcast_optimizer_state(adam, root_rank=0)
    assert adam.param_groups[0]["lr"] == 1e-3

    print(f"WORKER_OK rank={rank}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
