"""Monitor-subsystem acceptance worker (the tentpole's two-process proof).

Run via torovodrun with HOROVOD_MONITOR=1, a small HOROVOD_MONITOR_INTERVAL,
HOROVOD_MONITOR_PORT (rank 0 binds it), HVD_TPU_SANITIZER=1 and a short
HVD_TPU_SANITIZER_TIMEOUT.  Proves, across REAL processes:

1. the coordinator monitor side-channel aggregates both ranks' snapshots
   on every rank (protocol v3 store-and-forward);
2. metrics frames never delay negotiation: the steady-state frame guard
   (zero per-tensor metadata after warm-up) holds with monitoring ON;
3. a forced stall on rank 1 produces an HVD302 report on rank 0 that
   contains *rank 1's* ledger tail (the ROADMAP ledger-exchange item);
4. rank 0's ``/health`` endpoint reflects the stall (503 + status
   "stalled" naming the stuck tensor) and recovers to "ok" afterwards.

Prints ``MONITOR_OK`` on success.
"""

import json
import logging
import os
import time
import urllib.error
import urllib.request

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.utils.logging import get_logger

SHAPES = [(31,), (17,), (64,)]


def train_step(value):
    xs = [np.full(s, value * (i + 1), np.float32)
          for i, s in enumerate(SHAPES)]
    outs = hvd.grouped_allreduce(xs, name="grad", op=hvd.Sum)
    world = hvd.size()
    got = np.asarray(hvd.to_local(outs[0])).reshape(SHAPES[0])
    np.testing.assert_allclose(
        got, np.full(SHAPES[0], world * value, np.float32), rtol=1e-5)


def submit_stall():
    """Both ranks submit stall.t through THIS line: the sanitizer's
    call-site tag must match across ranks (only the timing diverges)."""
    return hvd.allreduce_async(np.ones(4, np.float32), name="stall.t",
                               op=hvd.Sum)


def fetch_health(port):
    """GET /health; a stalled fleet answers 503 with the same JSON body."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return json.loads(exc.read())


def main():
    hvd.init()
    rank = hvd.rank()
    st = basics._get_state()
    eng, ctl, mon = st.engine, st.controller, st.monitor
    assert ctl is not None, "worker needs the torovodrun controller"
    assert mon is not None, "HOROVOD_MONITOR=1 must install the agent"
    assert eng.sanitizer is not None, "HVD_TPU_SANITIZER=1 expected"
    port = int(os.environ["HOROVOD_MONITOR_PORT"])

    # Capture HVD302 reports (the logger does not propagate to root).
    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    get_logger().addHandler(handler)

    # ---- 1. warm up + let snapshots ride the side-channel.  FIXED step
    # count on both ranks (a data-dependent break could diverge and
    # deadlock the blocking collectives).
    for k in range(15):
        train_step(1.0 + k)
        time.sleep(0.08)
    assert mon.aggregator.ranks() == [0, 1], (
        f"rank {rank}: aggregation table is {mon.aggregator.table()}")
    assert ctl.peer_monitor_proto, "server never advertised protocol v3"
    assert ctl.monitor_bytes_sent > 0

    # ---- 2. steady-state frame guard WITH monitoring enabled.
    stats = ctl.cache_stats
    full_before = stats.full_announces
    for k in range(5):
        train_step(50.0 + k)
    assert stats.full_announces == full_before, (
        f"monitoring pushed {stats.full_announces - full_before} cycles "
        f"off the bitvector fast path")
    assert stats.bit_announces >= 5 * len(SHAPES)
    assert eng.negotiation_cycles > 0

    # ---- 3. forced stall on rank 1: rank 0 announces, rank 1 sits out
    # past the sanitizer timeout, then joins in.
    if rank == 0:
        handle = submit_stall()
        deadline = time.time() + 25
        report = None
        while time.time() < deadline and report is None:
            for m in records:
                if "HVD302" in m and "stall.t" in m:
                    report = m
                    break
            time.sleep(0.1)
        assert report is not None, (
            f"no HVD302 report for stall.t; records tail: {records[-5:]}")
        # The tentpole claim: the report carries the LAGGARD's ledger
        # tail, pulled from the cross-rank aggregation table.
        assert "rank 1 last submissions" in report, report
        assert "worker_monitor.py" in report, report
        assert "grad" in report.split("rank 1 last submissions", 1)[1], report
        # /health reflects the stall while it lasts.
        health = fetch_health(port)
        assert health["status"] == "stalled", health
        assert "stall.t" in health["ranks"]["0"]["stalled"], health
    else:
        time.sleep(6.0)         # > HVD_TPU_SANITIZER_TIMEOUT + margin
        handle = submit_stall()
    out = hvd.synchronize(handle)
    np.testing.assert_allclose(np.asarray(hvd.to_local(out)).reshape(4),
                               np.full(4, hvd.size(), np.float32), rtol=1e-5)

    # ---- 4. recovery: /health returns to "ok" once the stall cleared.
    # Both ranks keep running MATCHED train steps (keeping engines — and
    # rank 1's liveness frames — flowing) while rank 0 polls; a barrier
    # here would itself trip the 2s sanitizer stall timeout on the rank
    # that reaches it first.  Fixed iteration count on both ranks.
    recovered = None
    for k in range(20):
        train_step(100.0 + k)
        if rank == 0 and recovered is None:
            health = fetch_health(port)
            if (health["status"] == "ok"
                    and health["ranks"]["0"]["stalled"] == []):
                recovered = health
        time.sleep(0.3)
    if rank == 0:
        assert recovered is not None, fetch_health(port)
        # Straggler attribution present once both ranks reported.
        assert recovered["slowest_rank"] in (0, 1), recovered
    print("MONITOR_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
