"""Multi-process worker: the reference's ``test/parallel`` pattern
(SURVEY.md §4) — every rank runs the same assertions against locally
computed expectations.  Launched by torovodrun in test_multiprocess.py.
"""

import os
import sys

# Each worker is one rank with ONE cpu device: strip the 8-virtual-device
# flag inherited from the test process.
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import horovod_tpu as hvd


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    assert size == int(os.environ["HOROVOD_SIZE"]), (size, os.environ["HOROVOD_SIZE"])
    assert rank == int(os.environ["HOROVOD_RANK"])
    assert jax.process_count() == size

    # allreduce: sum of rank-dependent tensors
    x = np.arange(4, dtype=np.float32) + rank * 10
    out = hvd.to_local(hvd.allreduce(x, name="ar", op=hvd.Sum))
    expected = sum(np.arange(4, dtype=np.float32) + r * 10
                   for r in range(size))
    np.testing.assert_allclose(out, expected, rtol=1e-6)

    # broadcast: everyone gets rank 1's value
    b = np.full((3,), float(rank), np.float32)
    out = hvd.to_local(hvd.broadcast(b, root_rank=1, name="bc"))
    np.testing.assert_allclose(out, np.full((3,), 1.0))

    # allgather
    g = np.full((2,), float(rank), np.float32)
    out = hvd.to_local(hvd.allgather(g, name="ag"))
    assert out.shape == (2 * size,)
    for r in range(size):
        np.testing.assert_allclose(out[2 * r:2 * r + 2], float(r))

    # grouped allreduce with mixed sizes
    outs = hvd.grouped_allreduce(
        [np.full((2,), float(rank + 1), np.float32),
         np.full((3, 2), float(rank + 2), np.float32)],
        name="grp", op=hvd.Average)
    np.testing.assert_allclose(hvd.to_local(outs[0]),
                               np.mean([r + 1 for r in range(size)]))
    np.testing.assert_allclose(hvd.to_local(outs[1]),
                               np.mean([r + 2 for r in range(size)]))

    # out-of-order submission across ranks: rank 0 submits a,b; rank 1
    # submits b,a — negotiation must still execute them consistently.
    names = ["ooo_a", "ooo_b"] if rank == 0 else ["ooo_b", "ooo_a"]
    hs = [hvd.allreduce_async(np.ones((2,), np.float32) * (i + 1), name=n,
                              op=hvd.Sum)
          for i, n in enumerate(names)]
    res = {n: hvd.to_local(r)
           for n, r in zip(names, hvd.synchronize(hs))}
    # rank r submitted value (position+1); global sum differs per name:
    # ooo_a: rank0 pos0 (1), rank1 pos1 (2) -> 3 (for size 2)
    if size == 2:
        np.testing.assert_allclose(res["ooo_a"], np.full((2,), 3.0))
        np.testing.assert_allclose(res["ooo_b"], np.full((2,), 3.0))

    hvd.barrier()
    # staggered submission: rank 0 waits, others submit first
    if rank == 0:
        import time
        time.sleep(0.3)
    out = hvd.to_local(hvd.allreduce(np.full((2,), 1.0, np.float32),
                                     name="late", op=hvd.Sum))
    np.testing.assert_allclose(out, np.full((2,), float(size)))

    # elastic object state sync via broadcast_object
    obj = hvd.broadcast_object({"rank_was": rank}, root_rank=0)
    assert obj == {"rank_was": 0}

    # allgather_object: ragged pickled payloads, every rank gets the list
    objs = hvd.allgather_object({"r": rank, "pad": "y" * (5 * (rank + 1))})
    assert [o["r"] for o in objs] == list(range(size)), objs

    # Sub-process-set collective: only ranks 0,1 participate (exercises the
    # required-count negotiation — non-members never announce the name).
    if size >= 3:
        ps = hvd.add_process_set([0, 1])
        if ps.included(rank):
            out = hvd.to_local(hvd.allreduce(
                np.full((2,), float(rank + 1), np.float32), name="subset",
                op=hvd.Sum, process_set=ps))
            np.testing.assert_allclose(out, np.full((2,), 3.0))
        hvd.barrier()

        # Same tensor name on two DISJOINT sets concurrently: negotiation
        # wire names are namespaced per set, so set A's readiness can never
        # merge with set B's and fire a collective before all members
        # announced (and non-members must not accumulate stale ready names).
        ps_b = hvd.add_process_set(list(range(2, size)))
        for step in range(3):  # repeat: stale-readiness bugs bite on reuse
            if ps.included(rank):
                out = hvd.to_local(hvd.allreduce(
                    np.full((2,), 1.0, np.float32), name="dup",
                    op=hvd.Sum, process_set=ps))
                np.testing.assert_allclose(out, np.full((2,), 2.0))
            else:
                out = hvd.to_local(hvd.allreduce(
                    np.full((2,), 10.0, np.float32), name="dup",
                    op=hvd.Sum, process_set=ps_b))
                np.testing.assert_allclose(
                    out, np.full((2,), 10.0 * (size - 2)))
        hvd.barrier()

    # Ragged alltoall (DLRM-style uneven embedding exchange, SURVEY.md §2c
    # config #5): rank r sends (r + j + 1) rows of value 100*r + j to rank j.
    dim = 3
    my_splits = np.array([rank + j + 1 for j in range(size)], np.int64)
    payload = np.concatenate(
        [np.full((rank + j + 1, dim), 100.0 * rank + j, np.float32)
         for j in range(size)], axis=0)
    out, rsplits = hvd.alltoall(payload, splits=my_splits, name="a2av")
    np.testing.assert_array_equal(
        rsplits, np.array([r + rank + 1 for r in range(size)], np.int64))
    expected = np.concatenate(
        [np.full((r + rank + 1, dim), 100.0 * r + rank, np.float32)
         for r in range(size)], axis=0)
    np.testing.assert_array_equal(out, expected)

    # ASYNC ragged alltoall: same exchange as above through the async
    # handle — size exchange in flight at submit, payload chases it.
    h = hvd.alltoall_async(payload, splits=my_splits, name="a2av_async")
    out2, rsplits2 = hvd.synchronize(h)
    np.testing.assert_array_equal(rsplits2, rsplits)
    np.testing.assert_array_equal(out2, expected)

    # JAX DistributedOptimizer in per-process mode: the eager update must
    # average RANK-DEPENDENT gradients through the engine (a plain-jit
    # train step silently skipping the reduce was code-review finding r3#1).
    import optax
    params = {"w": np.zeros((3,), np.float32)}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    opt_state = opt.init(params)
    grads = {"w": np.full((3,), float(rank + 1), np.float32)}
    updates, opt_state = opt.update(grads, opt_state, params)
    mean_grad = np.mean([r + 1.0 for r in range(size)])
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               np.full((3,), -mean_grad), rtol=1e-6)

    # The same update under a bare jax.jit must raise, not silently skip
    # the reduce.
    import jax as _jax
    try:
        _jax.jit(lambda g, s, p: opt.update(g, s, p))(grads, opt_state, params)
        raise AssertionError("expected RuntimeError for jit-traced "
                             "allreduce_gradients in multi-process mode")
    except RuntimeError as e:
        assert "shard_map" in str(e)

    # backward_passes_per_step=2 eagerly: two rank-dependent micro-grads
    # accumulate locally; the k-th update applies the cross-rank mean.
    opt2 = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    st2 = opt2.init(params)
    g1 = {"w": np.full((3,), float(rank + 1), np.float32)}
    g2 = {"w": np.full((3,), float(3 * (rank + 1)), np.float32)}
    u1, st2 = opt2.update(g1, st2, params)
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)  # accumulate step
    u2, st2 = opt2.update(g2, st2, params)
    expected = -np.mean([(r + 1 + 3 * (r + 1)) / 2.0 for r in range(size)])
    np.testing.assert_allclose(np.asarray(u2["w"]),
                               np.full((3,), expected), rtol=1e-6)

    # DistributedOptimizer over a SUBSET process set, eagerly: members
    # average over the set only (advisor r3: _reduce dropped process_set,
    # reducing over the global world and hanging non-members).
    if size >= 3:
        ps_opt = ps  # the [0, 1] set registered above
        if ps_opt.included(rank):
            opt3 = hvd.DistributedOptimizer(optax.sgd(1.0),
                                            process_set=ps_opt)
            st3 = opt3.init(params)
            g3 = {"w": np.full((3,), float(rank + 1), np.float32)}
            u3, st3 = opt3.update(g3, st3, params)
            # mean over ranks {0,1} = (1+2)/2, NOT over the full world.
            np.testing.assert_allclose(np.asarray(u3["w"]),
                                       np.full((3,), -1.5), rtol=1e-6)
        hvd.barrier()

    print(f"WORKER_OK rank={rank}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
