"""Simulated-load autoscaling acceptance worker (ISSUE 10) — jax-free.

A synthetic elastic "trainer" exercising the REAL wire stack end to end —
versioned rendezvous, native lock-step negotiation (flat or through a real
per-host ``HostAgent``), MON1 monitor side-channel + rank-0 HTTP exporter,
DRAIN notifications and protocol-v6 clean LEAVEs — without the jax data
plane, so the multiprocess scenario test can grow/shrink worlds in
seconds.  The load is scripted through files in ``AUTOSCALE_DIR``:

- ``load``       float: synthetic queue depth each rank reports (0 = idle;
                 also freezes the fake cycle counter, so the policy's
                 idle detector sees zero progress)
- ``straggler``  int rank whose fake cycle time inflates 100x ("" = none)
- ``done``       existence ends the run: every worker leaves cleanly and
                 exits 0 (the driver classifies the first non-draining
                 exit 0 as job success)

Per generation each worker: fetches its assignment, (hierarchical mode)
starts its host's agent, connects a real ``TCPController``, attaches a
real ``MonitorAgent`` over a duck-typed fake engine (rank 0 serves
``/health`` on ``HOROVOD_MONITOR_PORT`` — the driver's policy input), and
loops lock-step rounds.  ``DrainRequested`` → clean LEAVE → exit 0;
``HostsUpdatedInterrupt`` → clean LEAVE → re-rendezvous into the next
generation.
"""

import os
import sys
import time

from horovod_tpu.common.controller import TCPController
from horovod_tpu.common.exceptions import (
    DrainRequested, HorovodInternalError, HostsUpdatedInterrupt,
)
from horovod_tpu.elastic import rendezvous as rdv
from horovod_tpu.elastic import worker as ew
from horovod_tpu.monitor.agent import MonitorAgent

DIR = os.environ["AUTOSCALE_DIR"]
HIER = os.environ.get("HOROVOD_HIERARCHICAL_CONTROLLER", "") == "1"
MONITOR_PORT = int(os.environ.get("HOROVOD_MONITOR_PORT", "0"))

# Generation-surviving host agent (ISSUE 12): keyed on the HOST (this
# process), not a rendezvous generation — created once on the stable
# per-host port the driver ships in the assignment, then re-formed per
# generation via new_generation.  Mirrors basics.init/shutdown.
_agent = None


def _read(name, default=""):
    try:
        with open(os.path.join(DIR, name)) as fh:
            return fh.read().strip()
    except OSError:
        return default


class _FakeQueue:
    def __init__(self):
        self.depth = 0

    def pending_count(self):
        return self.depth


class _FakeEngine:
    """Duck-typed engine surface for MonitorAgent's collectors: the
    scripted load/straggler values flow through the SAME snapshot fields
    a real engine publishes (cycle_us_avg, cycle, hvd_queue_pending)."""

    def __init__(self):
        self.cycle_count = 0
        self.cycle_us_total = 0.0
        self._cycle_index = 0
        self.last_cycle_ts = time.time()
        self.negotiation_us_total = 0.0
        self.negotiation_cycles = 0
        # The autoscaler's idle detector reads this WORK counter (via
        # hvd_pipeline_dispatches_total): it advances only when batches
        # actually dispatch — exactly like the real engine's, whose cycle
        # index ticks on idle rounds too.
        self.pipeline_dispatches = 0
        self.queue = _FakeQueue()
        self.monitor = None

    def tick(self, cycle_us, busy):
        self.cycle_count += 1
        self.cycle_us_total += cycle_us
        self.last_cycle_ts = time.time()
        if busy:
            self._cycle_index += 1
            self.pipeline_dispatches += 1


class E:
    def __init__(self, name):
        import numpy as np
        self.name = name
        self.tensor = np.zeros((2, 4), np.float32)
        self.group_id = -1


def one_generation(mgr):
    """Run one rendezvous generation; returns True to re-rendezvous,
    False to exit 0."""
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
    min_v = 0 if ew._current_version is None else ew._current_version + 1
    a = rdv.fetch_assignment(addr, port, ew.identity(),
                             min_version=min_v, timeout_s=120)
    ew._current_version = int(a["version"])
    rank, size = int(a["rank"]), int(a["size"])
    ctl_port = int(a["controller_port2"]) or int(a["controller_port"]) + 1
    coord = a["controller_addr"]

    connect_addr, connect_port, server_port = coord, ctl_port, None
    if HIER:
        from horovod_tpu.common.host_agent import HostAgent
        global _agent
        cross = int(a["cross_rank"])
        agent_port = int(a.get("agent_port") or ctl_port + 1 + cross)
        if int(a["local_rank"]) == 0:
            reused = False
            if _agent is not None and _agent.port == agent_port:
                try:
                    _agent.new_generation(coord, ctl_port, [rank],
                                          host_index=cross)
                    reused = True
                except RuntimeError:
                    pass          # wedged old thread: replace the agent
            if not reused:
                if _agent is not None:
                    _agent.stop()
                _agent = HostAgent(agent_port, coord, ctl_port, [rank],
                                   host_index=cross).start()
            print(f"[worker {ew.identity()}] agent generation "
                  f"{_agent.stats.generations} on port {_agent.port}",
                  flush=True)
        connect_addr, connect_port = "127.0.0.1", agent_port
        if rank == 0:
            server_port = ctl_port
    elif rank == 0:
        server_port = ctl_port

    eng = _FakeEngine()
    ctl = TCPController(connect_addr, connect_port, rank=rank, world=size,
                        stall_warn_s=1e9, cache_capacity=256,
                        round_timeout_s=30.0, server_port=server_port)
    mon = MonitorAgent(engine=eng, controller=ctl, rank=rank, world=size,
                       interval_s=0.15)
    if rank == 0 and MONITOR_PORT:
        mon.serve_http(MONITOR_PORT)
    print(f"[worker {ew.identity()}] generation {a['version']} "
          f"rank={rank}/{size}", flush=True)

    step = 0
    try:
        while True:
            load = float(_read("load", "0") or 0)
            straggler = _read("straggler", "")
            busy = load > 0
            cycle_us = 100.0
            if straggler and int(straggler) == rank:
                cycle_us = 10000.0
            eng.queue.depth = int(load)
            # One lock-step negotiation round (a fresh entry while busy,
            # an empty round while idle — the monitor frames ride either).
            entries = [E(f"g{a['version']}.s{step}")] if busy else []
            pending = list(entries)
            for _ in range(50):
                ready, errs = ctl.negotiate(pending)
                got = {e.name for e in ready}
                pending = [e for e in pending if e.name not in got]
                if not pending:
                    break
            eng.tick(cycle_us, busy)
            step += 1
            if os.path.exists(os.path.join(DIR, "done")):
                return False
            # Checkpoint pacing (ISSUE 12): the driver pings COMMIT just
            # before executing a scale/preemption decision — the synthetic
            # trainer's "commit" is a log line the scenario test asserts.
            if mgr.consume_commit_request():
                print(f"[worker {ew.identity()}] commit requested by the "
                      f"driver (checkpoint pacing)", flush=True)
            mgr.raise_if_updated()
            time.sleep(0.05)
    except DrainRequested:
        print(f"[worker {ew.identity()}] drain requested -> clean LEAVE",
              flush=True)
        return False
    except HostsUpdatedInterrupt:
        print(f"[worker {ew.identity()}] hosts updated -> re-rendezvous",
              flush=True)
        return True
    except HorovodInternalError as exc:
        # The old generation's coordinator went away mid-round (its rank-0
        # left first): re-rendezvous, exactly like the real elastic path.
        print(f"[worker {ew.identity()}] control plane ended ({exc}); "
              f"re-rendezvous", flush=True)
        return True
    finally:
        mon.close()
        ctl.leave()          # best-effort clean departure (protocol v6)
        ctl.shutdown()
        # The host agent is NOT stopped: it survives into the next
        # rendezvous generation (new_generation re-forms its links).
        if _agent is not None:
            _agent.end_generation()


def main():
    mgr = ew.WorkerNotificationManager()
    ew._manager = mgr
    while one_generation(mgr):
        pass
    print(f"[worker {ew.identity()}] exiting 0", flush=True)


if __name__ == "__main__":
    sys.exit(main())
