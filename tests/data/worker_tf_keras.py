"""Multi-process TF/Keras worker: rank-dependent collectives through the TF
binding, DistributedGradientTape averaging, and an mnist-style Keras fit
with cross-rank weight sync (reference: ``test/parallel/test_tensorflow.py``
+ ``test_tensorflow2_keras.py`` — SURVEY.md §4).  Launched by torovodrun in
test_multiprocess.py.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import tensorflow as tf
import keras

import horovod_tpu.tensorflow as hvd
import horovod_tpu.keras as khvd
from horovod_tpu.keras import callbacks as kcb


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Rank-dependent allreduce through the TF surface.
    t = tf.constant([1.0, 2.0]) * float(rank + 1)
    out = hvd.allreduce(t, name="tf_ar", op=hvd.Sum)
    scale = sum(r + 1 for r in range(size))
    np.testing.assert_allclose(out.numpy(), np.array([1.0, 2.0]) * scale,
                               rtol=1e-6)

    # DistributedGradientTape: grads averaged across ranks.
    x = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(x * x) * float(rank + 1)
    tape = hvd.DistributedGradientTape(tape)
    (grad,) = tape.gradient(loss, [x])
    expected = np.array([2.0, 4.0]) * np.mean([r + 1 for r in range(size)])
    np.testing.assert_allclose(grad.numpy(), expected, rtol=1e-6)

    # broadcast_variables: everyone ends with rank 0's values.
    v = tf.Variable(np.full((3,), float(rank + 10), np.float32))
    hvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), np.full((3,), 10.0))

    # backward_passes_per_step=2 with RANK-DEPENDENT micro-grads must equal
    # one bpps=1 step on the locally pre-averaged gradient (VERDICT r2 #5:
    # local gradient aggregation, reference gradient_aggregation_eager.py).
    va = tf.Variable([1.0, -1.0])
    vb = tf.Variable([1.0, -1.0])
    opt2 = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.5),
                                    backward_passes_per_step=2)
    opt1 = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.5))
    g1 = tf.constant([0.1, 0.2]) * float(rank + 1)
    g2 = tf.constant([0.3, -0.1]) * float(rank + 1)
    opt2.apply_gradients([(g1, va)])
    np.testing.assert_allclose(va.numpy(), [1.0, -1.0])  # no update yet
    opt2.apply_gradients([(g2, va)])        # reduces accumulated average
    opt1.apply_gradients([((g1 + g2) / 2.0, vb)])
    np.testing.assert_allclose(va.numpy(), vb.numpy(), rtol=1e-6,
                               err_msg="bpps=2 != pre-averaged bpps=1")

    # mnist-style Keras fit: per-rank data shards, distributed optimizer,
    # broadcast + metric-average callbacks; ranks must end bit-identical.
    rng = np.random.RandomState(100 + rank)   # DIFFERENT shard per rank
    X = rng.randn(128, 8).astype(np.float32)
    y = (X.sum(axis=1, keepdims=True) > 0).astype(np.float32)

    keras.utils.set_random_seed(rank + 1)     # DIFFERENT init per rank
    model = keras.Sequential([
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(1, activation="sigmoid"),
    ])
    opt = khvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1))
    model.compile(optimizer=opt, loss="binary_crossentropy")
    hist = model.fit(X, y, batch_size=32, epochs=2, verbose=0, shuffle=False,
                     callbacks=[kcb.BroadcastGlobalVariablesCallback(0),
                                kcb.MetricAverageCallback()])
    assert len(hist.history["loss"]) == 2

    # Weight sync check: allgather a digest of the flattened weights.
    flat = np.concatenate([w.numpy().ravel() for w in model.weights])
    digest = np.array([flat.sum(), np.abs(flat).sum()], np.float64)
    gathered = np.asarray(hvd.allgather(
        tf.constant(digest), name="wdigest").numpy()).reshape(size, 2)
    for r in range(size):
        np.testing.assert_allclose(gathered[r], gathered[0], rtol=1e-10,
                                   err_msg="ranks diverged after fit")

    print(f"TF_OK rank={rank}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
