"""ZeRO-sharded data-plane worker (ISSUE 15 acceptance): the full
``DistributedOptimizer(sharded=True)`` pipeline across REAL processes —
per-bucket reduce-scatter of fused gradients, the inner optax update on
this rank's 1/N shard only, allgather of the updated deltas.

Proves, end to end through negotiate → fuse → execute:

- parameters after 10 steps on the same gradient stream are BITWISE
  identical to the replicated ``sharded=False`` path (2 ranks: one
  floating add per element, so reduction order cannot drift — the
  documented caveat only bites at wider worlds);
- optimizer-state bytes on this rank scale ~1/world (adam's mu+nu live
  only for the shard; the replicated path holds the full tree);
- pad+slice edges ride along: a non-divisible leaf, a scalar leaf and a
  bf16 leaf are all in the tree;
- the sharded ops carry their own fusion-key/digest dimension (the
  compiled reduce-scatter program count is additive, never cross-served),
  and the steady-state warm path still rides the pinned ~13B bitvector
  frame (no per-tensor metadata re-announces, request bytes flat);
- the scatter → update → gather pipeline buckets engage when
  HOROVOD_PIPELINE_CHUNK is set (more than one bucket's worth of RS/AG
  groups per step) with results unchanged.

Launched by test_multiprocess.py::test_torovodrun_sharded_optimizer with
``torovodrun -np 2`` — flat AND --hierarchical-controller.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.common import basics

STEPS = 10


def make_params():
    """Mixed tree: non-divisible (257 % 2 != 0), scalar, bf16 — the
    pad+slice edge cases ride the acceptance run itself."""
    return {
        "w1": jnp.asarray(np.linspace(-1.0, 1.0, 257), jnp.float32),
        "w2": jnp.asarray(np.linspace(0.5, -0.5, 128).reshape(16, 8),
                          jnp.float32),
        "scalar": jnp.asarray(0.25, jnp.float32),
        "half": jnp.asarray(np.linspace(-2.0, 2.0, 66), jnp.bfloat16),
    }


def grad_stream(step, rank):
    """Deterministic per-rank gradient stream — both paths replay it."""
    rng = np.random.RandomState(1000 * (rank + 1) + step)
    return {
        "w1": jnp.asarray(rng.randn(257), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 8), jnp.float32),
        "scalar": jnp.asarray(rng.randn(), jnp.float32),
        "half": jnp.asarray(rng.randn(66), jnp.bfloat16),
    }


def train(opt, rank, steps=STEPS):
    params = make_params()
    state = opt.init(params)
    for s in range(steps):
        grads = grad_stream(s, rank)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return jax.device_get(params), state


def opt_state_bytes(state):
    from horovod_tpu.jax.optimizer import ShardedOptimizerState
    if isinstance(state, ShardedOptimizerState):
        return state.opt_state_bytes()
    return sum(int(l.nbytes) for l in jax.tree_util.tree_leaves(state)
               if hasattr(l, "nbytes"))


def main():
    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats

    inner = optax.adam(1e-2)

    # ---- replicated baseline --------------------------------------------
    p_rep, s_rep = train(hvd.DistributedOptimizer(inner, sharded=False),
                         rank)
    rep_bytes = opt_state_bytes(s_rep.inner_state)

    # ---- sharded path: bitwise parity + 1/N state ------------------------
    rs_misses0 = eng.cache.misses
    p_sh, s_sh = train(hvd.DistributedOptimizer(inner, sharded=True), rank)
    for k in sorted(p_rep):
        np.testing.assert_array_equal(p_rep[k], p_sh[k])   # BITWISE
    sh_bytes = opt_state_bytes(s_sh)
    # mu+nu shard ≈ replicated/world; padding adds at most world-1 elems
    # per leaf per moment, count scalars are replicated.
    n_leaves = len(p_rep)
    slack = 2 * n_leaves * world * 8 + 64 * n_leaves
    assert sh_bytes <= rep_bytes / world + slack, (sh_bytes, rep_bytes)
    assert eng.cache.misses > rs_misses0, \
        "sharded programs never compiled (did the RS/AG legs run?)"

    # ---- steady-state warm path: frames stay the pinned bitvector -------
    opt = hvd.DistributedOptimizer(inner, sharded=True)
    params = make_params()
    state = opt.init(params)
    for s in range(3):                       # warm-up: learn slots
        updates, state = opt.update(grad_stream(s, rank), state, params)
        params = optax.apply_updates(params, updates)
    full_before = st.full_announces
    bytes_before = ctl.bytes_sent
    rounds_before = ctl.rounds
    for s in range(5):
        updates, state = opt.update(grad_stream(10 + s, rank), state,
                                    params)
        params = optax.apply_updates(params, updates)
    assert st.full_announces == full_before, (
        f"sharded steady state sent per-tensor metadata: "
        f"{st.full_announces - full_before} full announces")
    per_round = (ctl.bytes_sent - bytes_before) \
        / max(1, ctl.rounds - rounds_before)
    assert per_round <= 32, (
        f"sharded warm-path request grew to {per_round}B/round")

    # ---- chunked pipeline: >1 bucket, results unchanged ------------------
    eng.pipeline_chunk_bytes = 512            # w1 alone exceeds one bucket
    opt2 = hvd.DistributedOptimizer(inner, sharded=True)
    p2, s2 = train(opt2, rank)
    assert len(s2.plan.buckets) > 1, s2.plan.buckets
    for k in sorted(p_rep):
        np.testing.assert_array_equal(p_rep[k], p2[k])
    eng.pipeline_chunk_bytes = 0

    hvd.barrier()
    print(f"SHARDED_OK rank={rank} state_bytes={sh_bytes} "
          f"rep_bytes={rep_bytes} per_round={per_round:.1f}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
