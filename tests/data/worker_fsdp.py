"""Full-parameter-sharding worker (ISSUE 18 acceptance): the complete
``DistributedOptimizer(sharded="full")`` ZeRO-3/FSDP pipeline across REAL
processes — per-step rematerialization of full parameters through the
PREFETCH-lane allgather pipeline, per-bucket reduce-scatter of gradients
straight into this rank's 1/N shard, shard-local inner update on the
RESIDENT parameter shards.

Proves, end to end through negotiate → fuse → execute:

- parameters after 10 steps on the same gradient stream are BITWISE
  identical to the replicated ``sharded=False`` path (2 ranks: one
  floating add per element, so reduction order cannot drift);
- resident bytes (parameter shards + optimizer state) scale ~1/world
  against the replicated params + full optimizer tree;
- with the chunked pipeline armed (>1 bucket) the prefetch lane engages:
  ``prefetch_dispatches`` counts PREFETCH-lane batches and
  ``prefetch_overlapped`` proves bucket k+1's gather was dispatched
  before bucket k settled — the overlap acceptance criterion;
- pad+slice edges ride along: a non-divisible leaf, a scalar leaf and a
  bf16 leaf are all in the tree;
- the steady-state warm path — gather_params + update every step, with
  prefetch armed — still rides the pinned ~13B bitvector frame (no
  per-tensor re-announces, request bytes flat);
- the shard-native elastic form round-trips: ``hvd_sharded_saveable`` →
  ``load_sharded_saveable`` restores bitwise-identical parameter shards
  (the resident shard IS the checkpoint shard).

Launched by test_multiprocess.py::test_torovodrun_full_sharding with
``torovodrun -np 2`` — flat AND --hierarchical-controller.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.jax.optimizer import load_sharded_saveable

STEPS = 10


def make_params():
    """Mixed tree: non-divisible (257 % 2 != 0), scalar, bf16 — the
    pad+slice edge cases ride the acceptance run itself."""
    return {
        "w1": jnp.asarray(np.linspace(-1.0, 1.0, 257), jnp.float32),
        "w2": jnp.asarray(np.linspace(0.5, -0.5, 128).reshape(16, 8),
                          jnp.float32),
        "scalar": jnp.asarray(0.25, jnp.float32),
        "half": jnp.asarray(np.linspace(-2.0, 2.0, 66), jnp.bfloat16),
    }


def grad_stream(step, rank):
    """Deterministic per-rank gradient stream — both paths replay it."""
    rng = np.random.RandomState(1000 * (rank + 1) + step)
    return {
        "w1": jnp.asarray(rng.randn(257), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 8), jnp.float32),
        "scalar": jnp.asarray(rng.randn(), jnp.float32),
        "half": jnp.asarray(rng.randn(66), jnp.bfloat16),
    }


def train_replicated(inner, rank, steps=STEPS):
    opt = hvd.DistributedOptimizer(inner, sharded=False)
    params = make_params()
    state = opt.init(params)
    for s in range(steps):
        updates, state = opt.update(grad_stream(s, rank), state, params)
        params = optax.apply_updates(params, updates)
    return jax.device_get(params), state


def train_full(inner, rank, steps=STEPS):
    """The FSDP loop: forward rematerializes full params through the
    prefetch pipeline, backward reduce-scatters into the shard — no
    replicated parameter or gradient tree survives a step."""
    opt = hvd.DistributedOptimizer(inner, sharded="full")
    state = opt.init(make_params())
    for s in range(steps):
        full = state.gather_params()     # forward half (prefetch lane)
        assert set(full) == {"w1", "w2", "scalar", "half"}
        del full                         # gathered buffers die with the step
        _, state = opt.update(grad_stream(s, rank), state)
    return state


def tree_bytes(tree):
    return sum(int(l.nbytes) for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "nbytes"))


def main():
    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats

    inner = optax.adam(1e-2)

    # ---- replicated baseline --------------------------------------------
    p_rep, s_rep = train_replicated(inner, rank)
    rep_resident = tree_bytes(p_rep) + tree_bytes(s_rep.inner_state)

    # ---- FSDP, chunked so >1 bucket: parity + 1/N + prefetch overlap ----
    eng.pipeline_chunk_bytes = 512        # w1 alone exceeds one bucket
    pf0, ov0 = eng.prefetch_dispatches, eng.prefetch_overlapped
    state = train_full(inner, rank)
    assert len(state.plan.buckets) > 1, state.plan.buckets
    p_full = state.gather_params()
    for k in sorted(p_rep):
        np.testing.assert_array_equal(p_rep[k], p_full[k])   # BITWISE
    assert eng.prefetch_dispatches > pf0, \
        "no allgather rode the PREFETCH lane"
    assert eng.prefetch_overlapped > ov0, \
        "bucket k+1's gather never overlapped bucket k (prefetch depth?)"

    # ---- resident bytes ≈ 1/N (params + opt state) ----------------------
    resident = state.resident_bytes()
    n_leaves = len(p_rep)
    # padding ≤ world-1 elems/leaf for params and each adam moment;
    # replicated step counters add a constant per leaf.
    slack = 3 * n_leaves * world * 8 + 64 * n_leaves
    assert resident <= rep_resident / world + slack, \
        (resident, rep_resident)

    # ---- shard-native elastic form: save → load → bitwise shards --------
    saved = state.hvd_sharded_saveable()
    assert saved.get("__hvd_full_sharded__") == 1
    revived = load_sharded_saveable(saved, rank, world)
    for b, shards in enumerate(state.param_shards):
        for j, s in enumerate(shards):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(s)),
                np.asarray(jax.device_get(revived.param_shards[b][j])))
    eng.pipeline_chunk_bytes = 0

    # ---- steady-state warm path with prefetch armed: frames stay 13B ---
    opt = hvd.DistributedOptimizer(inner, sharded="full")
    wstate = opt.init(make_params())
    for s in range(3):                    # warm-up: learn slots
        wstate.gather_params()
        _, wstate = opt.update(grad_stream(s, rank), wstate)
    full_before = st.full_announces
    bytes_before = ctl.bytes_sent
    rounds_before = ctl.rounds
    for s in range(5):
        wstate.gather_params()
        _, wstate = opt.update(grad_stream(10 + s, rank), wstate)
    assert st.full_announces == full_before, (
        f"FSDP steady state sent per-tensor metadata: "
        f"{st.full_announces - full_before} full announces")
    per_round = (ctl.bytes_sent - bytes_before) \
        / max(1, ctl.rounds - rounds_before)
    assert per_round <= 32, (
        f"FSDP warm-path request grew to {per_round}B/round")

    hvd.barrier()
    print(f"FSDP_OK rank={rank} resident={resident} "
          f"rep_resident={rep_resident} "
          f"prefetch={eng.prefetch_dispatches} "
          f"overlapped={eng.prefetch_overlapped} "
          f"per_round={per_round:.1f}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
