"""Sanitizer divergence worker: ranks deliberately submit the SAME two
allreduces in OPPOSITE order from different call sites.

Shapes, dtypes and ops all match, so plain negotiation cannot tell the
submissions apart — without the sanitizer the run "succeeds" while pairing
rank 0's first tensor with rank 1's second (silent numeric corruption).
With ``HVD_TPU_SANITIZER=1`` the per-entry seq/call-site tag rides the
negotiation digest and the divergence fails fast as a NegotiationError
naming both ranks and both call sites.

Prints ``SANITIZER_OK`` when the divergence is caught with full
attribution, ``SANITIZER_MISSED`` when the run completes undetected.

``HVD_TPU_SANITIZER=hash`` mode exercises the SAME-SITE blind spot
instead: both ranks submit through one call site, in the same order, with
the same seq — only the *content* diverges.  Tag mode cannot tell the
submissions apart; the content digest folded into the tag can.  Prints
``SANITIZER_HASH_OK`` when the divergence is caught and a replicated
control collective still negotiates cleanly afterwards.
"""

import os

# Each worker is one rank with ONE cpu device: strip the 8-virtual-device
# flag inherited from the test process, use gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.controller import NegotiationError


def hash_main(rank):
    """Same call site, same order, same seq — divergent CONTENT only."""
    # Deliberately divergent data through ONE call site: undetectable by
    # seq/site tags (they match exactly), caught only by the content
    # digest.  Hash mode compares LOCAL contributions, so it is meant for
    # replicated-expectation debugging — which is exactly this shape.
    x = np.full((4,), 1.0 + rank, np.float32)
    try:
        hvd.allreduce(x, name="hash.t", op=hvd.Sum)
        print("SANITIZER_HASH_MISSED", flush=True)
    except NegotiationError as e:
        msg = str(e)
        assert "ranks [0]" in msg and "ranks [1]" in msg, msg
        assert "h=" in msg, msg
        assert "site=worker_sanitizer.py" in msg, msg
        # Control: replicated content hashes identically on both ranks and
        # negotiates cleanly — the runtime survived the failed collective.
        y = np.ones(4, np.float32)
        out = hvd.allreduce(y, name="hash.ok", op=hvd.Sum)
        got = np.asarray(hvd.to_local(out)).reshape(4)
        np.testing.assert_allclose(
            got, np.full(4, float(hvd.size()), np.float32), rtol=1e-6)
        print("SANITIZER_HASH_OK", flush=True)
    hvd.shutdown()


def main():
    hvd.init()
    rank = hvd.rank()
    if os.environ.get("HVD_TPU_SANITIZER", "").strip().lower() == "hash":  # hvd-lint: disable=HVD108  (env-selected test mode)
        hash_main(rank)
        return
    a = np.ones(4, np.float32)
    b = np.full((4,), 2.0, np.float32)

    try:
        if rank == 0:   # hvd-lint: disable=HVD101  (deliberate divergence)
            h1 = hvd.allreduce_async(a)  # hvd-lint: disable=HVD101
            h2 = hvd.allreduce_async(b)  # hvd-lint: disable=HVD101
        else:
            h1 = hvd.allreduce_async(b)  # hvd-lint: disable=HVD101
            h2 = hvd.allreduce_async(a)  # hvd-lint: disable=HVD101  (deliberate order swap under test)
        hvd.synchronize([h1, h2])
        print("SANITIZER_MISSED", flush=True)
    except NegotiationError as e:
        msg = str(e)
        assert "ranks [0]" in msg and "ranks [1]" in msg, msg
        assert "site=worker_sanitizer.py" in msg, msg
        assert "seq=" in msg, msg
        print("SANITIZER_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
