"""Steady-state fast-path worker: response cache + wire compression across
REAL processes (the PR 2 acceptance runs).

Default mode (CACHE_OK): after a 2-step warm-up, 5 steady-state training
steps must exchange ZERO per-tensor metadata (bitvector frames only — the
frame-count assertion), a shape change under a cached name must fall back
to full negotiation on all ranks and renegotiate cleanly, and a bf16-wire
allreduce must match the fp32 result within cast tolerance while reusing a
single cached fused program.

Sanitizer mode (HVD_TPU_SANITIZER=1 → CACHE_SANITIZER_OK): with both ranks
warm ON the cached path, swapped submission order must still fail fast as a
NegotiationError with call-site attribution — the tag side-channel riding
the bitvector frame, not a fall-back to full announces.
"""

import os

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.controller import NegotiationError

SHAPES = [(31,), (17,), (64,)]


def train_step(value):
    xs = [np.full(s, value * (i + 1), np.float32)
          for i, s in enumerate(SHAPES)]
    outs = hvd.grouped_allreduce(xs, name="grad", op=hvd.Sum)
    world = hvd.size()
    for i, o in enumerate(outs):
        got = np.asarray(hvd.to_local(o)).reshape(SHAPES[i])
        np.testing.assert_allclose(
            got, np.full(SHAPES[i], world * value * (i + 1), np.float32),
            rtol=1e-5)


def main():
    hvd.init()
    rank = hvd.rank()
    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats

    # Warm-up: step 1 learns the slots (full announces), step 2 is the
    # first all-bitvector step.
    train_step(1.0)
    train_step(2.0)

    if os.environ.get("HVD_TPU_SANITIZER", "") == "1":
        # Warm the two named tensors with a consistent order first...
        a = np.ones(4, np.float32)
        b = np.full((4,), 2.0, np.float32)
        h1 = hvd.allreduce_async(a, name="san.a")
        h2 = hvd.allreduce_async(b, name="san.b")
        hvd.synchronize([h1, h2])
        full_before = st.full_announces
        try:
            # ...then swap it on rank 1 (different call sites, same
            # signatures): the cached path's tag side-channel must catch
            # it — same guarantee PR 1 proved on the full path.
            if rank == 0:   # hvd-lint: disable=HVD101 (deliberate)
                h1 = hvd.allreduce_async(a, name="san.a")
                h2 = hvd.allreduce_async(b, name="san.b")
            else:
                h1 = hvd.allreduce_async(b, name="san.b")
                h2 = hvd.allreduce_async(a, name="san.a")
            hvd.synchronize([h1, h2])
            print("CACHE_SANITIZER_MISSED", flush=True)
        except NegotiationError as exc:
            msg = str(exc)
            assert "site=worker_cache.py" in msg, msg
            assert "ranks [0]" in msg and "ranks [1]" in msg, msg
            assert st.full_announces == full_before, \
                "divergence was caught, but NOT on the cached path"
            print("CACHE_SANITIZER_OK", flush=True)
        hvd.shutdown()
        return

    # Frame-count assertion: steady state exchanges only bitvector frames.
    full_before = st.full_announces
    for k in range(5):
        train_step(3.0 + k)
    assert st.full_announces == full_before, (
        f"steady-state sent per-tensor metadata: "
        f"{st.full_announces - full_before} full announces")
    assert st.bit_announces >= 5 * len(SHAPES), st
    assert (st.hit_rate() or 0.0) > 0.4, st
    assert eng.negotiation_cycles > 0 and eng.negotiation_us_total > 0.0

    # Shape change under a cached name: miss -> full negotiation on all
    # ranks (no error, no hang), then the new tuple re-caches.
    full_before = st.full_announces
    out = hvd.allreduce(np.full((7,), 5.0, np.float32), name="grad.0",
                        op=hvd.Sum)
    np.testing.assert_allclose(
        np.asarray(hvd.to_local(out)).reshape(7),
        np.full(7, 5.0 * hvd.size(), np.float32), rtol=1e-5)
    assert st.full_announces == full_before + 1, st

    # Wire compression: bf16 matches fp32 within cast tolerance, returns
    # fp32, and the 2nd compressed step reuses ONE cached fused program.
    x = (np.linspace(-1.0, 1.0, 127).astype(np.float32) * (rank + 1))
    base = np.asarray(hvd.to_local(
        hvd.allreduce(x, name="comp.32", op=hvd.Sum))).reshape(127)
    misses_before = eng.cache.misses
    c1 = np.asarray(hvd.to_local(hvd.allreduce(
        x, name="comp.b1", op=hvd.Sum, compression="bf16"))).reshape(127)
    c2 = np.asarray(hvd.to_local(hvd.allreduce(
        x, name="comp.b2", op=hvd.Sum, compression="bf16"))).reshape(127)
    assert eng.cache.misses == misses_before + 1, (
        "compressed program was not reused from the cache")
    assert c1.dtype == np.float32
    np.testing.assert_allclose(c1, base, rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(c2, base, rtol=3e-2, atol=3e-2)

    print("CACHE_OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
