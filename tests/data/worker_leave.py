"""Clean-LEAVE acceptance worker (ISSUE 10's two-process proof).

Two modes (``LEAVE_MODE``), one script — the same departure point, with
and without the typed LEAVE frame, so the disambiguation the protocol
exists for is asserted from both sides:

``clean``  rank 1 finishes K lock-step allreduce steps, then calls
           ``hvd.shutdown()`` — which quiesces the engine at a round
           boundary and sends the protocol-v6 LEAVE before the sever —
           and exits 0.  Rank 0 keeps training and must observe a
           ``PeerLeftInterrupt`` (a ``HostsUpdatedInterrupt`` — the
           re-rendezvous signal, NOT an HVD303 fault): ``engine.fault``
           stays None, ``controller.left_ranks == [1]``, new world-level
           submissions fail fast with the same interrupt, and the
           monitor's ``/health`` stays ``ok`` with rank 1 reported left.

``sever``  rank 1 severs its socket at the SAME point WITHOUT a LEAVE:
           rank 0 must get the typed attributed ``PeerFailureError``
           naming rank 1 (HVD303) — the legacy crash verdict, proving
           the LEAVE frame (not timing luck) is what made mode ``clean``
           clean.

Results ride files (``LEAVE_RESULT`` / + ``.r1``): both ranks exit via
``os._exit`` — the departed world cannot complete the jax coordination
service's cooperative shutdown barrier, exactly why clean departures
park it (docs/fault_tolerance.md).
"""

import json
import os
import time

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.exceptions import (
    HorovodInternalError, HostsUpdatedInterrupt, PeerLeftInterrupt,
)

MODE = os.environ.get("LEAVE_MODE", "clean")
RESULT = os.environ.get("LEAVE_RESULT", "")
WARM_STEPS = int(os.environ.get("LEAVE_WARM_STEPS", "6"))


def _write(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def main():
    hvd.init()
    rank = hvd.rank()
    st = basics._get_state()
    eng, ctl = st.engine, st.controller

    # Warm lock-step steps on BOTH ranks: all work settles, so the
    # departure point has zero outstanding negotiated work.
    for k in range(WARM_STEPS):
        out = hvd.allreduce(np.ones(2, np.float32), name=f"warm.{k}",
                            op=hvd.Sum)
        np.testing.assert_allclose(
            np.asarray(hvd.to_local(out)).reshape(2),
            np.full(2, float(hvd.size()), np.float32))

    if rank == 1:
        if MODE == "clean":
            hvd.shutdown()       # quiesce -> LEAVE -> sever (protocol v6)
            _write(RESULT + ".r1", {"ok": True,
                                    "leave_sent": bool(ctl.leave_sent)})
        else:
            # The control: the SAME departure point, no LEAVE frame.
            eng.quiesce(timeout=5.0)
            ctl._sever()
            _write(RESULT + ".r1", {"ok": True, "leave_sent": False})
            time.sleep(3)        # let rank 0 read the verdict first —
                                 # a nonzero exit makes the launcher reap
        os._exit(0 if MODE == "clean" else 3)

    # ------------------------------------------------------------- rank 0
    verdict = None
    try:
        for k in range(100000):
            hvd.allreduce(np.ones(2, np.float32), name=f"after.{k}",
                          op=hvd.Sum)
            time.sleep(0.01)
        raise AssertionError("peer departure never observed")
    except HostsUpdatedInterrupt as exc:
        verdict = exc
    except HorovodInternalError as exc:
        verdict = exc

    if MODE == "clean":
        assert isinstance(verdict, PeerLeftInterrupt), repr(verdict)
        assert not isinstance(verdict, HorovodInternalError), repr(verdict)
        assert verdict.left_ranks == [1], verdict.left_ranks
        assert eng.fault is None, repr(eng.fault)
        assert ctl.left_ranks == [1], ctl.left_ranks
        assert not ctl.interrupted
        # New world-level work fails FAST with the same interrupt (never
        # queues into a world that must re-form first).
        t0 = time.monotonic()
        try:
            hvd.allreduce(np.ones(2, np.float32), name="post.leave",
                          op=hvd.Sum)
            raise AssertionError("post-leave enqueue did not fail")
        except PeerLeftInterrupt:
            pass
        assert time.monotonic() - t0 < 5
        # /health stays ok with the departed rank reported LEFT — an
        # orderly departure is not a degradation.
        health = st.monitor.health()
        assert health["status"] == "ok", health
        assert health["left_ranks"] == [1], health
        assert health["ranks"]["1"].get("left") is True, health["ranks"]
        _write(RESULT, {
            "ok": True, "mode": MODE,
            "verdict": type(verdict).__name__,
            "left_ranks": verdict.left_ranks,
            "fault": None,
            "health_status": health["status"],
            "health_left": health["left_ranks"],
        })
        print("LEAVE_CLEAN_OK", flush=True)
    else:
        from horovod_tpu.common.exceptions import PeerFailureError
        # Without the LEAVE frame the same sever is a CRASH: typed,
        # attributed HVD303.
        assert isinstance(verdict, PeerFailureError) or \
            eng.fault is not None, repr(verdict)
        fault = verdict if isinstance(verdict, PeerFailureError) \
            else eng.fault
        assert isinstance(fault, PeerFailureError), repr(fault)
        assert fault.dead_ranks == [1], fault.dead_ranks
        assert "HVD303" in str(fault), str(fault)
        _write(RESULT, {
            "ok": True, "mode": MODE,
            "verdict": type(fault).__name__,
            "dead_ranks": fault.dead_ranks,
            "hvd303": "HVD303" in str(fault),
        })
        print("LEAVE_SEVER_OK", flush=True)
    os._exit(0)


if __name__ == "__main__":
    assert RESULT, "LEAVE_RESULT must point at a writable path"
    main()
