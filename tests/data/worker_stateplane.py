"""Resilient-state-plane acceptance worker (ISSUE 14) — jax-free.

A synthetic elastic "trainer" over the REAL wire stack (versioned
rendezvous + native lock-step negotiation, flat or behind a real
per-host ``HostAgent``) whose elastic state rides the REAL
:mod:`horovod_tpu.elastic.stateplane`: every worker commits epochs
(paced by the driver's COMMIT pings plus a periodic cadence), declares
them in the rendezvous state KV, and serves its committed blob from the
plane's shard server.  A worker that joins a generation while survivors
hold a NEWER epoch restores peer-to-peer — the scenario test asserts the
replacement rank's ``source=peer``, ``disk_reads=0`` and a digest
bitwise-identical to the survivors' committed epoch.

Scripted through files in ``STATEPLANE_DIR``:

- ``done``   existence ends the run (clean LEAVE, exit 0)

Log lines the scenario test pins::

    committed epoch=<E> digest=<D>
    restored epoch=<E> source=<peer|disk> digest=<D> disk_reads=<N>
"""

import os
import sys
import time

from horovod_tpu.common.controller import TCPController
from horovod_tpu.common.exceptions import (
    DrainRequested, HorovodInternalError, HostsUpdatedInterrupt,
)
from horovod_tpu.elastic import rendezvous as rdv
from horovod_tpu.elastic import stateplane as spl
from horovod_tpu.elastic import worker as ew

DIR = os.environ["STATEPLANE_DIR"]
CKPT_DIR = os.environ["HOROVOD_CKPT_DIR"]
HIER = os.environ.get("HOROVOD_HIERARCHICAL_CONTROLLER", "") == "1"
COMMIT_EVERY = int(os.environ.get("STATEPLANE_COMMIT_EVERY", "5"))

_agent = None          # generation-surviving per-host agent (ISSUE 12)
_plane = None          # generation-surviving state plane (ISSUE 14)


def _state_for(epoch: int) -> dict:
    """Deterministic per-epoch state, identical on every rank — what
    makes 'bitwise-identical to the survivors' epoch' assertable."""
    import numpy as np
    return {"step": epoch,
            "params": np.arange(4096, dtype=np.float32) * float(epoch)}


def _plane_for(rank: int, world: int):
    global _plane
    if _plane is None:
        _plane = spl.StatePlane(CKPT_DIR, rank=rank, world=world)
    else:
        # The plane (and its in-memory epoch — the thing a survivor
        # serves across a world change) SURVIVES re-rendezvous; only its
        # shard-file naming follows the new assignment.
        _plane.rank, _plane.world = rank, world
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
    ident = ew.identity()
    _plane.set_declare(
        lambda rec: rdv.declare_state(addr, port, ident, rec))
    return _plane


def _maybe_restore(plane) -> None:
    """Peer-first restore at generation entry, mirroring
    ``stateplane.maybe_restore`` for a stateless synthetic trainer."""
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
    ident = ew.identity()
    try:
        records = rdv.state_directory(addr, port)
    except OSError:
        return
    peers = [(who.rsplit(":", 1)[0], int(rec["port"]))
             for who, rec in records.items()
             if who != ident and rec.get("port")
             and int(rec.get("epoch", -1)) > plane.epoch]
    if not peers:
        return
    try:
        _data, epoch, source = plane.restore(peers=peers)
    except FileNotFoundError:
        return
    print(f"[worker {ident}] restored epoch={epoch} source={source} "
          f"digest={plane.memory_state()[2]} "
          f"disk_reads={plane.disk_reads}", flush=True)


class E:
    def __init__(self, name):
        import numpy as np
        self.name = name
        self.tensor = np.zeros((2, 4), np.float32)
        self.group_id = -1


def one_generation(mgr):
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
    min_v = 0 if ew._current_version is None else ew._current_version + 1
    a = rdv.fetch_assignment(addr, port, ew.identity(),
                             min_version=min_v, timeout_s=120)
    ew._current_version = int(a["version"])
    rank, size = int(a["rank"]), int(a["size"])
    ctl_port = int(a["controller_port2"]) or int(a["controller_port"]) + 1
    coord = a["controller_addr"]

    connect_addr, connect_port, server_port = coord, ctl_port, None
    if HIER:
        from horovod_tpu.common.host_agent import HostAgent
        global _agent
        cross = int(a["cross_rank"])
        agent_port = int(a.get("agent_port") or ctl_port + 1 + cross)
        if int(a["local_rank"]) == 0:
            reused = False
            if _agent is not None and _agent.port == agent_port:
                try:
                    _agent.new_generation(coord, ctl_port, [rank],
                                          host_index=cross)
                    reused = True
                except RuntimeError:
                    pass
            if not reused:
                if _agent is not None:
                    _agent.stop()
                _agent = HostAgent(agent_port, coord, ctl_port, [rank],
                                   host_index=cross).start()
        connect_addr, connect_port = "127.0.0.1", agent_port
        if rank == 0:
            server_port = ctl_port
    elif rank == 0:
        server_port = ctl_port

    plane = _plane_for(rank, size)
    # The peer-vs-disk decision, BEFORE any training round: survivors
    # holding a newer epoch hand it over shard-by-shard; a fresh
    # replacement rank never opens a checkpoint file.
    _maybe_restore(plane)

    # Short round timeout: back-to-back generations (a discovery change
    # landing while the drained worker's exit is being reaped) can strand
    # THIS worker in a generation its peer never joined — the timeout is
    # what converts that into a quick re-rendezvous instead of a minute-
    # long wedge.  A failed CONNECT means the same thing (the hosting
    # rank already moved on): re-rendezvous, don't crash.
    try:
        ctl = TCPController(connect_addr, connect_port, rank=rank,
                            world=size, stall_warn_s=1e9,
                            cache_capacity=256, round_timeout_s=6.0,
                            server_port=server_port)
    except (OSError, RuntimeError) as exc:
        print(f"[worker {ew.identity()}] controller for generation "
              f"{a['version']} unreachable ({exc}); re-rendezvous",
              flush=True)
        # Re-fetch the SAME generation (or any newer one the driver has
        # published since): the hosting rank may simply not be there yet.
        ew._current_version = int(a["version"]) - 1
        return True
    print(f"[worker {ew.identity()}] generation {a['version']} "
          f"rank={rank}/{size} epoch={plane.epoch}", flush=True)

    def commit():
        epoch = plane.commit(state=_state_for(plane.epoch + 1))
        plane.wait_durable(epoch, timeout=10)
        print(f"[worker {ew.identity()}] committed epoch={epoch} "
              f"digest={plane.memory_state()[2]}", flush=True)

    step = 0
    try:
        while True:
            entries = [E(f"g{a['version']}.s{step}")]
            pending = list(entries)
            for _ in range(50):
                ready, _errs = ctl.negotiate(pending)
                got = {e.name for e in ready}
                pending = [e for e in pending if e.name not in got]
                if not pending:
                    break
            step += 1
            if os.path.exists(os.path.join(DIR, "done")):
                return False
            # Paced commit (the driver's COMMIT ping before scale/
            # preemption decisions) OR the periodic cadence.
            if mgr.consume_commit_request():
                print(f"[worker {ew.identity()}] commit requested by the "
                      f"driver (checkpoint pacing)", flush=True)
                commit()
            elif step % COMMIT_EVERY == 0:
                commit()
            mgr.raise_if_updated()
            time.sleep(0.05)
    except DrainRequested:
        print(f"[worker {ew.identity()}] drain requested -> clean LEAVE",
              flush=True)
        return False
    except HostsUpdatedInterrupt:
        print(f"[worker {ew.identity()}] hosts updated -> re-rendezvous",
              flush=True)
        return True
    except HorovodInternalError as exc:
        print(f"[worker {ew.identity()}] control plane ended ({exc}); "
              f"re-rendezvous", flush=True)
        return True
    finally:
        ctl.leave()
        ctl.shutdown()
        if _agent is not None:
            _agent.end_generation()


def main():
    mgr = ew.WorkerNotificationManager()
    ew._manager = mgr
    while one_generation(mgr):
        pass
    if _plane is not None:
        _plane.close()
    print(f"[worker {ew.identity()}] exiting 0", flush=True)


if __name__ == "__main__":
    sys.exit(main())
