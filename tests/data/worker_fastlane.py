"""Latency fast lane + partitioning worker: the ISSUE 8 acceptance runs
across REAL processes.

Proves, end to end through negotiate → (lane fork) → execute:

- results are BITWISE-identical with the fast lane + partitioning on vs
  off (with and without bf16 wire compression) — the lane fork and the
  tensor split never change the math;
- the fast lane actually engaged AND the slot-keyed persistent-program
  pin served warm dispatches (the controller stamps the response-cache
  slot during the bit announce; dispatch is one dict probe);
- a huge tensor split into priority-inheriting sub-tensors and the
  parent reassembled transparently;
- the steady-state control-plane contract holds with BOTH knobs on:
  zero per-tensor metadata after warm-up, the per-cycle request stays
  the fixed bitvector handful of bytes, and the negotiation ROUND COUNT
  per step is unchanged vs the knobs-off baseline (the fast lane is
  wire-invisible).

Launched by test_multiprocess.py::test_torovodrun_fast_lane with
``torovodrun -np 2``.
"""

import os

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics


def step(value, rank, compression=None, tag=""):
    """One small blocking allreduce + one huge one; returns host arrays."""
    small = (np.linspace(-1.0, 1.0, 256).astype(np.float32)
             * value * (rank + 1))
    huge = (np.linspace(-2.0, 2.0, 5000).astype(np.float32)
            * value * (rank + 2))
    a = hvd.allreduce(small, name=f"small{tag}", op=hvd.Sum,
                      compression=compression, priority=5)
    b = hvd.allreduce(huge, name=f"huge{tag}", op=hvd.Sum,
                      compression=compression)
    return [np.asarray(hvd.to_local(a)), np.asarray(hvd.to_local(b))]


def main():
    hvd.init()
    rank = hvd.rank()
    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats

    # ---- knobs OFF baseline --------------------------------------------
    eng.fast_lane_threshold = 0
    eng.partition_threshold = 0
    base32 = step(1.0, rank, tag=".off32")
    base16 = step(1.0, rank, compression="bf16", tag=".off16")
    for k in range(3):
        step(2.0 + k, rank, tag=".off32")       # warm the steady state
    bits0, fulls0 = st.bit_announces, st.full_announces
    for k in range(3):
        step(5.0 + k, rank, tag=".off32")
    bits_per_step_off = (st.bit_announces - bits0) / 3
    assert st.full_announces == fulls0

    # ---- fast lane ON (alone): bitwise + frame count unchanged ---------
    # "Frame count" is announce content, which is deterministic — raw
    # round counts are wall-clock pacing (the cycle thread ticks every
    # HOROVOD_CYCLE_TIME regardless of work) and may not be compared.
    eng.fast_lane_threshold = 64 * 1024     # small (1KB) rides the lane
    on32 = step(1.0, rank, tag=".on32")
    on16 = step(1.0, rank, compression="bf16", tag=".on16")
    for b, o in zip(base32 + base16, on32 + on16):
        np.testing.assert_array_equal(b, o)   # BITWISE, not allclose
    assert eng.fast_lane_dispatches > 0, "fast lane never engaged"
    step(2.0, rank, tag=".on32")                # warm the lane's programs
    bits1, fulls1 = st.bit_announces, st.full_announces
    for k in range(3):
        step(5.0 + k, rank, tag=".on32")
    bits_per_step_on = (st.bit_announces - bits1) / 3
    assert st.full_announces == fulls1, (
        "fast-lane steady state fell back to full negotiation")
    assert bits_per_step_on == bits_per_step_off, (
        f"fast lane changed the steady-state announce count per step: "
        f"{bits_per_step_on} vs {bits_per_step_off}")

    # ---- + partitioning: bitwise with both knobs on --------------------
    eng.partition_threshold = 8 * 1024      # huge (20KB) splits into 3
    mix32 = step(1.0, rank, tag=".mix32")
    mix16 = step(1.0, rank, compression="bf16", tag=".mix16")
    for b, o in zip(base32 + base16, mix32 + mix16):
        np.testing.assert_array_equal(b, o)
    assert eng.partition_splits > 0, "partitioning never engaged"

    # ---- steady state: frames frozen, pin serving ----------------------
    step(3.0, rank, tag=".steady")           # warm-up: learn slots
    step(4.0, rank, tag=".steady")
    full_before = st.full_announces
    bytes_before = ctl.bytes_sent
    rounds2 = ctl.rounds
    hits_before = eng.fast_lane_hits
    for k in range(5):
        step(5.0 + k, rank, tag=".steady")
    assert st.full_announces == full_before, (
        f"fast-lane/partitioned steady state sent per-tensor metadata: "
        f"{st.full_announces - full_before} full announces")
    per_round = (ctl.bytes_sent - bytes_before) / max(1, ctl.rounds - rounds2)
    assert per_round <= 32, (
        f"warm-path request grew to {per_round}B/round with the lane on")
    assert eng.fast_lane_hits > hits_before, (
        "slot-keyed persistent-program pin never served a warm dispatch")

    # ---- partitioned steady state relearns nothing either --------------
    # (sub-names hold response-cache slots like any tensor; toggling the
    # fast-lane knob mid-run is invisible to the control plane)
    full_before = st.full_announces
    eng.fast_lane_threshold = 32 * 1024
    step(11.0, rank, tag=".steady")
    assert st.full_announces == full_before, (
        "fast-lane knob change invalidated response-cache slots")

    hvd.barrier()
    print(f"FASTLANE_OK rank={rank} "
          f"lane_dispatches={eng.fast_lane_dispatches} "
          f"pin_hits={eng.fast_lane_hits} "
          f"splits={eng.partition_splits}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
