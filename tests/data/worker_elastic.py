"""Elastic integration worker: trains a toy JAX model under
``@hvd.elastic.run`` while the test mutates the discovery host set, mirroring
the reference's ``test/integration/data`` training scripts (SURVEY.md §4).

Writes a JSON result (epochs completed, final world size, reset count) from
rank 0 at the end so the test can assert the job survived the resize.
"""

import json
import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.elastic import JaxState, run

MARKER = os.environ["ELASTIC_TEST_MARKER"]
RESULT = os.environ["ELASTIC_TEST_RESULT"]
EPOCHS = int(os.environ.get("ELASTIC_TEST_EPOCHS", "6"))

resets = {"n": 0}


@run
def train(state):
    import time
    while state.epoch < EPOCHS:
        # One "epoch": a real collective so peers must be alive and the
        # world must be consistent.
        contrib = np.full((2,), float(hvd.rank() + 1), np.float32)
        out = hvd.to_local(hvd.allreduce(
            contrib, name=f"epoch.{state.epoch}", op=hvd.Sum))
        expected = sum(r + 1.0 for r in range(hvd.size()))
        np.testing.assert_allclose(out, np.full((2,), expected))
        state.epoch += 1
        state.commit()  # checks for host updates -> may raise/reset
        if state.epoch == 2 and hvd.rank() == 0:
            with open(MARKER, "w") as fh:
                fh.write(str(state.epoch))
        if state.epoch >= 2:
            # Give the driver time to act on the mutated host set before the
            # job finishes (discovery poll interval is 1s).
            time.sleep(1.0)
    return hvd.size()


def on_reset():
    resets["n"] += 1


def main():
    hvd.init()
    state = JaxState(epoch=0)
    state.register_reset_callbacks([on_reset])
    final_size = train(state)
    if hvd.rank() == 0:
        with open(RESULT, "w") as fh:
            json.dump({"epochs": state.epoch, "final_size": final_size,
                       "resets": resets["n"]}, fh)
    hvd.shutdown()


if __name__ == "__main__":
    main()


