"""Pipelined data plane worker: chunked collectives + in-flight dispatch
window + priority drain across REAL processes (the PR 3 acceptance runs).

Proves, end to end through negotiate → fuse → execute:

- results are BITWISE-identical with the pipeline on vs off (chunking,
  in-flight window and priority stamps all active), with and without bf16
  wire compression;
- the steady-state response-cache frame guarantee holds with the pipeline
  on — and toggling the chunk knob mid-run is invisible to the control
  plane (chunking is not in the negotiation digest);
- the FusedProgramCache stays bounded by chunk-COUNT keying: a knob change
  that maps to the same chunk plan reuses the compiled program;
- the in-flight ring actually engaged (dispatches flowed through the
  watcher) and the pipeline counters advanced.

Launched by test_multiprocess.py::test_torovodrun_pipeline with
``torovodrun -np 2``.
"""

import os

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics

SHAPES = [(257,), (130,), (64,)]
PRIOS = [3, 2, 1]          # reverse-registration stamps (first grad leads)


def step(value, rank, compression=None, tag=""):
    """One fused, priority-stamped grouped allreduce; returns per-tensor
    host arrays."""
    xs = [(np.linspace(-1.0, 1.0, int(np.prod(s))).astype(np.float32)
           .reshape(s) * value * (rank + 1) * (i + 1)) for i, s in
          enumerate(SHAPES)]
    outs = hvd.grouped_allreduce(xs, name=f"grad{tag}", op=hvd.Sum,
                                 compression=compression, priorities=PRIOS)
    return [np.asarray(hvd.to_local(o)).reshape(SHAPES[i])
            for i, o in enumerate(outs)]


def main():
    hvd.init()
    rank = hvd.rank()
    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats

    # ---- pipeline OFF baseline (single chunk, inline settling) ----------
    eng.pipeline_chunk_bytes = 0
    eng.max_inflight = 1
    base32 = step(1.0, rank, tag=".off32")
    base16 = step(1.0, rank, compression="bf16", tag=".off16")
    assert eng._inflight is None, "inline mode must not build the ring"

    # ---- pipeline ON: small chunks + in-flight window -------------------
    eng.pipeline_chunk_bytes = 256          # 64 fp32 elems -> many chunks
    eng.max_inflight = 2
    on32 = step(1.0, rank, tag=".on32")
    on16 = step(1.0, rank, compression="bf16", tag=".on16")
    for b, o in zip(base32 + base16, on32 + on16):
        np.testing.assert_array_equal(b, o)   # BITWISE, not allclose
    assert eng._inflight is not None and eng._inflight.dispatched > 0, \
        "in-flight ring never engaged"
    assert eng.pipeline_dispatches > 0
    assert eng.pipeline_chunks_total > eng.pipeline_dispatches, \
        "chunked programs did not report multiple chunks"

    # ---- steady-state frame guarantee with the pipeline on --------------
    step(2.0, rank, tag=".steady")          # warm-up: learn slots
    step(3.0, rank, tag=".steady")
    full_before = st.full_announces
    for k in range(5):
        step(4.0 + k, rank, tag=".steady")
    assert st.full_announces == full_before, (
        f"pipeline-on steady state sent per-tensor metadata: "
        f"{st.full_announces - full_before} full announces")
    assert st.bit_announces >= 5 * len(SHAPES), st

    # Toggling the chunk knob mid-run must be invisible to the control
    # plane: chunking is NOT in the negotiation digest, so no full
    # announces — only a data-plane recompile.
    full_before = st.full_announces
    eng.pipeline_chunk_bytes = 512
    step(9.0, rank, tag=".steady")
    assert st.full_announces == full_before, (
        "chunk-knob change invalidated response-cache slots")

    # ---- chunk-COUNT (not chunk-size) keys the program cache ------------
    x = np.full((64,), 1.0 + rank, np.float32)     # 256 B per rank shard
    eng.pipeline_chunk_bytes = 128                 # -> 2 chunks
    hvd.allreduce(x, name="keyed.a", op=hvd.Sum)
    misses = eng.cache.misses
    eng.pipeline_chunk_bytes = 130                 # same plan: 2 chunks
    hvd.allreduce(x, name="keyed.b", op=hvd.Sum)
    assert eng.cache.misses == misses, (
        "equal chunk plans under different byte knobs recompiled")
    eng.pipeline_chunk_bytes = 64                  # -> 4 chunks: new plan
    hvd.allreduce(x, name="keyed.c", op=hvd.Sum)
    assert eng.cache.misses == misses + 1, (
        "a new chunk plan did not produce exactly one new program")

    hvd.barrier()
    print(f"PIPELINE_OK rank={rank} "
          f"inflight_hwm={eng._inflight.high_water} "
          f"chunks={eng.pipeline_chunks_total}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
