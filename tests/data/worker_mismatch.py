"""Worker: mismatched submission shapes must fail fast, per-tensor, with
rank attribution (reference: controller.cc shape/dtype consistency ->
per-tensor error Response)."""
import os

# Each worker is one rank with ONE cpu device: strip the 8-virtual-device
# flag inherited from the test process.
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.controller import NegotiationError

hvd.init()
r = hvd.rank()

# Divergent per-rank shapes under the same wire name.
bad = np.ones((4,) if r == 0 else (8,), np.float32)
try:
    hvd.allreduce(bad, name="divergent", op=hvd.Sum)
    raise SystemExit(f"rank {r}: mismatched allreduce unexpectedly succeeded")
except NegotiationError as e:
    msg = str(e)
    assert "ranks [0]" in msg and "ranks [1]" in msg, msg
    assert "(4,)" in msg and "(8,)" in msg, msg

# Grouped ops are atomic: one divergent member fails the whole group.
hs = hvd.grouped_allreduce_async(
    [np.ones((2,), np.float32),
     np.ones((4,) if r == 0 else (6,), np.float32)],
    name="grp", op=hvd.Sum)
errs = 0
for h in hs:
    try:
        hvd.synchronize(h)
    except NegotiationError:
        errs += 1
assert errs == 2, f"rank {r}: expected both group members to fail, got {errs}"

# The runtime must survive a per-tensor failure: consistent work continues.
good = hvd.to_local(hvd.allreduce(
    np.full((3,), float(r + 1), np.float32), name="after", op=hvd.Sum))
np.testing.assert_allclose(good, np.full((3,), 3.0, np.float32))
print("MISMATCH_OK", flush=True)
