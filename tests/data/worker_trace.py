"""Tracing-subsystem acceptance worker (the tentpole's two-process proof).

Run via torovodrun with ``--trace-filename`` (the launcher suffixes the
base per rank), HOROVOD_MONITOR=1 and a small HOROVOD_MONITOR_INTERVAL.
Proves, across REAL processes:

1. tracing is armed from the launcher knob and every committed span
   carries the lock-step cycle id (the cross-rank correlation key);
2. the steady-state frame guard holds with tracing + monitoring ON —
   warm cycles still exchange zero per-tensor metadata, and the MON1
   digest blob stays inside the size cap;
3. each rank's trace digest reaches the PEER through the side-channel
   (aggregation table carries per-cycle phase rows);
4. the per-rank trace files are written and flushed on shutdown — the
   launcher-side test then merges them with ``python -m
   horovod_tpu.trace`` and asserts per-rank lanes + matched cycle flows.

Prints ``TRACE_OK`` on success.
"""

import json
import os
import time

# One rank per process, one CPU device each; gloo for cross-process XLA
# collectives (same preamble as worker_collectives.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics

SHAPES = [(31,), (17,), (64,)]


def train_step(value):
    xs = [np.full(s, value * (i + 1), np.float32)
          for i, s in enumerate(SHAPES)]
    outs = hvd.grouped_allreduce(xs, name="grad", op=hvd.Sum)
    world = hvd.size()
    got = np.asarray(hvd.to_local(outs[0])).reshape(SHAPES[0])
    np.testing.assert_allclose(
        got, np.full(SHAPES[0], world * value, np.float32), rtol=1e-5)


def main():
    hvd.init()
    rank = hvd.rank()
    st = basics._get_state()
    eng, ctl, mon = st.engine, st.controller, st.monitor
    assert ctl is not None, "worker needs the torovodrun controller"
    assert mon is not None, "HOROVOD_MONITOR=1 must install the agent"
    tracer = eng.tracer
    assert tracer is not None, "--trace-filename must arm the tracer"
    trace_file = st.config.trace_filename
    assert trace_file.endswith(f".{rank}"), (
        f"per-rank suffix scheme broken: {trace_file!r}")

    # ---- 1. steady state: fixed step count on both ranks.
    for k in range(15):
        train_step(1.0 + k)
        time.sleep(0.05)
    assert tracer.spans_committed >= 15 * len(SHAPES), (
        tracer.spans_committed)
    summary = tracer.phase_summary()
    assert summary["phases_us"] is not None
    # Phase sums partition the measured lifecycle (the bench consistency).
    drift = abs(summary["phase_sum_us"] - summary["cycle_us"])
    assert drift <= max(1.0, 0.01 * summary["cycle_us"]), summary

    # ---- 2. frame guard with tracing + monitoring ON, digest size cap.
    stats = ctl.cache_stats
    full_before = stats.full_announces
    for k in range(5):
        train_step(50.0 + k)
    assert stats.full_announces == full_before, (
        f"tracing pushed {stats.full_announces - full_before} cycles "
        f"off the bitvector fast path")
    assert stats.bit_announces >= 5 * len(SHAPES)
    digest_blob = json.dumps(tracer.digest(),
                             separators=(",", ":")).encode()
    assert len(digest_blob) <= 8192, len(digest_blob)

    # ---- 3. the PEER's digest arrived through the MON1 side-channel.
    peer = 1 - rank
    deadline = time.time() + 20
    peer_trace = None
    while time.time() < deadline and not peer_trace:
        snap = mon.aggregator.snapshot_of(peer)
        tr = (snap or {}).get("trace") or {}
        if tr.get("cycles"):
            peer_trace = tr
            break
        train_step(100.0 + time.time() % 1)
        time.sleep(0.1)
    assert peer_trace is not None, (
        f"rank {rank}: no trace digest from rank {peer}: "
        f"{mon.aggregator.table()}")
    # Digest rows carry the shared cycle ids and the five phases.
    row = peer_trace["cycles"][-1]
    assert len(row) == 2 + 5 and row[0] > 0, row

    print("TRACE_OK", flush=True)
    hvd.shutdown()     # stops the engine -> closes/flushes the trace file
    assert os.path.exists(trace_file), trace_file


if __name__ == "__main__":
    main()
