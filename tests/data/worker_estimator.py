"""Multi-process estimator worker: every rank runs ``fit`` with a local
backend over a SHARED store — each rank materializes (idempotently), reads
its own shard, averages gradients through the coordinator, and all ranks
end with identical learned parameters (reference:
``horovod/spark/torch/estimator.py`` training flow).  Launched by
torovodrun in test_multiprocess.py.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.spark import JaxEstimator, LocalStore


class Rows:
    def __init__(self, rows):
        self._rows = rows

    def collect(self):
        return self._rows


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    rng = np.random.RandomState(0)        # SAME data on every rank
    X = rng.randn(64, 3).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5], np.float32)
    y = X @ w
    df = Rows([{"f0": float(a), "f1": float(b), "f2": float(c),
                "label": float(t)} for (a, b, c), t in zip(X, y)])

    est = JaxEstimator(
        init_fn=lambda r, x: {"w": jnp.zeros((x.shape[1],)),
                              "b": jnp.zeros(())},
        apply_fn=lambda p, Xb: Xb @ p["w"] + p["b"],
        loss_fn=lambda pred, yb: (pred - yb.reshape(pred.shape)) ** 2,
        feature_cols=["f0", "f1", "f2"], label_cols=["label"],
        store=LocalStore(os.environ["EST_DIR"]), num_proc=size,
        epochs=40, batch_size=16, learning_rate=0.1, run_id="mp",
        backend=lambda fn, n, env=None: [fn()])
    model = est.fit(df)
    np.testing.assert_allclose(np.asarray(model.params["w"]), w, atol=0.1)

    # All ranks must hold identical trained params (grads were averaged).
    digest = np.array([float(np.asarray(model.params["w"]).sum()),
                       float(model.params["b"])], np.float64)
    g = hvd.to_local(hvd.allgather(digest, name="est_digest")).reshape(size, 2)
    for r in range(size):
        np.testing.assert_allclose(g[r], g[0], rtol=1e-9)

    # TorchEstimator over the SAME shared store, with data constructed so
    # gradient AVERAGING is observable: shard materialization is
    # round-robin (row j -> shard j % size), and row j's target uses
    # w + (j % size) * delta — shard r alone would converge to
    # w + r*delta, so landing on the MEAN optimum proves the torch
    # binding's allreduce hooks actually averaged across ranks
    # (reference: spark/torch/estimator.py).
    import torch
    from horovod_tpu.spark import TorchEstimator

    delta = np.array([0.8, 0.0, 0.0], np.float32)
    y2 = np.array([X[j] @ (w + (j % size) * delta)
                   for j in range(len(X))], np.float32)
    df2 = Rows([{"f0": float(a), "f1": float(b), "f2": float(c),
                 "label": float(t)} for (a, b, c), t in zip(X, y2)])
    expected = w + delta * (size - 1) / 2.0

    t_est = TorchEstimator(
        model_factory=lambda: torch.nn.Linear(3, 1, bias=False),
        loss=lambda p, t: torch.nn.functional.mse_loss(
            p, t.reshape(p.shape)),
        feature_cols=["f0", "f1", "f2"], label_cols=["label"],
        store=LocalStore(os.environ["EST_DIR"] + "/torch"), num_proc=size,
        epochs=40, batch_size=16, learning_rate=0.1, run_id="mp_torch",
        backend=lambda fn, n, env=None: [fn()])
    t_model = t_est.fit(df2)
    got = t_model.params["weight"].numpy().reshape(-1)
    np.testing.assert_allclose(got, expected, atol=0.15)
    # Un-averaged training would sit at shard 0's optimum (w) — reject it.
    assert got[0] - w[0] > 0.2, (got, w, expected)

    print(f"EST_OK rank={rank}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
