"""Minimal jax-free scenario worker for MANY-host driver-level tests
(ISSUE 14 satellite, carried from PR 12).

The full acceptance workers (worker_autoscale/worker_stateplane) carry a
real ``TCPController`` + ``MonitorAgent`` per process, which caps
driver-level scenarios at a handful of hosts.  This worker is the
lightest thing that still exercises the DRIVER end to end — versioned
rendezvous long-poll, the notification channel (HOSTS_UPDATED / DRAIN /
COMMIT with the receipt ack), generation re-entry, clean exit
classification — so churn scenarios run at 64+ simulated hosts in
seconds.  No controller, no monitor, no jax: what is under test is the
driver's orchestration, not the wire protocol (the wire has its own
2-proc and ChurnRunner tiers).

Scripted through ``SCENARIO_DIR``: ``done`` ends the run (exit 0).
"""

import os
import sys
import time

from horovod_tpu.common.exceptions import (
    DrainRequested, HostsUpdatedInterrupt,
)
from horovod_tpu.elastic import rendezvous as rdv
from horovod_tpu.elastic import worker as ew

DIR = os.environ["SCENARIO_DIR"]


def one_generation(mgr):
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
    min_v = 0 if ew._current_version is None else ew._current_version + 1
    a = rdv.fetch_assignment(addr, port, ew.identity(),
                             min_version=min_v, timeout_s=300)
    ew._current_version = int(a["version"])
    print(f"[lite {ew.identity()}] generation {a['version']} "
          f"rank={a['rank']}/{a['size']}", flush=True)
    try:
        while True:
            if os.path.exists(os.path.join(DIR, "done")):
                return False
            if mgr.consume_commit_request():
                print(f"[lite {ew.identity()}] commit requested",
                      flush=True)
            mgr.raise_if_updated()
            time.sleep(0.05)
    except DrainRequested:
        print(f"[lite {ew.identity()}] drain -> exiting 0", flush=True)
        return False
    except HostsUpdatedInterrupt:
        return True


def main():
    mgr = ew.WorkerNotificationManager()
    ew._manager = mgr
    while one_generation(mgr):
        pass
    print(f"[lite {ew.identity()}] exiting 0", flush=True)


if __name__ == "__main__":
    sys.exit(main())
