"""Two-level allreduce acceptance worker (ISSUE 17): bitwise parity
flat-vs-hierarchical across REAL processes.

2 processes × 4 local devices = 8 global ranks over 2 simulated slices
(``HOROVOD_SLICE_MAP=4``; the gloo TCP hop stands in for DCN, the
intra-process device group for one slice's ICI domain).  Proves, end to
end through negotiate → fuse → execute:

- parameters after 10 steps on a mixed fp32/bf16/scalar gradient tree are
  BITWISE identical between the flat ring and the two-level
  RS(local) → AR(cross) → AG(local) pipeline — the gradient stream is
  integer-valued (|sum| ≤ 32, inside bf16's exact-integer range), so
  every reduction order produces the same bits and any parity break is a
  data-plane bug, not fp noise;
- the leg counters prove the two-level path actually ran (dispatches,
  2 intra legs + 1 cross leg each);
- toggling the mode mid-run costs ZERO warm-path control bytes: the
  decision lives in the fusion key, never the negotiation digest, so the
  response-cache slots stay pinned (no new full announces) and the
  per-round request bytes stay on the same bitvector frame.

Launched by test_multiprocess.py::test_torovodrun_hier_parity with
``torovodrun -np 2`` — flat control plane AND --hierarchical-controller.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from slice_harness import configure_slice_world

jax = configure_slice_world(4)

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics

STEPS = 10
LR = 1.0 / 64.0          # power of two: updates stay exactly representable


def make_params():
    """Mixed tree: non-divisible fp32, 2-D fp32, scalar — all updated in
    fp32; the bf16 leaf exercises the wire dtype only (its reduced value
    is exact for integer grads within ±256)."""
    return {
        "w1": (np.arange(257, dtype=np.float32) % 7) - 3.0,
        "w2": ((np.arange(128, dtype=np.float32) % 5) - 2.0).reshape(16, 8),
        "scalar": np.float32(2.0),
        "half": (np.arange(66, dtype=np.float32) % 7) - 3.0,
    }


def grad_stream(step, r):
    """Deterministic integer-valued grads for global rank ``r``."""
    base = step * 31 + r * 7
    return {
        "w1": ((np.arange(257, dtype=np.float32) + base) % 7) - 3.0,
        "w2": (((np.arange(128, dtype=np.float32) + base) % 5) - 2.0)
        .reshape(16, 8),
        "scalar": np.float32((base % 9) - 4),
        "half": (((np.arange(66, dtype=np.float32) + base) % 7) - 3.0)
        .astype(jax.numpy.bfloat16),
    }


def train(my_ranks, steps=STEPS, start=0):
    params = make_params()
    keys = sorted(params)
    for s in range(start, start + steps):
        stacked = [np.stack([np.asarray(grad_stream(s, r)[k])
                             for r in my_ranks]) for k in keys]
        outs = hvd.grouped_allreduce(stacked, name="hgrads", op=hvd.Sum)
        for k, o in zip(keys, outs):
            loc = np.asarray(hvd.to_local(o))
            g = (loc if loc.ndim == np.ndim(params[k])
                 else loc[0]).astype(np.float32)
            params[k] = np.asarray(params[k] - LR * g, np.float32)
    return params


def main():
    hvd.init()
    rank, size, local = hvd.rank(), hvd.size(), hvd.local_size()
    proc = jax.process_index()
    assert size == 8 and local == 4, (size, local)
    my_ranks = range(4 * proc, 4 * proc + 4)

    eng = basics._get_state().engine
    ctl = eng.controller
    assert ctl is not None, "worker needs the torovodrun controller"
    st = ctl.cache_stats
    assert not eng.hierarchical_allreduce, \
        "worker must start flat (it toggles the mode itself)"

    # ---- flat baseline + warm-path frame measurement ---------------------
    p_flat = train(my_ranks)
    full_before = st.full_announces
    bytes_before, rounds_before = ctl.bytes_sent, ctl.rounds
    train(my_ranks, steps=5, start=STEPS)     # flat steady state
    flat_full = st.full_announces - full_before
    flat_round = (ctl.bytes_sent - bytes_before) \
        / max(1, ctl.rounds - rounds_before)
    assert flat_full == 0, f"flat steady state re-announced: {flat_full}"

    # ---- toggle: two-level data plane over 2 simulated slices ------------
    eng.hierarchical_allreduce = True
    eng._slice_topos.clear()                  # knob mutated mid-run
    topo = eng._slice_topology(0)
    assert topo is not None and topo.num_slices == 2 \
        and topo.local_size == 4, topo

    d0, i0, c0 = eng.hier_dispatches, eng.hier_intra_legs, eng.hier_cross_legs
    full_before = st.full_announces
    bytes_before, rounds_before = ctl.bytes_sent, ctl.rounds
    p_hier = train(my_ranks)
    for k in sorted(p_flat):
        np.testing.assert_array_equal(p_flat[k], p_hier[k])   # BITWISE

    # Two-level path actually ran: 1 dispatch per step (one fused batch),
    # 2 intra legs + 1 cross leg each.
    assert eng.hier_dispatches > d0, "no hierarchical dispatches"
    assert eng.hier_intra_legs == i0 + 2 * (eng.hier_dispatches - d0)
    assert eng.hier_cross_legs == c0 + (eng.hier_dispatches - d0)

    # Zero extra control bytes: the knob flip must not re-announce (the
    # mode is fusion-key-only, never in the digest) and the per-round
    # request must stay on the same pinned bitvector frame as flat.
    hier_full = st.full_announces - full_before
    hier_round = (ctl.bytes_sent - bytes_before) \
        / max(1, ctl.rounds - rounds_before)
    assert hier_full == 0, \
        f"hier toggle re-announced {hier_full} tensors (digest leak?)"
    assert hier_round <= flat_round + 0.5, (hier_round, flat_round)

    hvd.barrier()
    print(f"HIER_OK rank={rank} dispatches={eng.hier_dispatches} "
          f"intra={eng.hier_intra_legs} cross={eng.hier_cross_legs} "
          f"round={hier_round:.1f}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
