"""Serving-plane worker (ISSUE 19 acceptance): the data-parallel serving
pipeline across REAL processes — version-stamped weight fan-out over the
collective broadcast path, the continuous batcher feeding each replica's
bucket-compiled jitted forward, and the drain contract under live load.

Proves, end to end through negotiate → fuse → execute:

- ``Replica.load`` broadcasts rank 0's weights onto every replica: rank 1
  starts from zeros and ends BITWISE identical to rank 0's tree;
- version stamping makes re-delivery free (same version → no broadcast)
  while a rolling update (version+1) re-broadcasts WITHOUT restart;
- the batched padded-bucket forward is BITWISE identical to one-request-
  at-a-time forwards, and batch-size churn inside the bucket menu never
  recompiles (FusedProgramCache miss count pinned);
- a scripted load ramp drives the serving-mode ScalePolicy through
  hold → scale_out, and a rate collapse through the idle scale_in — the
  serving autoscale loop's decision sequence;
- the drain contract holds under live load: in-flight requests COMPLETE
  with correct results, new admissions are refused.

Launched by test_multiprocess.py::test_torovodrun_serving with
``torovodrun -np 2`` — flat AND --hierarchical-controller.
"""

import os

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.elastic.autoscale import ScalePolicy
from horovod_tpu.serve.batcher import ContinuousBatcher, Draining
from horovod_tpu.serve.replica import Replica


def apply_fn(params, x):
    return x @ params["w"] + params["b"]


def weights(seed):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(16, 8).astype(np.float32),
            "b": rng.randn(8).astype(np.float32)}


def main():
    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    assert world == 2, world

    # ---- version-stamped weight fan-out ---------------------------------
    # Rank 0 owns the trained tree; rank 1 starts from zeros and must end
    # bitwise identical after load() (the broadcast IS the fan-out).
    v1 = weights(1) if rank == 0 else \
        {"w": np.zeros((16, 8), np.float32), "b": np.zeros(8, np.float32)}
    rep = Replica(apply_fn)
    assert rep.load(v1, version=1) is True
    truth = weights(1)
    for k in ("w", "b"):
        np.testing.assert_array_equal(np.asarray(rep.params[k]), truth[k])

    # Re-delivery of the serving version is a no-op on every rank (a
    # rolling updater may retry blindly) — no collective runs, so ranks
    # could even disagree on calling it.
    assert rep.load(v1, version=1) is False
    assert rep.loads == 1

    # Rolling update: version 2 re-broadcasts without restart.
    v2 = weights(2) if rank == 0 else \
        {"w": np.zeros((16, 8), np.float32), "b": np.zeros(8, np.float32)}
    assert rep.load(v2, version=2) is True
    truth2 = weights(2)
    np.testing.assert_array_equal(np.asarray(rep.params["w"]), truth2["w"])
    assert rep.loads == 2

    # ---- batched-vs-sequential bitwise parity + recompile pin -----------
    # The serving invariant: a request's result depends only on its OWN
    # row, never on its position in the bucket or on the co-batched
    # requests sharing it — row i of the full batch must be bitwise
    # identical to submitting row i alone through the same bucket program
    # (cross-bucket programs are different XLA reductions, so only
    # matched shapes can be pinned bitwise).
    x = np.random.RandomState(100 + rank).randn(8, 16).astype(np.float32)
    batched = rep.forward(x)
    blank = np.zeros_like(x)
    seq = []
    for i in range(8):
        alone = blank.copy()
        alone[0] = x[i]                   # row i alone, position 0
        seq.append(rep.forward(alone)[0])
    np.testing.assert_array_equal(batched, np.stack(seq))  # BITWISE
    misses = rep.cache.misses
    for n in (3, 5, 7, 2, 6, 8):          # churn across the bucket menu
        rep.forward(x[:n])
    new_programs = rep.cache.misses - misses
    assert new_programs <= 2, \
        f"batch churn compiled {new_programs} extra programs"

    # ---- scripted ramp -> scale_out -> drain (serving-mode policy) ------
    pol = ScalePolicy(min_np=1, max_np=4, persistence=2, cooldown_s=5.0,
                      idle_s=10.0, rate_high=100.0, idle_qps=5.0)
    size, clock, actions = 2, 0.0, []
    for rate in [80.0] * 2 + [350.0] * 3 + [1.0] * 8:
        clock += 6.0
        d = pol.observe({"request_rate": rate, "queue_depth": 0},
                        size=size, now=clock)
        actions.append(d.action)
        if d.target_size is not None:
            size = d.target_size
        if d.action == "scale_in":
            break
    assert "scale_out" in actions and "scale_in" in actions, actions

    # ---- drain with in-flight requests completed ------------------------
    # Queue 8 requests with no consumer, cordon, THEN run the serve loop:
    # deterministic 4+4 batching, and the drain contract is exercised
    # with real work in flight — everything queued before the cordon
    # completes with correct results, new admissions are refused.
    batcher = ContinuousBatcher(max_batch=4, deadline_ms=10000.0,
                                max_inflight=2)
    inflight = [batcher.submit(x[i]) for i in range(8)]
    batcher.drain()
    refused = False
    try:
        batcher.submit(x[0])
    except Draining:
        refused = True
    assert refused, "draining batcher admitted new work"
    served = rep.serve_loop(batcher)      # returns once drained + empty
    assert served == 2, served            # 4 + 4, bucket 4 twice
    got = np.stack([r.wait(0.0) for r in inflight])       # all COMPLETE
    want = np.concatenate([rep.forward(x[:4]), rep.forward(x[4:8])])
    np.testing.assert_array_equal(got, want)              # same program

    hvd.barrier()
    print(f"SERVE_OK rank={rank} loads={rep.loads} "
          f"programs={rep.cache.misses} actions={len(actions)}",
          flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
