"""Tests for the collective-correctness analyzer (horovod_tpu.analysis).

Per lint rule: one violating fixture that must fire and one clean fixture
that must stay quiet.  Plus: trace_check over a toy shard_map step, ledger
comparison, the runtime sanitizer's recording/tagging layer, the CLI, and
the bindings' ``check=`` hook.
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

from horovod_tpu.analysis import lint_source, RULES, Severity
from horovod_tpu.analysis.findings import Finding, summarize


def rules_of(findings):
    return {f.rule for f in findings}


def lint(snippet):
    return lint_source(textwrap.dedent(snippet), "fixture.py")


# ---------------------------------------------------------------- HVD101
def test_hvd101_fires_on_rank_guarded_collective():
    findings = lint("""
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 0:
            hvd.broadcast(x, root_rank=0)
    """)
    assert "HVD101" in rules_of(findings)
    assert any(f.is_error for f in findings if f.rule == "HVD101")


def test_hvd101_fires_via_tainted_variable_and_local_rank():
    findings = lint("""
        import horovod_tpu as hvd
        rank = hvd.local_rank()
        if rank != 0:
            hvd.allreduce(x)
    """)
    assert "HVD101" in rules_of(findings)


def test_hvd101_fires_after_rank_divergent_early_return():
    findings = lint("""
        import horovod_tpu as hvd
        def save(x):
            rank = hvd.rank()
            if rank != 0:
                return None
            return hvd.allgather(x)
    """)
    assert "HVD101" in rules_of(findings)


def test_hvd101_quiet_on_print_only_branch():
    findings = lint("""
        import horovod_tpu as hvd
        loss = hvd.allreduce(x, name="loss")
        if hvd.rank() == 0:
            print(loss)
    """)
    assert "HVD101" not in rules_of(findings)


def test_hvd101_quiet_on_join():
    # join() is the sanctioned rank-divergent call (uneven final batches).
    findings = lint("""
        import horovod_tpu as hvd
        if hvd.rank() == 1:
            hvd.join()
    """)
    assert "HVD101" not in rules_of(findings)


def test_hvd101_suppression_comment():
    findings = lint("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.broadcast(x, root_rank=0)  # hvd-lint: disable=HVD101
    """)
    assert "HVD101" not in rules_of(findings)


# ---------------------------------------------------------------- HVD102
def test_hvd102_fires_when_subgroup_sets_exist():
    findings = lint("""
        import horovod_tpu as hvd
        evens = hvd.add_process_set([0, 2])
        hvd.allreduce(x, process_set=evens)
        hvd.allreduce(y)
    """)
    hits = [f for f in findings if f.rule == "HVD102"]
    assert len(hits) == 1 and hits[0].line == 5


def test_hvd102_quiet_without_subgroup_sets():
    findings = lint("""
        import horovod_tpu as hvd
        hvd.allreduce(y)
    """)
    assert "HVD102" not in rules_of(findings)


# ---------------------------------------------------------------- HVD103
def test_hvd103_fires_on_unbroadcast_training_script():
    findings = lint("""
        import horovod_tpu as hvd
        hvd.init()
        opt = hvd.DistributedOptimizer(opt)
    """)
    assert "HVD103" in rules_of(findings)


def test_hvd103_quiet_with_broadcast_parameters():
    findings = lint("""
        import horovod_tpu as hvd
        hvd.init()
        opt = hvd.DistributedOptimizer(opt)
        params = hvd.broadcast_parameters(params, root_rank=0)
    """)
    assert "HVD103" not in rules_of(findings)


def test_hvd103_quiet_with_elastic_state():
    findings = lint("""
        import horovod_tpu as hvd
        from horovod_tpu.elastic import JaxState
        hvd.init()
        opt = hvd.DistributedOptimizer(opt)
        state = JaxState(params=params, opt_state=s, epoch=0)
    """)
    assert "HVD103" not in rules_of(findings)


def test_hvd103_quiet_with_elastic_run_decorator():
    findings = lint("""
        import horovod_tpu as hvd
        from horovod_tpu.elastic import run
        @run
        def train(state):
            pass
        hvd.init()
        opt = hvd.DistributedOptimizer(opt)
    """)
    assert "HVD103" not in rules_of(findings)


def test_hvd103_not_suppressed_by_unrelated_run_call():
    findings = lint("""
        import horovod_tpu as hvd
        hvd.init()
        opt = hvd.DistributedOptimizer(opt)
        app.run()
    """)
    assert "HVD103" in rules_of(findings)


# ------------------------------------------------------------ HVD104/105
def test_hvd104_fires_on_set_iteration():
    findings = lint("""
        import horovod_tpu as hvd
        for name in {"b", "a"}:
            hvd.allreduce_async(grads[name], name=name)
    """)
    assert "HVD104" in rules_of(findings)


def test_hvd104_fires_on_set_call():
    findings = lint("""
        import horovod_tpu as hvd
        for name in set(grads):
            hvd.allreduce_async(grads[name], name=name)
    """)
    assert "HVD104" in rules_of(findings)


def test_hvd105_fires_on_dict_items():
    findings = lint("""
        import horovod_tpu as hvd
        for k, v in params.items():
            hvd.broadcast_async(v, name=k)
    """)
    hits = [f for f in findings if f.rule == "HVD105"]
    assert hits and not hits[0].is_error  # warning severity


def test_hvd104_105_quiet_when_sorted():
    findings = lint("""
        import horovod_tpu as hvd
        for name in sorted(set(grads)):
            hvd.allreduce_async(grads[name], name=name)
        for k, v in sorted(params.items()):
            hvd.broadcast_async(v, name=k)
    """)
    assert not ({"HVD104", "HVD105"} & rules_of(findings))


def test_hvd104_quiet_on_list_iteration():
    findings = lint("""
        import horovod_tpu as hvd
        for t in tensors:
            hvd.allreduce_async(t)
    """)
    assert not ({"HVD104", "HVD105"} & rules_of(findings))


# ------------------------------------------------------------ HVD106/107
def test_hvd106_fires_on_block_until_ready_in_jit():
    findings = lint("""
        import jax
        @jax.jit
        def step(x):
            jax.block_until_ready(x)
            return x
    """)
    assert "HVD106" in rules_of(findings)


def test_hvd106_fires_under_partial_jit():
    findings = lint("""
        import jax, functools
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(x):
            jax.io_callback(cb, None, x)
            return x
    """)
    assert "HVD106" in rules_of(findings)


def test_hvd106_quiet_outside_jit():
    findings = lint("""
        import jax
        def step(x):
            jax.block_until_ready(x)
            return x
    """)
    assert "HVD106" not in rules_of(findings)


def test_hvd107_fires_on_eager_collective_in_jit():
    findings = lint("""
        import jax
        import horovod_tpu as hvd
        @jax.jit
        def step(x):
            return hvd.allreduce(x)
    """)
    assert "HVD107" in rules_of(findings)


def test_hvd107_quiet_on_in_graph_collective():
    # axis_name= marks the in-graph lax.psum spelling — jit-safe.
    findings = lint("""
        import jax
        from horovod_tpu.ops import collectives as C
        @jax.jit
        def step(x):
            return C.allreduce(x, axis_name="hvd")
    """)
    assert "HVD107" not in rules_of(findings)


def test_hvd107_quiet_on_in_graph_default_axis():
    # C.allreduce(x) relying on DEFAULT_AXIS is correct in-graph code.
    findings = lint("""
        import jax
        from horovod_tpu.ops import collectives as C
        @jax.jit
        def step(x):
            return C.allreduce(x)
    """)
    assert "HVD107" not in rules_of(findings)


# ---------------------------------------------------------------- HVD110
def test_hvd110_fires_on_rank_derived_sharded_flag():
    findings = lint("""
        import horovod_tpu as hvd
        import optax

        opt = hvd.DistributedOptimizer(optax.adam(1e-3),
                                       sharded=hvd.rank() == 0)
    """)
    assert "HVD110" in rules_of(findings)
    f = next(x for x in findings if x.rule == "HVD110")
    assert f.is_error and "rank identity" in f.message


def test_hvd110_fires_via_tainted_shard_count():
    findings = lint("""
        import horovod_tpu as hvd

        def scatter(x):
            n = hvd.local_rank()
            return hvd.grouped_reducescatter([x], num_shards=n + 1)
    """)
    assert "HVD110" in rules_of(findings)


def test_hvd110_quiet_on_constant_and_env_flags():
    findings = lint("""
        import os
        import horovod_tpu as hvd
        import optax

        opt = hvd.DistributedOptimizer(optax.adam(1e-3), sharded=True)
        flag = bool(int(os.environ.get("HOROVOD_SHARDED_OPTIMIZER", "0")))
        opt2 = hvd.DistributedOptimizer(optax.adam(1e-3), sharded=flag)
    """)
    assert "HVD110" not in rules_of(findings)


# ---------------------------------------------------------------- misc lint
def test_lint_source_handles_syntax_error():
    findings = lint_source("def broken(:\n", "bad.py")
    assert findings and findings[0].rule == "HVD100" and findings[0].is_error


def test_rule_catalog_ids_and_severities():
    # ≥ 6 distinct lint rule classes, each with catalog metadata.
    lint_ids = {"HVD101", "HVD102", "HVD103", "HVD104", "HVD105",
                "HVD106", "HVD107", "HVD110"}
    assert lint_ids <= set(RULES)
    assert RULES["HVD101"].severity is Severity.ERROR
    assert RULES["HVD110"].severity is Severity.ERROR
    assert RULES["HVD105"].severity is Severity.WARNING
    assert summarize([Finding("HVD101", "f.py", 1, 1, "m")]).startswith("1 ")


# ================================================================ trace_check
def test_trace_check_clean_toy_shard_map_step(world_size):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.compat import shard_map
    from horovod_tpu.analysis.trace_check import check_step_fn

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        g = jax.lax.psum(x, "dp")
        return g + jax.lax.axis_index("dp")

    step = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                     check_vma=False)
    report = check_step_fn(step, jnp.zeros((world_size, 4)), mesh=mesh)
    assert report.ok, [f.render() for f in report.findings]
    prims = [r.primitive for r in report.ledger]
    assert "psum" in prims and "axis_index" in prims
    psum = report.ledger[prims.index("psum")]
    assert psum.axes == ("dp",)
    assert psum.dtypes == ("float32",)


def test_trace_check_flags_unknown_axis():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return lax.psum(x, "tp")          # only "dp" is bound

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 8})
    assert not report.ok
    assert any(f.rule == "HVD201" for f in report.findings)
    assert any("tp" in f.message for f in report.findings)


def test_trace_check_flags_bad_axis_index_groups():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        # groups cover ranks 0-3 of an 8-wide axis: 4-7 wait forever.
        return lax.psum(x, "dp", axis_index_groups=[[0, 1], [2, 3]])

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 8})
    assert any(f.rule == "HVD202" for f in report.findings)


def test_trace_check_flags_host_callback():
    import jax
    import jax.numpy as jnp
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    report = check_step_fn(step, jnp.zeros((4,)))
    assert any(f.rule == "HVD203" for f in report.findings)


def test_compare_ledgers_names_first_divergence():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import (check_step_fn,
                                                  compare_ledgers)

    def step_a(x):
        y = lax.psum(x, "dp")
        return lax.pmax(y, "dp")

    def step_b(x):
        y = lax.pmax(x, "dp")             # reordered vs step_a
        return lax.psum(y, "dp")

    x = jnp.zeros((4,))
    la = check_step_fn(step_a, x, axis_sizes={"dp": 8}).ledger
    lb = check_step_fn(step_b, x, axis_sizes={"dp": 8}).ledger
    same = compare_ledgers(la, la)
    assert not same
    diff = compare_ledgers(la, lb, names=("rank 0", "rank 1"))
    assert diff and diff[0].rule == "HVD301"
    assert "#0" in diff[0].message and "rank 0" in diff[0].message


def test_compare_ledgers_flags_extra_collective():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import (check_step_fn,
                                                  compare_ledgers)

    def one(x):
        return lax.psum(x, "dp")

    def two(x):
        return lax.pmax(lax.psum(x, "dp"), "dp")

    x = jnp.zeros((4,))
    la = check_step_fn(one, x, axis_sizes={"dp": 8}).ledger
    lb = check_step_fn(two, x, axis_sizes={"dp": 8}).ledger
    diff = compare_ledgers(la, lb)
    assert diff and diff[0].rule == "HVD301"
    assert "block forever" in diff[0].message


# ============================================================ runtime sanitizer
class _FakeEntry:
    def __init__(self, name, shape=(4,), dtype=np.float32):
        self.name = name
        self.tensor = np.zeros((2,) + shape, dtype)
        from horovod_tpu.ops.engine import CollectiveType
        from horovod_tpu.ops import collectives as C
        self.ctype = CollectiveType.ALLREDUCE
        self.reduce_op = C.ReduceOp.AVERAGE
        self.root_rank = 0
        self.process_set_id = 0
        self.prescale_factor = None
        self.postscale_factor = None


def test_sanitizer_records_and_tags():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer(capacity=8)
    e1, e2 = _FakeEntry("a"), _FakeEntry("b", shape=(8,))
    s.observe([e1, e2])
    assert e1.sanitizer_tag.startswith("seq=0:0;site=")
    assert e2.sanitizer_tag.startswith("seq=0:1;site=")
    # The call site is THIS test file, not engine internals.
    assert "test_analysis.py" in e1.sanitizer_tag
    tail = s.tail()
    assert [t.name for t in tail] == ["a", "b"]
    assert "(8,)" in tail[1].digest
    assert "last submissions" in s.render_tail()


def test_sanitizer_seq_is_per_process_set():
    """Subgroup collectives are only submitted by member ranks; a global
    counter would drift on non-members and false-positive every later
    world collective.  Counters are therefore per process set."""
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer()
    world = _FakeEntry("w0")
    sub = _FakeEntry("s0")
    sub.process_set_id = 7
    world2 = _FakeEntry("w1")
    s.observe([world])
    s.observe([sub])
    s.observe([world2])
    assert world.sanitizer_tag.startswith("seq=0:0;")
    assert sub.sanitizer_tag.startswith("seq=7:0;")
    assert world2.sanitizer_tag.startswith("seq=0:1;")  # not 0:2


def test_sanitizer_synthesized_entries_keep_seq_aligned():
    """hvd.join: a joined rank synthesizes identity entries for peers'
    collectives; the counter must advance as if it had submitted, or every
    post-join collective mismatches on seq."""
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer()
    s.observe([_FakeEntry("pre")])
    s.observe_synthesized(_FakeEntry("peer.0"))
    post = _FakeEntry("post")
    s.observe([post])
    assert post.sanitizer_tag.startswith("seq=0:2;")
    assert s.tail()[1].site == "<joined:synthesized>"


def test_sanitizer_rollback_on_rejected_push():
    """Duplicate-name queue rejection is rank-local: the seq advance must
    be undone or every later tag skews against the peers'."""
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer()
    ok = _FakeEntry("ok")
    s.observe([ok])
    rejected = _FakeEntry("dup")
    s.observe([rejected])
    s.rollback([rejected])
    after = _FakeEntry("after")
    s.observe([after])
    assert after.sanitizer_tag.startswith("seq=0:1;")   # reused the slot
    assert [t.name for t in s.tail()] == ["ok", "after"]


def test_sanitizer_ledger_is_bounded():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer(capacity=4)
    for i in range(10):
        s.observe([_FakeEntry(f"t{i}")])
    assert len(s.ledger) == 4
    assert s.tail(2)[-1].seq == 9  # seq keeps counting past eviction


def test_controller_digest_is_step_invariant_tag_rides_beside():
    """Since the response-cache fast path, the sanitizer tag no longer
    rides INSIDE the digest (that would churn the slot key every step): the
    digest stays step-invariant and the tag travels in the announce's
    separate field — the server folds it back into its effective-digest
    comparison (csrc/coordinator.cc), so divergence detection is
    unchanged, now also on the cached/bitvector path
    (tests/test_response_cache.py)."""
    from horovod_tpu.common.controller import TCPController

    e = _FakeEntry("t")
    base = TCPController._digest(e)
    e.sanitizer_tag = "seq=3;site=train.py:17"
    assert TCPController._digest(e) == base  # tag NOT in the slot key
    # negotiate() sends the tag as the announce's 6th field; the server
    # compares digest + "|" + tag — same mismatch semantics as before.


def test_sanitizer_disabled_by_default(monkeypatch):
    from horovod_tpu.analysis import runtime_sanitizer as rts

    monkeypatch.delenv("HVD_TPU_SANITIZER", raising=False)
    assert not rts.enabled()

    class _Eng:
        stall = None
    assert rts.maybe_install(_Eng()) is None


def test_sanitizer_stall_wrapper_reports_ledger():
    import logging
    from horovod_tpu.analysis.runtime_sanitizer import (
        CollectiveSanitizer, SanitizerStallInspector)
    from horovod_tpu.ops.engine import StallInspector

    s = CollectiveSanitizer()
    e = _FakeEntry("slow")
    s.observe([e])
    e.enqueue_time = -1e9  # ancient: guaranteed past any threshold
    inner = StallInspector(warn_after_s=10.0, shutdown_after_s=0)
    wrapped = SanitizerStallInspector(inner, s, warn_after_s=0.001)
    # The project logger doesn't propagate; capture with our own handler.
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("horovod_tpu")
    cap = _Capture()
    logger.addHandler(cap)
    try:
        wrapped.check([e], missing_ranks={"slow": [1]})
    finally:
        logger.removeHandler(cap)
    text = "\n".join(records)
    assert "HVD302" in text and "slow" in text
    assert "ranks [1]" in text
    assert "test_analysis.py" in text  # divergent call site named

    # Shutdown path: RuntimeError carries the ledger tail.
    inner2 = StallInspector(warn_after_s=0.001, shutdown_after_s=0.002)
    wrapped2 = SanitizerStallInspector(inner2, s, warn_after_s=0.001)
    with pytest.raises(RuntimeError, match="HVD302"):
        wrapped2.check([e])


# ===================================================================== CLI
def test_cli_exit_codes_and_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.broadcast(x, root_rank=0)
    """))
    clean = tmp_path / "clean.py"
    clean.write_text("import horovod_tpu as hvd\nhvd.allreduce(x)\n")

    from horovod_tpu.analysis.__main__ import main

    assert main([str(clean)]) == 0
    assert main([str(bad)]) == 1
    assert main([str(bad), "--disable", "HVD101"]) == 0
    assert main([]) == 2
    assert main(["--list-rules"]) == 0
    assert main([str(bad), "--json"]) == 1
    # Missing path: usage error, not a crash or a clean verdict.
    assert main([str(tmp_path / "nonexistent.py")]) == 2
    # Explicit suffix-less file is linted, not silently skipped.
    noext = tmp_path / "trainscript"
    noext.write_text(bad.read_text())
    assert main([str(noext)]) == 1


def test_cli_subprocess_entrypoint(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import horovod_tpu as hvd\n"
        "if hvd.rank() == 0:\n"
        "    hvd.barrier()\n")
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis", str(bad)],
        capture_output=True, text=True)
    assert res.returncode == 1, res.stderr
    assert "HVD101" in res.stdout


# ============================================================== check= hook
def test_check_hook_strict_raises_on_caller_errors(tmp_path, monkeypatch):
    from horovod_tpu.analysis.hooks import (CollectiveCheckError,
                                            run_check_hook)

    bad = tmp_path / "train.py"
    bad.write_text(
        "import horovod_tpu as hvd\n"
        "if hvd.rank() == 0:\n"
        "    hvd.broadcast(x, root_rank=0)\n")
    with pytest.raises(CollectiveCheckError) as ei:
        run_check_hook("strict", caller_file=str(bad))
    assert any(f.rule == "HVD101" for f in ei.value.findings)

    # warn mode: findings returned, no raise
    findings = run_check_hook(True, caller_file=str(bad))
    assert any(f.rule == "HVD101" for f in findings)
    assert run_check_hook(False, caller_file=str(bad)) == []


def test_distributed_optimizer_check_hook(hvd, tmp_path):
    import optax
    # check=True on a clean caller (this test file): must not raise and
    # must return a working optimizer.
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), check=True)
    params = {"w": np.zeros(3, np.float32)}
    state = opt.init(params)
    assert state is not None


# ================================================== HVD204: ppermute perms
def test_hvd204_clean_on_full_ring():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return lax.ppermute(x, "dp", perm=[(i, (i + 1) % 8)
                                           for i in range(8)])

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 8})
    assert not any(f.rule == "HVD204" for f in report.findings), \
        [f.render() for f in report.findings]
    assert any(r.primitive == "ppermute" for r in report.ledger)


def test_hvd204_fires_on_duplicate_destination():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        # ranks 0 and 1 both send to 0; rank 1 never receives.
        return lax.ppermute(x, "dp", perm=[(0, 0), (1, 0)])

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 2})
    f204 = [f for f in report.findings if f.rule == "HVD204"]
    assert f204 and f204[0].is_error
    assert "receive more than once" in f204[0].message


def test_hvd204_fires_on_out_of_range_rank():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return lax.ppermute(x, "dp", perm=[(0, 1), (1, 7)])  # axis size 2

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 2})
    f204 = [f for f in report.findings if f.rule == "HVD204"]
    assert f204 and "outside" in f204[0].message


def test_hvd204_fires_on_uncovered_ranks():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        # Partial shift: rank 7 never sends, rank 0 never receives — a
        # multi-host launch deadlocks exactly like bad axis_index_groups.
        return lax.ppermute(x, "dp", perm=[(i, i + 1) for i in range(7)])

    report = check_step_fn(step, jnp.zeros((4,)), axis_sizes={"dp": 8})
    f204 = [f for f in report.findings if f.rule == "HVD204"]
    assert f204 and "[7]" in f204[0].message
    # Partial perms are valid (zero-fill) JAX — flagged, but not an error,
    # so check="strict" never rejects a correct non-wrapping shift.
    assert not f204[0].is_error


def test_repo_ring_and_pipeline_perms_are_bijective(world_size):
    """The repo's own ppermute users (pipeline ring, adasum VHDD) must lint
    clean under HVD204 — they are full bijections by construction."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.compat import shard_map
    from horovod_tpu.analysis.trace_check import check_step_fn
    from horovod_tpu.parallel.pipeline import pipeline_apply

    mesh = Mesh(np.array(jax.devices()), ("pp",))

    def body(xs):
        return pipeline_apply(lambda p, x: x * 2.0, jnp.zeros(()), xs,
                              axis_name="pp")

    step = shard_map(body, mesh=mesh, in_specs=P(None, "pp"),
                     out_specs=P(None, "pp"), check_vma=False)
    report = check_step_fn(
        step, jnp.zeros((4, world_size, 2)), mesh=mesh)
    assert not any(f.rule == "HVD204" for f in report.findings), \
        [f.render() for f in report.findings]


# ============================================= spmd check= trace-time audit
def _toy_spmd_pieces(world_size, bad_perm=False):
    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("hvd",))

    def step(params, opt_state, tokens, targets):
        g = lax.psum(jnp.mean((tokens - params) ** 2), "hvd")
        if bad_perm:
            tokens = lax.ppermute(tokens, "hvd", perm=[(0, 0), (1, 0)])
        loss = g + jnp.sum(tokens * 0.0) + jnp.sum(targets * 0.0)
        return params - 0.1 * g, opt_state, loss

    import jax.numpy as jnp  # noqa: F401 - used in step closure
    params = jax.device_put(np.zeros((), np.float32))
    opt_state = jax.device_put(np.zeros((), np.float32))
    data = np.ones((world_size, 2), np.float32)
    return mesh, step, params, opt_state, data


def test_spmd_check_true_runs_clean_step(world_size):
    import jax.numpy as jnp  # noqa: F401
    from horovod_tpu.parallel import spmd
    from jax.sharding import PartitionSpec as P

    mesh, step, params, opt_state, data = _toy_spmd_pieces(world_size)
    fn = spmd.make_sharded_train_step(step, mesh, P(), P(), P("hvd"),
                                      check=True)
    p, o, loss = fn(params, opt_state, data, data)
    assert float(loss) == float(loss)  # ran, finite-path


def test_spmd_check_strict_raises_on_bad_ppermute(world_size):
    import jax.numpy as jnp  # noqa: F401
    from horovod_tpu.parallel import spmd
    from jax.sharding import PartitionSpec as P

    if world_size < 2:
        pytest.skip("needs >= 2 devices")
    mesh, step, params, opt_state, data = _toy_spmd_pieces(world_size,
                                                           bad_perm=True)
    fn = spmd.make_sharded_train_step(step, mesh, P(), P(), P("hvd"),
                                      check="strict")
    with pytest.raises(RuntimeError, match="HVD204"):
        fn(params, opt_state, data, data)


# ================================== HVD201-203 on shard_map-partitioned fns
# The compat-shimmed shard_map path (horovod_tpu.compat.shard_map) had no
# direct trace-check coverage: the mesh axes are bound INSIDE the traced
# jaxpr by the shard_map eqn's params, not by the outer axis_env, so the
# walker's sub-jaxpr descent is what these tests pin down.
def test_trace_check_hvd201_unknown_axis_inside_shard_map(world_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.compat import shard_map
    from horovod_tpu.analysis.trace_check import check_step_fn

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        return jax.lax.psum(x, "tp")      # mesh binds only "dp"

    step = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                     check_vma=False)
    report = check_step_fn(step, jnp.zeros((world_size, 4)), mesh=mesh)
    assert not report.ok
    assert any(f.rule == "HVD201" for f in report.findings)
    assert any("tp" in f.message for f in report.findings)


def test_trace_check_hvd202_bad_groups_inside_shard_map(world_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.compat import shard_map
    from horovod_tpu.analysis.trace_check import check_step_fn

    if world_size < 4:
        pytest.skip("needs >= 4 devices for a non-partitioning group set")
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    half = [[0, 1], [2, 3]]               # covers 0-3 of the dp axis only

    def body(x):
        return jax.lax.psum(x, "dp", axis_index_groups=half)

    step = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                     check_vma=False)
    report = check_step_fn(step, jnp.zeros((world_size, 4)), mesh=mesh)
    f202 = [f for f in report.findings if f.rule == "HVD202"]
    assert f202, [f.render() for f in report.findings]
    assert "partition" in f202[0].message


def test_trace_check_hvd203_callback_inside_shard_map(world_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.compat import shard_map
    from horovod_tpu.analysis.trace_check import check_step_fn

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        g = jax.lax.psum(x, "dp")
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(g.shape, g.dtype), g)

    step = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                     check_vma=False)
    report = check_step_fn(step, jnp.zeros((world_size, 4)), mesh=mesh)
    assert any(f.rule == "HVD203" for f in report.findings), \
        [f.render() for f in report.findings]
    # The ledger still records the psum that precedes the callback.
    assert any(r.primitive == "psum" for r in report.ledger)


def test_hvd204_clean_on_multi_axis_ring():
    """Ranks in a multi-axis ppermute index the axes' flattened product:
    a full 4-ring over a 2x2 ('a','b') mesh must not be flagged."""
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return lax.ppermute(x, ("a", "b"),
                            perm=[(i, (i + 1) % 4) for i in range(4)])

    report = check_step_fn(step, jnp.zeros((4,)),
                           axis_sizes={"a": 2, "b": 2})
    assert not any(f.rule == "HVD204" for f in report.findings), \
        [f.render() for f in report.findings]


# =============================================== process-set lanes (ISSUE 16)
# Satellite: sorted() neutralizes the ITERATION order, but rank-varying
# process_set=/priorities= kwargs on the grouped op still diverge per rank.
def test_hvd105_sorted_does_not_excuse_rank_varying_process_set():
    findings = lint("""
        import horovod_tpu as hvd
        for k in sorted(groups.keys()):
            hvd.grouped_allreduce(groups[k],
                                  process_set=sets[hvd.rank() % 2])
    """)
    hits = [f for f in findings if f.rule == "HVD105"]
    assert hits and "process_set=" in hits[0].message


def test_hvd104_sorted_key_derived_from_rank_is_no_neutralizer():
    findings = lint("""
        import horovod_tpu as hvd
        r = hvd.rank()
        for name in sorted(set(grads), key=lambda n: (hash(n) + r) % 7):
            hvd.allreduce_async(grads[name], name=name)
    """)
    assert "HVD104" in rules_of(findings)


def test_hvd104_105_sorted_with_uniform_kwargs_stays_quiet():
    findings = lint("""
        import horovod_tpu as hvd
        for k in sorted(groups.keys()):
            hvd.grouped_allreduce(groups[k], process_set=tenants)
    """)
    assert not ({"HVD104", "HVD105"} & rules_of(findings))


# HVD112 (AST half): collective axis absent from its binding mesh.
def test_hvd112_fires_on_axis_absent_from_shard_map_mesh():
    findings = lint("""
        import jax
        from functools import partial
        from jax import lax
        from jax.sharding import Mesh, PartitionSpec as P
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.compat import shard_map

        mesh = make_mesh({"fsdp": 4, "tp": 2})

        @partial(shard_map, mesh=mesh, in_specs=P("fsdp"),
                 out_specs=P("fsdp"))
        def step(x):
            return lax.psum(x, "dp")
    """)
    hits = [f for f in findings if f.rule == "HVD112"]
    assert hits and hits[0].is_error
    assert "'dp'" in hits[0].message and "fsdp" in hits[0].message


def test_hvd112_fires_on_partition_spec_with_unknown_axis():
    findings = lint("""
        from functools import partial
        from jax import lax
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"fsdp": 4, "tp": 2})

        def impl(x):
            return lax.psum(x, "tp")

        step = shard_map(impl, mesh=mesh, in_specs=P("badaxis"),
                         out_specs=P("fsdp"))
    """)
    hits = [f for f in findings if f.rule == "HVD112"]
    assert hits and "badaxis" in hits[0].message


def test_hvd112_quiet_when_axis_is_bound():
    findings = lint("""
        from functools import partial
        from jax import lax
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"fsdp": 4, "tp": 2})

        @partial(shard_map, mesh=mesh, in_specs=P("fsdp"),
                 out_specs=P(("fsdp", "tp")))
        def step(x):
            x = lax.psum(x, "tp")
            return lax.pmean(x, ("fsdp", "tp"))
    """)
    assert "HVD112" not in rules_of(findings)


def test_hvd112_quiet_when_mesh_axes_unknown():
    """Dynamically built meshes (non-literal axis dict) resolve to no
    axis set: the rule must stay conservative, not guess."""
    findings = lint("""
        from functools import partial
        from jax import lax
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(dict(axes))

        @partial(shard_map, mesh=mesh, in_specs=P("fsdp"),
                 out_specs=P("fsdp"))
        def step(x):
            return lax.psum(x, "anything")
    """)
    assert "HVD112" not in rules_of(findings)


# HVD112 (jaxpr half): bound axis outside the declared partition axes.
def test_trace_check_hvd112_bound_but_undeclared_axis():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        g = lax.psum(x, "dp")            # declared: fine
        return lax.psum(g, "tp")         # bound but undeclared: HVD112

    report = check_step_fn(step, jnp.ones((4,)),
                           axis_sizes={"dp": 2, "tp": 2},
                           partition_axes=["dp"])
    hits = [f for f in report.findings if f.rule == "HVD112"]
    assert len(hits) == 1 and hits[0].is_error
    assert "'tp'" in hits[0].message and "replicated" in hits[0].message


def test_trace_check_hvd112_axis_index_is_exempt():
    """axis_index over an undeclared axis moves no data (rng folding,
    shard bookkeeping) — only data-moving collectives fire."""
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        i = lax.axis_index("tp")
        return lax.psum(x + i, "dp")

    report = check_step_fn(step, jnp.ones((4,)),
                           axis_sizes={"dp": 2, "tp": 2},
                           partition_axes=["dp"])
    assert "HVD112" not in {f.rule for f in report.findings}


def test_trace_check_no_partition_axes_keeps_old_contract():
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.analysis.trace_check import check_step_fn

    def step(x):
        return lax.psum(lax.psum(x, "dp"), "tp")

    report = check_step_fn(step, jnp.ones((4,)),
                           axis_sizes={"dp": 2, "tp": 2})
    assert not report.findings


def test_spmd_derives_partition_axes_from_specs():
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel.spmd import _spec_axes

    axes = _spec_axes((P("dp"), {"w": P(None, ("fsdp", "tp"))}, P()))
    assert axes == {"dp", "fsdp", "tp"}
    assert _spec_axes((P(), P())) == set()


# Per-process-set sanitizer namespace.
def test_sanitizer_ledger_is_namespaced_per_process_set():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer(capacity=8)
    w0, t0, w1 = _FakeEntry("w0"), _FakeEntry("t0"), _FakeEntry("w1")
    t0.process_set_id = 7
    s.observe([w0]); s.observe([t0]); s.observe([w1])
    # Combined stream sees everything; per-set views are filtered.
    assert [e.name for e in s.tail()] == ["w0", "t0", "w1"]
    assert [e.name for e in s.tail(process_set=7)] == ["t0"]
    assert [e.name for e in s.tail(process_set=0)] == ["w0", "w1"]
    # Rendered per-set tails are scoped and prefixed with the namespace.
    r = s.render_tail(process_set=7)
    assert "process set 7" in r and "#7:0 t0" in r
    assert s.render_tail(process_set=3) == \
        "(collective ledger (process set 3) empty)"


def test_sanitizer_rollback_pops_per_set_view_too():
    from horovod_tpu.analysis.runtime_sanitizer import CollectiveSanitizer

    s = CollectiveSanitizer()
    keep = _FakeEntry("keep")
    keep.process_set_id = 7
    s.observe([keep])
    rejected = _FakeEntry("dup")
    rejected.process_set_id = 7
    s.observe([rejected])
    s.rollback([rejected])
    assert [e.name for e in s.tail(process_set=7)] == ["keep"]
    after = _FakeEntry("after")
    after.process_set_id = 7
    s.observe([after])
    assert after.sanitizer_tag.startswith("seq=7:1;")
