"""Parallelism primitive tests: ring attention, Ulysses, ZeRO, hierarchical
allreduce, Adasum — each against a locally computed reference.
"""

import jax
import jax.export  # noqa: F401  (not auto-imported on jax<=0.4)
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.mesh import make_mesh, infer_mesh


def _qkv(B=2, T=32, H=4, D=16, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(B, T, H, D).astype(dtype)) for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_local(causal):
    from horovod_tpu.parallel.ring_attention import (
        ring_attention, local_flash_attention)
    q, k, v = _qkv()
    ref = local_flash_attention(q, k, v, causal=causal)

    mesh = make_mesh({"sp": 8})
    out = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_local(causal, monkeypatch):
    """The pallas-flash ring engine (use_flash=True; interpret mode on CPU)
    == the single-device reference — values AND all three gradients through
    the custom-VJP backward ring (VERDICT r3 weak #5b)."""
    import importlib
    ra = importlib.import_module("horovod_tpu.parallel.ring_attention")
    # Spy: the flash path must never fall back to the jnp blockwise engine.
    monkeypatch.setattr(ra, "_block_attn",
                        lambda *a, **k: (_ for _ in ()).throw(
                            AssertionError("flash ring used _block_attn")))
    from jax import lax as _lax
    q, k, v = _qkv()
    ref = ra.local_flash_attention(q, k, v, causal=causal)

    mesh = make_mesh({"sp": 8})

    def ring(q, k, v):
        return ra.ring_attention(q, k, v, axis_name="sp", causal=causal,
                                 use_flash=True)

    out = jax.jit(shard_map(
        ring, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    def loss_ring(q, k, v):
        def f(q, k, v):
            o = ring(q, k, v)
            return _lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "sp")
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(),
            check_vma=False))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(
            ra.local_flash_attention(q, k, v, causal=causal)
            .astype(jnp.float32) ** 2)

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gqa(causal):
    """GQA through the flash ring: kv rotate UN-repeated (H/K× less ring
    traffic); values + grads == the materialized-repeat reference."""
    import importlib
    ra = importlib.import_module("horovod_tpu.parallel.ring_attention")
    from jax import lax as _lax
    rng = np.random.RandomState(11)
    B, T, H, K, D = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    kr = jnp.repeat(k, H // K, axis=2)
    vr = jnp.repeat(v, H // K, axis=2)
    ref = ra.local_flash_attention(q, kr, vr, causal=causal)

    mesh = make_mesh({"sp": 8})

    def ring(q, k, v):
        return ra.ring_attention(q, k, v, axis_name="sp", causal=causal,
                                 use_flash=True)

    out = jax.jit(shard_map(
        ring, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    def loss_ring(q, k, v):
        def f(q, k, v):
            return _lax.psum(
                jnp.sum(ring(q, k, v).astype(jnp.float32) ** 2), "sp")
        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(),
            check_vma=False))(q, k, v)

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, H // K, axis=2)
        vr = jnp.repeat(v, H // K, axis=2)
        return jnp.sum(ra.local_flash_attention(q, kr, vr, causal=causal)
                       .astype(jnp.float32) ** 2)

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_flash_tpu_lowering():
    """Cross-platform lowering of the FULL flash ring — forward and the
    custom-VJP backward ring — over an abstract sp mesh at real llama
    shapes (bf16, GQA, D=128): the Mosaic/TPU pipeline runs client-side,
    so a CPU host proves ring_attention on TPU lowers to the pallas
    kernels (VERDICT r3 ask #5 'assert on lowered HLO/stablehlo')."""
    import importlib
    from horovod_tpu.compat import abstract_mesh
    ra = importlib.import_module("horovod_tpu.parallel.ring_attention")
    mesh = abstract_mesh((4,), ("sp",))

    def f(q, k, v):
        def loss(q, k, v):
            o = ra.ring_attention(q, k, v, axis_name="sp", causal=True,
                                  use_flash=True, interpret=False)
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32)), "sp")
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    sm = shard_map(f, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                   out_specs=(P(None, "sp"),) * 3, check_vma=False)
    spec_q = jax.ShapeDtypeStruct((1, 2048, 8, 128), jnp.bfloat16)
    spec_kv = jax.ShapeDtypeStruct((1, 2048, 4, 128), jnp.bfloat16)
    exp = jax.export.export(jax.jit(sm), platforms=["tpu"])(
        spec_q, spec_kv, spec_kv)
    mod = exp.mlir_module()
    # The pallas kernels must actually be IN the lowered module (the jnp
    # fallback would lower to plain dots and pass a weaker length check).
    assert mod.count("tpu_custom_call") >= 3, mod.count("tpu_custom_call")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_local(causal):
    from horovod_tpu.parallel.ring_attention import local_flash_attention
    from horovod_tpu.parallel.ulysses import ulysses_attention
    q, k, v = _qkv(H=8)
    ref = local_flash_attention(q, k, v, causal=causal)

    mesh = make_mesh({"sp": 8})
    out = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                          causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_gqa(causal):
    """GQA kv travels UN-REPEATED through Ulysses' alltoall (the local
    attention handles shared kv heads natively): sp=4, H=8, K=4."""
    from horovod_tpu.parallel.ring_attention import local_flash_attention
    from horovod_tpu.parallel.ulysses import ulysses_attention
    rng = np.random.RandomState(13)
    B, T, H, K, D = 2, 32, 8, 4, 16
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, K, D), jnp.float32)
    ref = local_flash_attention(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2),
                                causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    out = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                          causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_zero_sharded_optimizer_matches_plain():
    """ZeRO-sharded adam == unsharded adam on the mean gradient."""
    from horovod_tpu.parallel.zero import sharded_optimizer

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(13, 7)
                               .astype(np.float32)),
              "b": jnp.zeros((7,), jnp.float32)}
    per_rank_grads = [
        jax.tree_util.tree_map(
            lambda p, r=r: jnp.asarray(
                np.random.RandomState(100 + r).randn(*p.shape)
                .astype(np.float32)), params)
        for r in range(8)]
    mean_grads = jax.tree_util.tree_map(
        lambda *gs: sum(gs) / len(gs), *per_rank_grads)

    inner = optax.adam(1e-2)
    ref_state = inner.init(params)
    ref_updates, _ = inner.update(mean_grads, ref_state, params)

    mesh = make_mesh({"dp": 8})
    zopt = sharded_optimizer(optax.adam(1e-2), axis_name="dp")

    def run(params, *grads_stacked):
        # inside shard_map: this rank's grads
        grads = {"w": grads_stacked[0].reshape(params["w"].shape),
                 "b": grads_stacked[1].reshape(params["b"].shape)}
        state = zopt.init(params)
        updates, _ = zopt.update(grads, state, params)
        return updates

    gw = jnp.stack([g["w"] for g in per_rank_grads])
    gb = jnp.stack([g["b"] for g in per_rank_grads])
    updates = jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(P(), P("dp"), P("dp")), out_specs=P(),
        check_vma=False))(params, gw, gb)
    for kk in ("w", "b"):
        np.testing.assert_allclose(np.asarray(updates[kk]),
                                   np.asarray(ref_updates[kk]),
                                   rtol=1e-4, atol=1e-6)


def test_distributed_optimizer_sharded_mixed_mode_raises():
    """init outside the mesh axis (plain-state fallback) + update inside
    shard_map over it must fail LOUDLY: the plain fallback would apply
    raw per-shard gradients with no reduction — silent replica
    divergence."""
    from horovod_tpu.jax.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.adam(1e-2), sharded=True)
    params = {"w": jnp.zeros((16,), jnp.float32)}
    state = opt.init(params)            # no axis in scope: plain state
    mesh = make_mesh({"hvd": 4}, devices=jax.devices()[:4])

    def step(p, s, g):
        u, _ = opt.update(g, s, p)
        return u

    with pytest.raises(RuntimeError, match="outside the mesh axis"):
        jax.jit(shard_map(step, mesh=mesh, in_specs=(P(), P(), P()),
                          out_specs=P(), check_vma=False))(
            params, state, params)


def test_zero_sharded_optimizer_matches_plain_adamw():
    """Param-DEPENDENT inner transform (adamw weight decay): the param
    shards the inner update sees must be this rank's true slice, never a
    psum over replicas — a world-scaled decay would silently train a
    different model (adam can't catch this; decay reads the params)."""
    from horovod_tpu.parallel.zero import sharded_optimizer

    params = {"w": jnp.asarray(np.random.RandomState(3).randn(257)
                               .astype(np.float32))}
    grads = {"w": jnp.asarray(np.random.RandomState(4).randn(257)
                              .astype(np.float32))}
    inner = optax.adamw(1e-2, weight_decay=0.1)
    ref_updates, _ = inner.update(grads, inner.init(params), params)

    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    zopt = sharded_optimizer(optax.adamw(1e-2, weight_decay=0.1),
                             axis_name="dp", average=True)

    def run(p, g):
        # every rank contributes the same grads: scatter-mean == grads
        state = zopt.init(p)
        updates, _ = zopt.update(g, state, p)
        return updates

    updates = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(params, grads)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               np.asarray(ref_updates["w"]),
                               rtol=1e-5, atol=1e-7)


# ------------------------------------------------ zero pad/slice edges
# Property-style coverage of the ONE sharding convention (ISSUE 15): the
# pure shard math, the host slicer, the state plane's jax-free twin and
# the in-graph shard/unshard must all agree on every edge — non-divisible
# leaves, bf16, empty, scalar, world 1.

def test_zero_shard_info_properties():
    from horovod_tpu.parallel.zero import shard_info
    for n in (0, 1, 2, 3, 7, 64, 257, 1023):
        for world in (1, 2, 3, 4, 8, 16, 1000):
            pad, per = shard_info(n, world)
            assert 0 <= pad < world
            assert (n + pad) == per * world          # even split, exactly
            assert per * world >= n                   # never loses elements
    assert shard_info(5, 1) == (0, 5)                 # world 1: identity
    assert shard_info(0, 4) == (0, 0)                 # empty leaf


def test_zero_host_slices_partition_and_roundtrip():
    from horovod_tpu.parallel.zero import (shard_info, shard_slice_host,
                                           unshard_host)
    rng = np.random.RandomState(0)
    for n, world, dtype in [(257, 4, np.float32), (7, 8, np.float32),
                            (66, 4, "bfloat16"), (1, 4, np.float32),
                            (0, 4, np.float32), (12, 1, np.float64),
                            (64, 2, np.int32)]:
        dtype = jnp.dtype(dtype)
        arr = np.asarray(rng.randn(n), dtype=dtype)
        shards = [shard_slice_host(arr, r, world) for r in range(world)]
        pad, per = shard_info(n, world)
        assert all(s.shape == (per,) for s in shards)
        # Concatenated slices == padded flat buffer (the partition law).
        cat = np.concatenate(shards) if shards else np.zeros(0, dtype)
        np.testing.assert_array_equal(cat[:n], arr)
        if pad:
            np.testing.assert_array_equal(
                cat[n:], np.zeros((pad,), dtype))
        # unshard_host inverts the slicing bitwise.
        back = unshard_host(shards, n, (n,), dtype)
        np.testing.assert_array_equal(back, arr)


def test_zero_host_slice_matches_stateplane_convention():
    """The state plane's jax-free slicer (churn harness, byte shards) and
    zero.py's host slicer implement the SAME convention — pinned so the
    checkpoint shard of a sharded optimizer state stays this rank's own
    slice."""
    from horovod_tpu.elastic.stateplane import shard_slice_array
    from horovod_tpu.parallel.zero import shard_slice_host
    rng = np.random.RandomState(1)
    for n, world in [(257, 4), (8, 8), (5, 2), (1, 3), (0, 2), (10, 1)]:
        arr = rng.randn(n).astype(np.float32)
        for r in range(world):
            np.testing.assert_array_equal(
                shard_slice_host(arr, r, world),
                shard_slice_array(arr, r, world))


def test_zero_shard_leaf_device_matches_host():
    """In-graph _shard_leaf under shard_map (a reduce+scatter: with every
    rank contributing the same leaf, the shard is the slice of world*x)
    == the host slicer of the summed leaf, for non-divisible, bf16,
    scalar, empty and world-1 leaves; _unshard_leaf round-trips the
    reduced value bitwise."""
    from horovod_tpu.parallel import zero

    for world, shape, dtype in [(4, (257,), jnp.float32),
                                (4, (16, 8), jnp.float32),
                                (4, (66,), jnp.bfloat16),
                                (4, (), jnp.float32),
                                (4, (0,), jnp.float32),
                                (1, (9,), jnp.float32)]:
        mesh = make_mesh({"dp": world}, devices=jax.devices()[:world])
        n = int(np.prod(shape)) if shape else 1
        arr = jnp.asarray(
            np.linspace(-1, 1, max(n, 1))[:n].reshape(shape), dtype)

        def run(x):
            s, pad = zero._shard_leaf(x, "dp")
            return s[None], zero._unshard_leaf(s, pad, shape, "dp")

        shards, back = jax.jit(shard_map(
            run, mesh=mesh, in_specs=(P(),), out_specs=(P("dp"), P()),
            check_vma=False))(arr)
        reduced = jax.device_get(
            (arr * world).astype(dtype))     # identical contributions sum
        for r in range(world):
            np.testing.assert_array_equal(
                np.asarray(shards)[r],
                zero.shard_slice_host(reduced, r, world))
        np.testing.assert_array_equal(np.asarray(back), reduced)


def test_zero_init_sharded_state_specs_and_memory():
    """init_sharded_state: state leaves live sharded P('dp') on the mesh
    (1/world per device), specs match the state structure, and the step
    built from them (models.mnist path) runs."""
    from horovod_tpu.parallel import zero
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    params = {"w": jnp.asarray(np.random.RandomState(0)
                               .randn(33, 3).astype(np.float32)),
              "s": jnp.asarray(1.5, jnp.float32)}
    state, specs = zero.init_sharded_state(optax.adam(1e-2), params, mesh,
                                           "dp")
    flat_state = jax.tree_util.tree_leaves(state)
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert len(flat_state) == len(flat_specs)
    for leaf, spec in zip(flat_state, flat_specs):
        if getattr(leaf, "ndim", 0) >= 1:
            assert spec == P("dp"), (leaf.shape, spec)
            # Each device holds exactly 1/world of the leaf.
            shard_sizes = {s.data.size for s in leaf.addressable_shards}
            assert shard_sizes == {leaf.size // 4}, shard_sizes
        else:
            assert spec == P(), spec


def test_hierarchical_allreduce():
    from horovod_tpu.parallel.hierarchical import hierarchical_allreduce
    mesh = make_mesh({"cross": 2, "local": 4})
    vals = np.random.RandomState(3).randn(8, 5, 3).astype(np.float32)
    x = jnp.asarray(vals)

    out = jax.jit(shard_map(
        lambda x: hierarchical_allreduce(x.reshape(x.shape[1:]),
                                         average=True)[None],
        mesh=mesh, in_specs=P(("cross", "local")),
        out_specs=P(("cross", "local")), check_vma=False))(x)
    expected = vals.mean(axis=0)
    for r in range(8):
        np.testing.assert_allclose(np.asarray(out)[r], expected, rtol=1e-5)


def test_adasum_properties():
    """Adasum invariants: orthogonal grads add; identical grads average."""
    from horovod_tpu.parallel.adasum import adasum_combine
    a = jnp.asarray([1.0, 0.0, 0.0])
    b = jnp.asarray([0.0, 1.0, 0.0])
    np.testing.assert_allclose(np.asarray(adasum_combine(a, b)),
                               [1.0, 1.0, 0.0], atol=1e-6)
    c = jnp.asarray([2.0, 2.0, 0.0])
    np.testing.assert_allclose(np.asarray(adasum_combine(c, c)),
                               np.asarray(c), atol=1e-5)


def test_adasum_allreduce_eager(hvd, world_size):
    """Eager Adasum op through the engine (reference: hvd.Adasum op)."""
    vals = [np.eye(4, dtype=np.float32)[r % 4][None] for r in range(world_size)]
    out = hvd.allreduce(hvd.stack_per_rank(vals), op=hvd.Adasum)
    assert np.asarray(out).shape == (1, 4)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("n", [4, 8])
def test_adasum_hd_equals_tree(n):
    """Halving-doubling Adasum ≡ gather-tree Adasum (VERDICT r2 #3 'done'
    criterion): the VHDD distributes the coefficient dot products across
    the active XOR subgroup, so its combine tree is numerically the same
    pairing as ``_tree_reduce`` — outputs match up to fp summation order."""
    from horovod_tpu.parallel.adasum import (_tree_reduce,
                                             adasum_allreduce_hd)
    mesh = make_mesh({"hvd": n}, devices=jax.devices()[:n])
    # Odd length exercises the padding path.
    vals = np.random.RandomState(7).randn(n, 17).astype(np.float32)
    x = jnp.asarray(vals)

    hd_out = jax.jit(shard_map(
        lambda x: adasum_allreduce_hd(x.reshape(-1), axis_name="hvd")[None],
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    expected = np.asarray(_tree_reduce(jnp.asarray(vals), n))
    assert np.isfinite(np.asarray(hd_out)).all()
    for r in range(n):
        np.testing.assert_allclose(np.asarray(hd_out)[r], expected,
                                   rtol=1e-4, atol=1e-5)


def test_adasum_hd_rejects_non_pow2():
    from jax.sharding import Mesh
    from horovod_tpu.parallel.adasum import adasum_allreduce_hd
    mesh = Mesh(np.array(jax.devices()[:6]), ("hvd",))
    vals = jnp.asarray(np.ones((6, 4), np.float32))
    with pytest.raises(ValueError, match="power-of-two"):
        jax.jit(shard_map(
            lambda x: adasum_allreduce_hd(x.reshape(-1),
                                          axis_name="hvd")[None],
            mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
            check_vma=False))(vals)


def test_torus_bit_order_validation():
    from horovod_tpu.parallel.adasum import torus_bit_order
    assert torus_bit_order(8, (2, 2, 2)) == [0, 1, 2]
    assert torus_bit_order(8, (4, 2)) == [0, 1, 2]
    assert torus_bit_order(16, (4, 2)) == [0, 1, 2, 3]  # 2 cores/chip
    assert torus_bit_order(8, (3, 3)) is None           # not pow2 extents
    assert torus_bit_order(6, (3, 2)) is None           # world not pow2
    assert torus_bit_order(8, None) is None


def test_infer_mesh_axes():
    m = infer_mesh(8, tp=2, sp=2)
    assert dict(zip(m.axis_names, m.devices.shape)) == {
        "dp": 2, "pp": 1, "ep": 1, "sp": 2, "tp": 2}
    with pytest.raises(ValueError):
        infer_mesh(8, tp=3)
