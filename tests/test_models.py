"""Model zoo tests: each model trains data-parallel on the virtual mesh and
the sharded run matches a single-device reference where applicable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.mesh import infer_mesh, make_mesh
from horovod_tpu.parallel import spmd


# ----------------------------------------------------------------- MNIST CNN
def test_mnist_trains():
    from horovod_tpu.models import mnist
    mesh = make_mesh({"hvd": 8})
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = mnist.make_sharded_train_step(opt, mesh)
    x, y = mnist.synthetic_batch(64)
    losses = []
    for i in range(6):
        params, opt_state, loss = step(params, opt_state, jnp.asarray(x),
                                       jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_mnist_dp_matches_single_device():
    from horovod_tpu.models import mnist
    x, y = mnist.synthetic_batch(32, seed=1)

    params0 = mnist.init_params(jax.random.PRNGKey(1))
    opt = optax.sgd(0.05)

    # single device
    step1 = jax.jit(mnist.make_train_step(opt, axis_name=None))
    p_ref, s_ref = params0, opt.init(params0)
    for _ in range(2):
        p_ref, s_ref, l_ref = step1(p_ref, s_ref, jnp.asarray(x),
                                    jnp.asarray(y))

    # 8-way dp
    mesh = make_mesh({"hvd": 8})
    stepN = mnist.make_sharded_train_step(opt, mesh)
    p, s = params0, opt.init(params0)
    for _ in range(2):
        p, s, l = stepN(p, s, jnp.asarray(x), jnp.asarray(y))

    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6)


# ----------------------------------------------------------------- ResNet
def test_resnet18_trains_with_syncbn():
    from horovod_tpu.models import resnet
    cfg = resnet.ResNetConfig(depth=18, num_classes=10, width=16,
                              compute_dtype=jnp.float32)
    mesh = make_mesh({"hvd": 8})
    params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.05, momentum=0.9)
    opt_state = opt.init(params)
    step = resnet.make_sharded_train_step(cfg, opt, mesh)
    x, y = resnet.synthetic_batch(16, image_size=32, num_classes=10)
    losses = []
    for _ in range(4):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # running BN stats actually moved
    assert not np.allclose(np.asarray(stats["stem"]["mean"]), 0.0)


def test_resnet50_forward_shape():
    from horovod_tpu.models import resnet
    cfg = resnet.ResNetConfig(depth=50, num_classes=1000, width=8,
                              compute_dtype=jnp.float32, sync_bn_axis=None)
    params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
    x, _ = resnet.synthetic_batch(2, image_size=64)
    logits, new_stats = jax.jit(
        lambda p, s, x: resnet.forward(p, s, x, cfg, train=False))(
        params, stats, jnp.asarray(x))
    assert logits.shape == (2, 1000)
    assert np.isfinite(np.asarray(logits)).all()


# ----------------------------------------------------------------- BERT
def test_bert_sharded_matches_reference():
    from horovod_tpu.models import bert

    tokens = np.random.RandomState(0).randint(0, 256, (8, 32)).astype(np.int32)
    targets = np.random.RandomState(1).randint(0, 256, (8, 32)).astype(np.int32)
    mask = (np.random.RandomState(2).rand(8, 32) < 0.25).astype(np.float32)

    cfg_ref = bert.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None,
                        sp_axis=None)
    params = bert.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    step_ref = jax.jit(bert.make_train_step(cfg_ref, opt))
    p_ref, s_ref = params, opt.init(params)
    ref_losses = []
    for _ in range(2):
        p_ref, s_ref, l = step_ref(p_ref, s_ref, jnp.asarray(tokens),
                                   jnp.asarray(targets), jnp.asarray(mask))
        ref_losses.append(float(l))

    cfg = bert.tiny(dtype=jnp.float32)
    mesh = infer_mesh(8, tp=2, sp=2)
    pspecs = bert.param_specs(cfg)
    p, s = params, opt.init(params)
    os_specs = spmd.infer_specs_like(s, params, pspecs)
    data_spec = P(("dp", "ep", "pp"), "sp")
    step = jax.jit(shard_map(
        bert.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(pspecs, os_specs, data_spec, data_spec, data_spec),
        out_specs=(pspecs, os_specs, P()), check_vma=False))
    losses = []
    for _ in range(2):
        p, s, l = step(p, s, jnp.asarray(tokens), jnp.asarray(targets),
                       jnp.asarray(mask))
        losses.append(float(l))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)


# ----------------------------------------------------------------- DLRM
def test_dlrm_sharded_matches_reference():
    from horovod_tpu.models import dlrm

    cfg_ref = dlrm.tiny(dp_axis=None, ep_axis=None)
    dense, sparse, labels = dlrm.synthetic_batch(cfg_ref, 16)
    params = dlrm.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    step_ref = jax.jit(dlrm.make_train_step(cfg_ref, opt))
    p_ref, s_ref = params, opt.init(params)
    ref_losses = []
    for _ in range(2):
        p_ref, s_ref, l = step_ref(p_ref, s_ref, jnp.asarray(dense),
                                   jnp.asarray(sparse), jnp.asarray(labels))
        ref_losses.append(float(l))

    cfg = dlrm.tiny()
    mesh = infer_mesh(8, ep=4)   # dp=2 x ep=4
    pspecs = dlrm.param_specs(cfg)
    p, s = params, opt.init(params)
    os_specs = spmd.infer_specs_like(s, params, pspecs)
    data_spec = P(("dp", "pp", "ep", "sp", "tp"))   # batch over dp AND ep
    step = jax.jit(shard_map(
        dlrm.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(pspecs, os_specs, data_spec, data_spec, data_spec),
        out_specs=(pspecs, os_specs, P()), check_vma=False))
    losses = []
    for _ in range(2):
        p, s, l = step(p, s, jnp.asarray(dense), jnp.asarray(sparse),
                       jnp.asarray(labels))
        losses.append(float(l))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    # table shards, recombined, match the reference tables
    tables = np.asarray(jax.device_get(p["tables"]))
    np.testing.assert_allclose(tables, np.asarray(p_ref["tables"]),
                               rtol=2e-3, atol=1e-6)


def test_llama_remat_layers_matches():
    """remat_layers=True recomputes the forward in backward (memory
    lever for models that do not otherwise fit — measured a throughput
    LOSS at bench scale, docs/benchmarks.md) and must be numerically
    invisible: same logits, same grads."""
    import jax
    import jax.numpy as jnp
    from horovod_tpu.models import llama

    base = dict(n_heads=4, n_kv_heads=2, d_model=64, d_ff=128,
                vocab_size=128, dtype=jnp.float32,
                dp_axis=None, tp_axis=None, sp_axis=None)
    cfg = llama.tiny(**base)
    cfg_r = llama.tiny(**base, remat_layers=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 33)),
                       jnp.int32)

    out = llama.forward(params, toks, cfg)
    out_r = llama.forward(params, toks, cfg_r)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_r))

    def loss(p, c):
        lg = llama.forward(p, toks, c)
        return jnp.mean((lg - 1.0) ** 2)

    g = jax.grad(lambda p: loss(p, cfg))(params)
    g_r = jax.grad(lambda p: loss(p, cfg_r))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


# ----------------------------------------------------------------- ViT
def test_vit_sharded_matches_reference():
    """dp x tp ViT training == the unsharded single-device run, exactly
    the bert contract (the encoder blocks ARE bert's)."""
    from horovod_tpu.models import vit

    rng = np.random.RandomState(0)
    images = rng.randn(8, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, 8).astype(np.int32)

    cfg_ref = vit.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = vit.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    step_ref = jax.jit(vit.make_train_step(cfg_ref, opt))
    p_ref, s_ref = params, opt.init(params)
    ref_losses = []
    for _ in range(3):
        p_ref, s_ref, l = step_ref(p_ref, s_ref, jnp.asarray(images),
                                   jnp.asarray(labels))
        ref_losses.append(float(l))
    assert ref_losses[-1] < ref_losses[0]   # it actually trains

    cfg = vit.tiny(dtype=jnp.float32)
    mesh = make_mesh({"dp": 4, "tp": 2})
    pspecs = vit.param_specs(cfg)
    p, s = params, opt.init(params)
    os_specs = spmd.infer_specs_like(s, params, pspecs)
    step = jax.jit(shard_map(
        vit.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(pspecs, os_specs, P("dp"), P("dp")),
        out_specs=(pspecs, os_specs, P()), check_vma=False))
    losses = []
    for _ in range(3):
        p, s, l = step(p, s, jnp.asarray(images), jnp.asarray(labels))
        losses.append(float(l))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)


def test_vit_config_validation():
    from horovod_tpu.models import vit

    with pytest.raises(ValueError, match="divisible"):
        vit.ViTConfig(image_size=30, patch_size=16)
    with pytest.raises(ValueError, match="sequence parallelism"):
        vit.tiny(sp_axis="sp")
    cfg = vit.tiny()
    assert cfg.n_patches == 16


# ----------------------------------------------------------------- GPT-2
def test_gpt2_sharded_matches_reference():
    """dp x tp GPT-2 training == the unsharded single-device run (the
    llama/bert/vit contract, third decoder architecture)."""
    from horovod_tpu.models import gpt2

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 256, (8, 32)).astype(np.int32)
    targets = rng.randint(0, 256, (8, 32)).astype(np.int32)

    cfg_ref = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.init_params(cfg_ref, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    step_ref = jax.jit(gpt2.make_train_step(cfg_ref, opt))
    p_ref, s_ref = params, opt.init(params)
    ref_losses = []
    for _ in range(3):
        p_ref, s_ref, l = step_ref(p_ref, s_ref, jnp.asarray(tokens),
                                   jnp.asarray(targets))
        ref_losses.append(float(l))
    assert ref_losses[-1] < ref_losses[0]

    cfg = gpt2.tiny(dtype=jnp.float32)
    mesh = make_mesh({"dp": 4, "tp": 2})
    pspecs = gpt2.param_specs(cfg)
    p, s = params, opt.init(params)
    os_specs = spmd.infer_specs_like(s, params, pspecs)
    step = jax.jit(shard_map(
        gpt2.make_train_step(cfg, opt), mesh=mesh,
        in_specs=(pspecs, os_specs, P("dp"), P("dp")),
        out_specs=(pspecs, os_specs, P()), check_vma=False))
    losses = []
    for _ in range(3):
        p, s, l = step(p, s, jnp.asarray(tokens), jnp.asarray(targets))
        losses.append(float(l))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)


def test_gpt2_generate_matches_full_forward():
    """Greedy KV-cache generation == argmax over full re-forward, token
    for token (the decode-path exactness contract, GPT-2 edition)."""
    from horovod_tpu.models import gpt2

    cfg = gpt2.tiny(dtype=jnp.float32, dp_axis=None, tp_axis=None)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 8)),
                         jnp.int32)
    out = gpt2.generate(params, prompt, 6, cfg)
    seq = prompt
    for _ in range(6):
        lg = gpt2.forward(params, seq, cfg)
        nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 8:]))

    with pytest.raises(ValueError, match="single-device"):
        gpt2.decode_step(params, gpt2.init_cache(gpt2.tiny(), 2),
                         prompt[:, 0], 0, gpt2.tiny())
